"""Static multi-process job launch (reference
``horovod/runner/gloo_run.py``: launch_gloo — rendezvous server +
per-slot process spawn with env handoff :66-103,203-292).

The launcher hosts the rendezvous/coordinator HTTP service; worker
processes get their rank/topology and the service address through
``HOROVOD_*`` env vars (exact names of the reference handoff,
gloo_run.py:66-103 ↔ gloo_context.cc:150-216).  Process 0 additionally
hosts the jax.distributed coordination service, which wires every
process's devices into one global XLA client so compiled collectives
span hosts (the TPU analogue of NCCL communicator bootstrap).
"""

import functools
import os
import secrets as _secrets
import signal
import socket
import subprocess
import sys
import time
from typing import List, Optional

from .hosts import SlotInfo, get_host_assignments, parse_hosts
from .http.http_server import (
    RendezvousServer, autotune_kwargs, free_port as _free_port, local_ip,
)


_LOCAL_HOSTNAMES = ("localhost", "127.0.0.1")

#: Env prefixes forwarded to remote workers (reference gloo_run.py
#: forwards the filtered launcher env plus the HOROVOD_* handoff).
_REMOTE_ENV_PREFIXES = ("HOROVOD_", "JAX_", "XLA_", "TPU_", "PYTHON",
                        "PATH", "LD_LIBRARY_PATH", "VIRTUAL_ENV")


@functools.lru_cache(maxsize=256)
def is_local(hostname: str) -> bool:
    """True when ``hostname`` addresses this machine (reference
    network.get_local_host_addresses check in gloo exec_command).
    Cached: the elastic driver asks per slot per round under its lock,
    and an unresolvable name costs a full resolver timeout."""
    if hostname in _LOCAL_HOSTNAMES or hostname == socket.gethostname():
        return True
    try:
        addr = socket.gethostbyname(hostname)
    except OSError:
        return False
    return addr.startswith("127.") or addr == local_ip()


def ssh_command(hostname: str, command: List[str], env: dict,
                cwd: str = None, ssh_port: int = None,
                extra_keys=()):
    """Build the ssh invocation that runs ``command`` on ``hostname``
    (reference runner/util/remote.py get_remote_command + gloo
    exec_command).  Returns ``(argv, stdin_payload)``.

    The worker env — including ``HOROVOD_SECRET_KEY`` — travels on
    **stdin** (sourced by the remote shell), never in argv, so it is
    invisible to ``ps``/``/proc/*/cmdline`` on either host.  Besides
    the standard prefixes, keys named in ``extra_keys`` (the caller's
    explicit ``env=`` dict) are always forwarded.
    """
    import shlex
    extra = set(extra_keys)
    payload = "".join(
        f"export {k}={shlex.quote(str(v))}\n"
        for k, v in sorted(env.items())
        if k.startswith(_REMOTE_ENV_PREFIXES) or k in extra)
    parts = []
    if cwd:
        parts.append(f"cd {shlex.quote(cwd)}")
    # source the env handoff from stdin, then exec the worker
    parts.append(". /dev/stdin && exec "
                 + " ".join(shlex.quote(c) for c in command))
    ssh = ["ssh", "-o", "StrictHostKeyChecking=no",
           "-o", "BatchMode=yes"]
    if ssh_port:
        ssh += ["-p", str(ssh_port)]
    return ssh + [hostname, " && ".join(parts)], payload.encode()


def host_of_rank_env(slots) -> str:
    """Comma-joined host-group index, ONE ENTRY PER PROCESS SLOT (the
    worker expands per-rank via its ranks_per_proc) — lets workers
    rebuild the full local/cross topology (the reference workers derive
    it from gloo contexts; here it rides the env contract).  Groups are
    taken from the launcher's own slot assignment (a new group starts
    at each local_rank 0), so hostfiles listing one hostname twice stay
    consistent with the per-slot HOROVOD_LOCAL_* env."""
    hosts = []
    group = -1
    for s in sorted(slots, key=lambda s: s.rank):
        if s.local_rank == 0:
            group += 1
        hosts.append(str(group))
    return ",".join(hosts)


def slot_env(slot: SlotInfo, *, rdv_addr, rdv_port, coordinator,
             secret_hex, num_procs, ranks_per_proc=1, platform=None,
             host_of_rank=None, ranks_of_proc=None):
    """Env handoff for one worker (reference gloo_run.py:66-103).

    ``ranks_of_proc``: per-process rank-thread counts for
    heterogeneous ``host:slots`` jobs; travels as
    ``HOROVOD_TPU_RANKS_OF_PROC`` so every worker derives the same
    rank->process table the engine's collectives group by."""
    env = {
        "HOROVOD_RANK": str(slot.rank),
        "HOROVOD_SIZE": str(slot.size),
        "HOROVOD_LOCAL_RANK": str(slot.local_rank),
        "HOROVOD_LOCAL_SIZE": str(slot.local_size),
        "HOROVOD_CROSS_RANK": str(slot.cross_rank),
        "HOROVOD_CROSS_SIZE": str(slot.cross_size),
        "HOROVOD_HOSTNAME": slot.hostname,
        "HOROVOD_CONTROLLER": "http",
        "HOROVOD_CPU_OPERATIONS": "xla",
        "HOROVOD_GLOO_RENDEZVOUS_ADDR": rdv_addr,
        "HOROVOD_GLOO_RENDEZVOUS_PORT": str(rdv_port),
        "HOROVOD_SECRET_KEY": secret_hex,
        "HOROVOD_TPU_PROC_INDEX": str(slot.rank),
        "HOROVOD_TPU_NUM_PROCS": str(num_procs),
        "HOROVOD_TPU_RANKS_PER_PROC": str(ranks_per_proc),
        "HOROVOD_TPU_COORDINATOR": coordinator,
    }
    if host_of_rank:
        env["HOROVOD_TPU_HOST_OF_RANK"] = host_of_rank
    if ranks_of_proc:
        env["HOROVOD_TPU_RANKS_OF_PROC"] = ",".join(
            str(n) for n in ranks_of_proc)
    if platform == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
        env["JAX_NUM_CPU_DEVICES"] = str(ranks_per_proc)
    return env


class ProcessPool:
    """Tracks spawned worker processes.  Training jobs terminate all
    on one failure (the reference's launcher kills the job when a
    worker dies, safe_shell_exec process-tree semantics); serving
    jobs pass ``stop_on_failure=False`` so a dead replica DEGRADES
    the fleet instead of collapsing it — survivors keep answering
    while liveness/elastic machinery handles the replacement
    (docs/serving.md "Failover")."""

    def __init__(self):
        self.procs: List[subprocess.Popen] = []

    def spawn(self, command, env, stdout=None, stderr=None,
              stdin_data: bytes = None):
        p = subprocess.Popen(
            command, env=env, stdout=stdout, stderr=stderr,
            stdin=subprocess.PIPE if stdin_data is not None else None)
        if stdin_data is not None:
            # deliver the payload and close so the remote shell sees
            # EOF (the env handoff is sourced from stdin)
            try:
                p.stdin.write(stdin_data)
                p.stdin.close()
            except (BrokenPipeError, OSError):
                # ssh died instantly (unreachable host / auth failure):
                # keep the dead Popen so wait() reports a clean launch
                # failure instead of an unhandled traceback here
                pass
        self.procs.append(p)
        return p

    def wait(self, timeout=None, stop_on_failure=True) -> List[int]:
        deadline = time.monotonic() + timeout if timeout else None
        codes: List[Optional[int]] = [None] * len(self.procs)
        try:
            while any(c is None for c in codes):
                for i, p in enumerate(self.procs):
                    if codes[i] is None:
                        codes[i] = p.poll()
                        if codes[i] is not None and codes[i] != 0 \
                                and stop_on_failure:
                            self.terminate()
                if deadline and time.monotonic() > deadline:
                    self.terminate()
                    raise TimeoutError("job timed out")
                time.sleep(0.05)
        except KeyboardInterrupt:
            self.terminate()
            raise
        return [c if c is not None else -1 for c in codes]

    def terminate(self):
        for p in self.procs:
            if p.poll() is None:
                try:
                    p.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        t0 = time.monotonic()
        while time.monotonic() - t0 < 5:
            if all(p.poll() is not None for p in self.procs):
                return
            time.sleep(0.05)
        for p in self.procs:
            if p.poll() is None:
                try:
                    p.kill()
                except OSError:
                    pass


def launch_procs(command: List[str], np: int, hosts: str = None,
                 ranks_per_proc: int = 1, env: dict = None,
                 platform: str = None, verbose: bool = False,
                 fusion_threshold_bytes: int = 64 * 1024 * 1024,
                 start_timeout: float = None,
                 output_filename: str = None,
                 stop_on_failure: bool = True):
    """Launch ``command`` once per slot with full env handoff; blocks
    until all workers exit.  Returns list of exit codes.

    ``output_filename``: directory for per-rank output capture —
    worker stdout/stderr land in ``<dir>/rank.<NN>/{stdout,stderr}``
    (reference ``horovodrun --output-filename``, launch.py:332; rank
    zero-padded the same way).  Remote workers' streams flow back
    through their ssh client and are captured identically.

    ``ranks_per_proc``: rank threads per worker process — an int
    (uniform, every process identical), or the string ``"host"`` for
    the reference's heterogeneous ``-H h1:4,h2:2`` layout
    (gloo_run.py:66-103 host allocation): ONE process per host entry,
    driving that entry's ``slots`` chips as rank threads.  The
    per-process rank counts travel to workers as
    ``HOROVOD_TPU_RANKS_OF_PROC`` so the engine maps rank->process by
    table instead of integer division.

    Only localhost spawning is wired (subprocess); remote hosts would
    go through ssh exactly as the reference's exec_command
    (gloo_run.py:203-229) — TPU pods normally use their own per-host
    agent instead.
    """
    hosts = hosts or f"localhost:{np}"
    host_infos = parse_hosts(hosts)
    any_remote = any(not is_local(h.hostname) for h in host_infos)
    ranks_of_proc = None
    if ranks_per_proc == "host":
        # heterogeneous: host entry i => process i with slots_i ranks,
        # filled in order until np ranks are placed
        ranks_of_proc, left = [], np
        for h in host_infos:
            if left <= 0:
                break
            take = min(h.slots, left)
            ranks_of_proc.append(take)
            left -= take
        if left > 0:
            raise ValueError(
                f"requested np={np} exceeds the "
                f"{sum(h.slots for h in host_infos)} slots in "
                f"-H {hosts}")
        num_procs = len(ranks_of_proc)
        slots = [SlotInfo(hostname=host_infos[i].hostname, rank=i,
                          local_rank=0, local_size=1, cross_rank=i,
                          cross_size=num_procs, size=num_procs)
                 for i in range(num_procs)]
    else:
        if np % ranks_per_proc != 0:
            raise ValueError(
                f"np={np} is not divisible by "
                f"ranks_per_proc={ranks_per_proc}; for unequal "
                f"hosts pass ranks_per_proc='host' (-H h1:2,h2:1 -> "
                f"one process per host driving that many chips)")
        num_procs = np // ranks_per_proc
        slots = get_host_assignments(host_infos, num_procs)

    secret_hex = _secrets.token_hex(16)
    launcher_env = dict(os.environ)
    launcher_env.update(env or {})
    server = RendezvousServer(
        secret=bytes.fromhex(secret_hex), world_size=num_procs,
        fusion_threshold_bytes=fusion_threshold_bytes,
        **autotune_kwargs(launcher_env))
    # fault-plan events with side="coord" are the LAUNCHER's to apply
    # (reject/stall chosen procs' coordinator requests server-side);
    # worker-side events ride the HOROVOD_FAULT_PLAN env handoff
    coord_faults = None
    if launcher_env.get("HOROVOD_FAULT_PLAN"):
        from ..chaos import (
            install_coordinator_rules, start_coordinator_faults,
        )
        install_coordinator_rules(server.coordinator, launcher_env)
    rdv_port = server.start()
    if launcher_env.get("HOROVOD_FAULT_PLAN"):
        # service-targeting faults (coord_kill/coord_restart) act on
        # the RUNNING server — armed after the port is bound so a
        # restart can rebind it
        coord_faults = start_coordinator_faults(server, launcher_env)
    rdv_addr = local_ip() if any_remote else "127.0.0.1"
    # jax.distributed's coordination service is hosted by PROCESS 0
    # (basics.py), so its address must point at rank 0's host — not
    # the launcher.  The port is probed free locally when rank 0 is
    # local; for a remote rank 0 it is a high random port (collision
    # surfaces as an init-timeout, same failure mode as the
    # reference's probe-then-bind race).
    rank0_host = slots[0].hostname
    coord_host = rdv_addr if is_local(rank0_host) else rank0_host
    coordinator = f"{coord_host}:{_free_port()}"

    pool = ProcessPool()
    hof = host_of_rank_env(slots)
    out_files = []
    pad = max(3, len(str(max(num_procs - 1, 0))))
    try:
        for slot in slots:
            child_env = dict(launcher_env)
            rpp = ranks_of_proc[slot.rank] if ranks_of_proc \
                else ranks_per_proc
            child_env.update(slot_env(
                slot, rdv_addr=rdv_addr, rdv_port=rdv_port,
                coordinator=coordinator, secret_hex=secret_hex,
                num_procs=num_procs, ranks_per_proc=rpp,
                platform=platform, host_of_rank=hof,
                ranks_of_proc=ranks_of_proc))
            if is_local(slot.hostname):
                cmd, payload, spawn_env = command, None, child_env
            else:
                # remote spawn over ssh: worker env rides on stdin;
                # ssh itself runs with the local env
                cmd, payload = ssh_command(
                    slot.hostname, command, child_env, cwd=os.getcwd(),
                    extra_keys=set(env or {}))
                spawn_env = dict(os.environ)
            if verbose:
                print(f"[horovodrun] rank {slot.rank} -> {cmd}",
                      file=sys.stderr)
            stdout = stderr = None
            if output_filename:
                d = os.path.join(output_filename,
                                 f"rank.{slot.rank:0{pad}d}")
                os.makedirs(d, exist_ok=True)
                stdout = open(os.path.join(d, "stdout"), "wb")
                stderr = open(os.path.join(d, "stderr"), "wb")
                out_files += [stdout, stderr]
            pool.spawn(cmd, spawn_env, stdout=stdout, stderr=stderr,
                       stdin_data=payload)
        codes = pool.wait(timeout=start_timeout,
                          stop_on_failure=stop_on_failure)
    finally:
        pool.terminate()
        if coord_faults is not None:
            coord_faults.stop()
        server.stop()
        for f in out_files:
            f.close()
    return codes

"""jsrun (LSF) launch surface (reference
``horovod/runner/js_run.py``).  Sanctioned N/A on TPU pods (SURVEY
§7.4): detection is a real ``which jsrun`` probe, the rankfile
generator works from an LSF allocation's env, and ``js_run`` fails
loudly with the supported alternative."""

import shutil

from .util.lsf import LSFUtils


def is_jsrun_installed():
    return shutil.which("jsrun") is not None


def generate_jsrun_rankfile(settings, path=None):
    """Explicit resource file for a jsrun launch (reference
    js_run.py:38), one line per host from the LSF allocation."""
    if not LSFUtils.using_lsf():
        raise RuntimeError(
            "generate_jsrun_rankfile requires an LSF allocation "
            "(LSB_JOBID not set)")
    import tempfile
    path = path or tempfile.mktemp(suffix=".rankfile")
    hosts = LSFUtils.get_compute_hosts()
    slots_total = settings.num_proc
    n_hosts = max(len(hosts), 1)
    base, rem = divmod(slots_total, n_hosts)
    with open(path, "w") as f:
        f.write("overlapping_rs: allow\ncpu_index_using: logical\n\n")
        rank = 0
        for i, host in enumerate(hosts):
            # first `rem` hosts carry one extra rank so every
            # requested rank lands in the file
            for _ in range(base + (1 if i < rem else 0)):
                f.write(f"rank: {rank}: {{ hostname: {host}; }}\n")
                rank += 1
    return path


def js_run(settings, nics, env, command, stdout=None, stderr=None):
    raise RuntimeError(
        "jsrun launch is not supported on the TPU runtime (no LSF on "
        "TPU pods). Use the default launcher — horovodrun / "
        "horovod_tpu.runner.launch — which spawns workers over "
        "ssh/subprocess with the same env contract.")

"""CLI/config-file → environment translation (reference
``horovod/runner/common/util/config_parser.py``: set_env_from_args maps
``--fusion-threshold-mb`` → ``HOROVOD_FUSION_THRESHOLD`` etc.; YAML
config file feeds the same overrides, launch.py:345-348)."""

import os

# reference config_parser.py constants
HOROVOD_FUSION_THRESHOLD = "HOROVOD_FUSION_THRESHOLD"
HOROVOD_CYCLE_TIME = "HOROVOD_CYCLE_TIME"
HOROVOD_CACHE_CAPACITY = "HOROVOD_CACHE_CAPACITY"
HOROVOD_TIMELINE = "HOROVOD_TIMELINE"
HOROVOD_TIMELINE_MARK_CYCLES = "HOROVOD_TIMELINE_MARK_CYCLES"
HOROVOD_AUTOTUNE = "HOROVOD_AUTOTUNE"
HOROVOD_AUTOTUNE_LOG = "HOROVOD_AUTOTUNE_LOG"
HOROVOD_METRICS_PORT = "HOROVOD_METRICS_PORT"
HOROVOD_METRICS_PUSH_SECONDS = "HOROVOD_METRICS_PUSH_SECONDS"
HOROVOD_TRACE_RING_EVENTS = "HOROVOD_TRACE_RING_EVENTS"
HOROVOD_TRACE_DUMP_DIR = "HOROVOD_TRACE_DUMP_DIR"
HOROVOD_TRACE_CLOCK_SYNC_SECONDS = "HOROVOD_TRACE_CLOCK_SYNC_SECONDS"
HOROVOD_FAULT_PLAN = "HOROVOD_FAULT_PLAN"
HOROVOD_FAULT_SEED = "HOROVOD_FAULT_SEED"
HOROVOD_HEARTBEAT_INTERVAL_SECONDS = "HOROVOD_HEARTBEAT_INTERVAL_SECONDS"
HOROVOD_HEARTBEAT_WINDOW_SECONDS = "HOROVOD_HEARTBEAT_WINDOW_SECONDS"
HOROVOD_COORD_JOURNAL = "HOROVOD_COORD_JOURNAL"
HOROVOD_ELASTIC_TIMEOUT = "HOROVOD_ELASTIC_TIMEOUT"
HOROVOD_COORD_OUTAGE_DEADLINE_SECONDS = \
    "HOROVOD_COORD_OUTAGE_DEADLINE_SECONDS"
HOROVOD_BYPASS_AFTER_CYCLES = "HOROVOD_BYPASS_AFTER_CYCLES"
HOROVOD_BYPASS_WAIT_SECONDS = "HOROVOD_BYPASS_WAIT_SECONDS"
HOROVOD_CONTROL_PLANE_TIER = "HOROVOD_CONTROL_PLANE_TIER"
HOROVOD_AGG_LINGER_MS = "HOROVOD_AGG_LINGER_MS"
HOROVOD_AGG_FALLBACK_DEADLINE_SECONDS = \
    "HOROVOD_AGG_FALLBACK_DEADLINE_SECONDS"
HOROVOD_STALL_CHECK_DISABLE = "HOROVOD_STALL_CHECK_DISABLE"
HOROVOD_STALL_CHECK_TIME_SECONDS = "HOROVOD_STALL_CHECK_TIME_SECONDS"
HOROVOD_STALL_SHUTDOWN_TIME_SECONDS = "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS"
HOROVOD_LOG_LEVEL = "HOROVOD_LOG_LEVEL"
# topology-aware collectives (common/env.py reads these; the boolean
# pair carries the reference's knob names, the generic one the
# flat/hierarchical/torus spelling)
HOROVOD_HIERARCHICAL_ALLREDUCE = "HOROVOD_HIERARCHICAL_ALLREDUCE"
HOROVOD_TORUS_ALLREDUCE = "HOROVOD_TORUS_ALLREDUCE"
HOROVOD_ALLREDUCE_ALGORITHM = "HOROVOD_ALLREDUCE_ALGORITHM"
# per-hop quantized wire (common/env.py reads these: DTYPE is the
# uniform shorthand, INNER/OUTER the explicit per-hop pair)
HOROVOD_WIRE_DTYPE = "HOROVOD_WIRE_DTYPE"
HOROVOD_WIRE_INNER = "HOROVOD_WIRE_INNER"
HOROVOD_WIRE_OUTER = "HOROVOD_WIRE_OUTER"
# MPMD pipeline runtime (common/env.py reads these;
# docs/parallelism.md knob catalogue)
HOROVOD_PP_STAGES = "HOROVOD_PP_STAGES"
HOROVOD_PP_MICROBATCHES = "HOROVOD_PP_MICROBATCHES"
HOROVOD_PP_SCHEDULE = "HOROVOD_PP_SCHEDULE"
HOROVOD_PP_CHUNKS = "HOROVOD_PP_CHUNKS"
HOROVOD_AUTOTUNE_CACHE = "HOROVOD_AUTOTUNE_CACHE"


def set_env_from_args(env: dict, args) -> dict:
    """Translate parsed CLI args into HOROVOD_* env entries."""
    def setb(name, val):
        if val:
            env[name] = "1"

    if getattr(args, "fusion_threshold_mb", None) is not None:
        env[HOROVOD_FUSION_THRESHOLD] = str(
            int(args.fusion_threshold_mb * 1024 * 1024))
    if getattr(args, "cycle_time_ms", None) is not None:
        env[HOROVOD_CYCLE_TIME] = str(args.cycle_time_ms)
    if getattr(args, "cache_capacity", None) is not None:
        env[HOROVOD_CACHE_CAPACITY] = str(args.cache_capacity)
    if getattr(args, "timeline_filename", None):
        env[HOROVOD_TIMELINE] = args.timeline_filename
    setb(HOROVOD_TIMELINE_MARK_CYCLES,
         getattr(args, "timeline_mark_cycles", False))
    if getattr(args, "trace_ring_events", None) is not None:
        env[HOROVOD_TRACE_RING_EVENTS] = str(args.trace_ring_events)
    if getattr(args, "trace_dump_dir", None):
        env[HOROVOD_TRACE_DUMP_DIR] = args.trace_dump_dir
    if getattr(args, "trace_clock_sync_seconds", None) is not None:
        env[HOROVOD_TRACE_CLOCK_SYNC_SECONDS] = str(
            args.trace_clock_sync_seconds)
    setb(HOROVOD_AUTOTUNE, getattr(args, "autotune", False))
    if getattr(args, "autotune_log_file", None):
        env[HOROVOD_AUTOTUNE_LOG] = args.autotune_log_file
    if getattr(args, "autotune_warmup_samples", None) is not None:
        env["HOROVOD_AUTOTUNE_WARMUP_SAMPLES"] = str(
            args.autotune_warmup_samples)
    if getattr(args, "autotune_steps_per_sample", None) is not None:
        env["HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE"] = str(
            args.autotune_steps_per_sample)
    if getattr(args, "autotune_bayes_opt_max_samples", None) is not None:
        env["HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES"] = str(
            args.autotune_bayes_opt_max_samples)
    if getattr(args, "disable_cache", False):
        # capacity 0 disables the coordinator response cache entirely
        # (reference --disable-cache -> HOROVOD_CACHE_CAPACITY=0)
        env[HOROVOD_CACHE_CAPACITY] = "0"
    if getattr(args, "metrics_port", None) is not None:
        env[HOROVOD_METRICS_PORT] = str(args.metrics_port)
    if getattr(args, "metrics_push_seconds", None) is not None:
        env[HOROVOD_METRICS_PUSH_SECONDS] = str(
            args.metrics_push_seconds)
    if getattr(args, "fault_plan", None):
        # inline the file contents so remote workers (env-over-ssh)
        # don't need the plan on their filesystem
        from ..chaos.plan import read_plan_source
        env[HOROVOD_FAULT_PLAN] = read_plan_source(args.fault_plan)
    if getattr(args, "fault_seed", None) is not None:
        env[HOROVOD_FAULT_SEED] = str(args.fault_seed)
    if getattr(args, "heartbeat_interval_seconds", None) is not None:
        env[HOROVOD_HEARTBEAT_INTERVAL_SECONDS] = str(
            args.heartbeat_interval_seconds)
    if getattr(args, "heartbeat_window_seconds", None) is not None:
        env[HOROVOD_HEARTBEAT_WINDOW_SECONDS] = str(
            args.heartbeat_window_seconds)
    if getattr(args, "elastic_timeout", None) is not None:
        # the elastic driver bounds each round's re-init with this
        # launcher-side, but workers ALSO wait on it at the init
        # barrier (common/basics.py reads HOROVOD_ELASTIC_TIMEOUT) —
        # without the handoff the flag silently didn't reach them
        # (found by hvdlint knob-flag-unhandled)
        env[HOROVOD_ELASTIC_TIMEOUT] = str(args.elastic_timeout)
    if getattr(args, "coord_journal", None):
        env[HOROVOD_COORD_JOURNAL] = args.coord_journal
    if getattr(args, "coord_outage_deadline_seconds", None) is not None:
        env[HOROVOD_COORD_OUTAGE_DEADLINE_SECONDS] = str(
            args.coord_outage_deadline_seconds)
    if getattr(args, "bypass_after_cycles", None) is not None:
        env[HOROVOD_BYPASS_AFTER_CYCLES] = str(
            args.bypass_after_cycles)
    if getattr(args, "bypass_wait_seconds", None) is not None:
        env[HOROVOD_BYPASS_WAIT_SECONDS] = str(
            args.bypass_wait_seconds)
    if getattr(args, "control_plane_tier", None):
        env[HOROVOD_CONTROL_PLANE_TIER] = args.control_plane_tier
    if getattr(args, "agg_linger_ms", None) is not None:
        env[HOROVOD_AGG_LINGER_MS] = str(args.agg_linger_ms)
    if getattr(args, "agg_fallback_deadline_seconds", None) is not None:
        env[HOROVOD_AGG_FALLBACK_DEADLINE_SECONDS] = str(
            args.agg_fallback_deadline_seconds)
    if getattr(args, "serve", False):
        env["HOROVOD_SERVING"] = "1"
        # the autoscaler is blind without the replicas' snapshot
        # stream: serving jobs push metrics even when no --metrics-port
        # is exposed (an explicit --metrics-push-seconds above wins)
        env.setdefault("HOROVOD_METRICS_PUSH_SECONDS", "2")
    if getattr(args, "serve_port", None) is not None:
        env["HOROVOD_SERVING_PORT"] = str(args.serve_port)
    if getattr(args, "serve_max_batch_size", None) is not None:
        env["HOROVOD_SERVING_MAX_BATCH_SIZE"] = str(
            args.serve_max_batch_size)
    if getattr(args, "serve_max_latency_ms", None) is not None:
        env["HOROVOD_SERVING_MAX_LATENCY_MS"] = str(
            args.serve_max_latency_ms)
    if getattr(args, "serve_batch_buckets", None):
        env["HOROVOD_SERVING_BATCH_BUCKETS"] = \
            str(args.serve_batch_buckets)
    if getattr(args, "serve_slo_p99_ms", None) is not None:
        env["HOROVOD_SERVING_SLO_P99_MS"] = str(args.serve_slo_p99_ms)
    if getattr(args, "serve_queue_high", None) is not None:
        env["HOROVOD_SERVING_QUEUE_HIGH"] = str(args.serve_queue_high)
    if getattr(args, "serve_autoscale_seconds", None) is not None:
        env["HOROVOD_SERVING_AUTOSCALE_SECONDS"] = str(
            args.serve_autoscale_seconds)
    if getattr(args, "serve_drain_seconds", None) is not None:
        env["HOROVOD_SERVING_DRAIN_SECONDS"] = str(
            args.serve_drain_seconds)
    setb(HOROVOD_STALL_CHECK_DISABLE,
         getattr(args, "no_stall_check", False))
    if getattr(args, "stall_check_warning_time_seconds", None) is not None:
        env[HOROVOD_STALL_CHECK_TIME_SECONDS] = str(
            args.stall_check_warning_time_seconds)
    if getattr(args, "stall_check_shutdown_time_seconds", None) is not None:
        env[HOROVOD_STALL_SHUTDOWN_TIME_SECONDS] = str(
            args.stall_check_shutdown_time_seconds)
    if getattr(args, "log_level", None):
        env[HOROVOD_LOG_LEVEL] = args.log_level
    setb(HOROVOD_TORUS_ALLREDUCE,
         getattr(args, "torus_allreduce", False))
    setb(HOROVOD_HIERARCHICAL_ALLREDUCE,
         getattr(args, "hierarchical_allreduce", False))
    if getattr(args, "allreduce_algorithm", None):
        env[HOROVOD_ALLREDUCE_ALGORITHM] = args.allreduce_algorithm
    if getattr(args, "wire_dtype", None):
        env[HOROVOD_WIRE_DTYPE] = args.wire_dtype
    if getattr(args, "wire_inner", None):
        env[HOROVOD_WIRE_INNER] = args.wire_inner
    if getattr(args, "wire_outer", None):
        env[HOROVOD_WIRE_OUTER] = args.wire_outer
    if getattr(args, "pipeline_stages", None) is not None:
        env[HOROVOD_PP_STAGES] = str(args.pipeline_stages)
    if getattr(args, "num_microbatches", None) is not None:
        env[HOROVOD_PP_MICROBATCHES] = str(args.num_microbatches)
    if getattr(args, "pipeline_schedule", None):
        env[HOROVOD_PP_SCHEDULE] = args.pipeline_schedule
    if getattr(args, "pipeline_chunks", None) is not None:
        env[HOROVOD_PP_CHUNKS] = str(args.pipeline_chunks)
    if getattr(args, "autotune_cache_file", None):
        env[HOROVOD_AUTOTUNE_CACHE] = args.autotune_cache_file
    return env


def parse_config_file(path, args):
    """Apply a YAML config file onto the args namespace (reference
    launch.py:345-348 + config_parser.py): CLI flags win over file
    values, file values win over defaults."""
    import yaml
    with open(path) as f:
        config = yaml.safe_load(f) or {}
    for key, value in config.items():
        attr = key.replace("-", "_")
        if hasattr(args, attr) and getattr(args, attr) in (None, False):
            setattr(args, attr, value)
    return args

"""Programmatic elastic launch shared by the platform integrations.

The Ray and Spark elastic entry points (reference ``ray/elastic.py``,
``spark/runner.py:312``) differ only in where host discovery comes
from; everything else — rendezvous server, pickled-function worker
command, ElasticDriver lifecycle — is this helper.
"""

import os
import secrets as _secrets
import sys

try:
    # closures/lambdas ship like the reference's cloudpickle-based
    # run services (runner/common/util/network.py wire format)
    import cloudpickle
except ImportError:  # pragma: no cover
    cloudpickle = None

from .elastic.driver import ElasticDriver
from .http.http_server import RendezvousServer, autotune_kwargs

FN_KEY = "/elastic/fn"

# Worker stub: fetch the pickled (fn, args, kwargs) from the
# launcher's KV store over the authenticated channel whose coordinates
# arrive in the standard env handoff.  Remote workers need only
# horovod_tpu installed — no shared filesystem (the reference ships
# the function the same way, through its run services' HMAC protocol).
_WORKER_STUB = """\
import os, pickle
from horovod_tpu.runner.http.http_client import StoreClient
client = StoreClient(os.environ["HOROVOD_GLOO_RENDEZVOUS_ADDR"],
                     int(os.environ["HOROVOD_GLOO_RENDEZVOUS_PORT"]),
                     bytes.fromhex(os.environ["HOROVOD_SECRET_KEY"]))
fn, a, kw = pickle.loads(client.get("{fn_key}", wait=30))
fn(*a, **kw)
"""


def run_elastic_fn(fn, args=(), kwargs=None, *, discovery, min_np,
                   max_np=None, env=None, reset_limit=None,
                   start_timeout=None, verbose=False, callbacks=None,
                   elastic_timeout=600):
    """Run ``fn(*args, **kwargs)`` on every elastic worker.

    ``discovery`` provides ``find_available_hosts_and_slots()``;
    workers spawn per slot (ssh for remote hosts) and membership
    changes re-form the mesh.  ``start_timeout`` bounds waiting for
    ``min_np`` slots at startup — it does NOT bound job duration (the
    reference's elastic_timeout bounds re-rendezvous, not training).

    ``callbacks`` (reference ray/elastic_v2.py:402-470 callback
    plumbing): each callable receives every round-lifecycle event as a
    dict — ``{"event": "hosts_updated"|"round_start"|"worker_start"|
    "worker_exit", ...}`` — as it happens.
    """
    if cloudpickle is None:  # pragma: no cover
        # stdlib pickle would serialize __main__ functions by
        # reference, which the worker stub (whose __main__ is the
        # stub) can never resolve — fail loudly instead
        raise RuntimeError(
            "run_elastic_fn requires cloudpickle to ship the training "
            "function to workers (pip install cloudpickle)")
    secret_hex = _secrets.token_hex(16)
    env = dict(env or {})
    # workers must import horovod_tpu even when the launcher runs it
    # from a source tree (sys.path doesn't survive exec)
    pkg_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (pkg_root, env.get("PYTHONPATH",
                                      os.environ.get("PYTHONPATH", "")))
        if p)
    at_env = dict(os.environ)
    at_env.update(env)
    server = RendezvousServer(secret=bytes.fromhex(secret_hex),
                              world_size=0, **autotune_kwargs(at_env))
    server.start()
    try:
        server.store.put(FN_KEY, cloudpickle.dumps(
            (fn, tuple(args), dict(kwargs or {})), protocol=4))
        command = [sys.executable, "-c",
                   _WORKER_STUB.format(fn_key=FN_KEY)]
        on_event = None
        if callbacks:
            cbs = list(callbacks)

            def on_event(event):
                for cb in cbs:
                    cb(event)

        driver = ElasticDriver(server, discovery, min_np=min_np,
                               max_np=max_np or min_np, command=command,
                               env=dict(env or {}),
                               reset_limit=reset_limit, verbose=verbose,
                               on_event=on_event,
                               elastic_timeout=elastic_timeout)
        driver.start(start_timeout=start_timeout)
        ok = driver.join()
    finally:
        server.stop()
    if not ok:
        raise RuntimeError("elastic job failed")

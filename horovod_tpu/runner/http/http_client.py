"""Client for the launcher's KV/coordinator service (reference
``horovod/runner/http/http_client.py``: read/write/delete KV helpers).

Connections are persistent (HTTP/1.1 keep-alive, one per thread): the
store-mode hot path issues a ready-POST and a poll per negotiation
cycle, and a fresh TCP handshake per request would dominate small-op
latency.  A dropped/stale connection transparently reconnects once.
"""

import hashlib
import hmac
import http.client
import json
import threading


class _HTTPError(Exception):
    def __init__(self, code, msg=""):
        super().__init__(f"HTTP {code} {msg}")
        self.code = code


class StoreClient:
    def __init__(self, addr: str, port: int, secret: bytes = None,
                 timeout: float = 30.0):
        self.addr = addr
        self.port = port
        self.secret = secret
        self.timeout = timeout
        self._tls = threading.local()

    # -- connection management ----------------------------------------------

    def _conn(self, timeout):
        conn = getattr(self._tls, "conn", None)
        if conn is None:
            conn = http.client.HTTPConnection(self.addr, self.port,
                                              timeout=timeout)
            self._tls.conn = conn
        elif conn.sock is not None:
            # adjust the live socket instead of reconnecting: the hot
            # path alternates ready-POST (default timeout) with
            # long-poll (larger timeout) on the same connection
            conn.sock.settimeout(timeout)
        else:
            conn.timeout = timeout
        return conn

    def _drop_conn(self):
        conn = getattr(self._tls, "conn", None)
        if conn is not None:
            conn.close()
        self._tls.conn = None
        self._tls.timeout = None

    # Stale keep-alive shapes only: a TIMEOUT is never retried (the
    # request may still be processing server-side; re-sending would
    # double-deliver and the caller's deadline is the contract), and
    # every coordinator verb is idempotent (ready/poll by design, join
    # via jid dedup) so replaying one of these failures is safe.
    _RETRYABLE = (http.client.RemoteDisconnected,
                  http.client.CannotSendRequest,
                  http.client.BadStatusLine,
                  ConnectionResetError, ConnectionRefusedError,
                  ConnectionAbortedError, BrokenPipeError)

    def _request(self, method, path, body=b"", timeout=None):
        timeout = timeout or self.timeout
        headers = dict(self._auth_headers(body))
        if body:
            headers["Content-Type"] = "application/json"
        for attempt in (0, 1):
            conn = self._conn(timeout)
            try:
                conn.request(method, path, body=body or None,
                             headers=headers)
                resp = conn.getresponse()
                data = resp.read()
                return resp.status, data
            except TimeoutError:
                self._drop_conn()
                raise
            except self._RETRYABLE:
                # stale keep-alive or server restart: reconnect once
                self._drop_conn()
                if attempt:
                    raise
        raise AssertionError("unreachable")

    def _auth_headers(self, body: bytes):
        if self.secret is None:
            return {}
        digest = hmac.new(self.secret, body, hashlib.sha256).hexdigest()
        return {"X-HVD-Auth": digest}

    # -- API -----------------------------------------------------------------

    def put(self, key: str, value: bytes):
        status, _ = self._request("PUT", key, value)
        if status != 200:
            raise _HTTPError(status, f"PUT {key}")

    def get(self, key: str, wait: float = 0.0):
        path = key + (f"?wait={wait}" if wait else "")
        status, data = self._request(
            "GET", path, timeout=max(self.timeout, wait + 5))
        if status == 404:
            return None
        if status != 200:
            raise _HTTPError(status, f"GET {key}")
        return data

    def delete(self, key: str):
        status, _ = self._request("DELETE", key)
        if status != 200:
            raise _HTTPError(status, f"DELETE {key}")

    def coord(self, verb: str, payload: dict, timeout: float = None):
        body = json.dumps(payload).encode()
        status, data = self._request("POST", f"/coord/{verb}", body,
                                     timeout=timeout)
        if status != 200:
            raise _HTTPError(status, f"coord/{verb}: "
                                     f"{data[:200].decode(errors='replace')}")
        return json.loads(data or b"{}")


# -- reference-shaped module functions (horovod/runner/http/http_client.py
#    read_data_from_kvstore :22 / put_data_into_kvstore :35).  Values are
#    base64-pickled (codec module); the signing key comes from
#    HOROVOD_SECRET_KEY when the server enforces HMAC. ----------------------

def _env_secret():
    import os
    secret_hex = os.environ.get("HOROVOD_SECRET_KEY")
    try:
        return bytes.fromhex(secret_hex) if secret_hex else None
    except ValueError:
        return None


def read_data_from_kvstore(addr, port, scope, key):
    from ..common.util import codec
    try:
        client = StoreClient(addr, port, _env_secret())
        raw = client.get(f"/{scope}/{key}")
    except Exception as e:  # noqa: BLE001 — reference raises RuntimeError
        raise RuntimeError("Read data from KVStore server failed.", e)
    if raw is None:
        raise RuntimeError(
            f"Read data from KVStore server failed: no value at "
            f"/{scope}/{key}")
    return codec.loads_base64(raw)


def put_data_into_kvstore(addr, port, scope, key, value):
    from ..common.util import codec
    try:
        client = StoreClient(addr, port, _env_secret())
        client.put(f"/{scope}/{key}",
                   codec.dumps_base64(value, to_ascii=False))
    except Exception as e:  # noqa: BLE001 — reference raises RuntimeError
        raise RuntimeError("Put data input KVStore server failed.", e)

"""Client for the launcher's KV/coordinator service (reference
``horovod/runner/http/http_client.py``: read/write/delete KV helpers).
"""

import hashlib
import hmac
import json
import urllib.error
import urllib.request


class StoreClient:
    def __init__(self, addr: str, port: int, secret: bytes = None,
                 timeout: float = 30.0):
        self.base = f"http://{addr}:{port}"
        self.secret = secret
        self.timeout = timeout

    def _auth_headers(self, body: bytes):
        if self.secret is None:
            return {}
        digest = hmac.new(self.secret, body, hashlib.sha256).hexdigest()
        return {"X-HVD-Auth": digest}

    def put(self, key: str, value: bytes):
        req = urllib.request.Request(
            self.base + key, data=value, method="PUT",
            headers=self._auth_headers(value))
        with urllib.request.urlopen(req, timeout=self.timeout):
            pass

    def get(self, key: str, wait: float = 0.0):
        url = self.base + key
        if wait:
            url += f"?wait={wait}"
        req = urllib.request.Request(url, method="GET",
                                     headers=self._auth_headers(b""))
        try:
            with urllib.request.urlopen(
                    req, timeout=max(self.timeout, wait + 5)) as r:
                return r.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise

    def delete(self, key: str):
        req = urllib.request.Request(self.base + key, method="DELETE",
                                     headers=self._auth_headers(b""))
        with urllib.request.urlopen(req, timeout=self.timeout):
            pass

    def coord(self, verb: str, payload: dict, timeout: float = None):
        body = json.dumps(payload).encode()
        req = urllib.request.Request(
            self.base + f"/coord/{verb}", data=body, method="POST",
            headers={**self._auth_headers(body),
                     "Content-Type": "application/json"})
        with urllib.request.urlopen(
                req, timeout=timeout or self.timeout) as r:
            return json.loads(r.read() or b"{}")

"""Client for the launcher's KV/coordinator service (reference
``horovod/runner/http/http_client.py``: read/write/delete KV helpers).

Connections are persistent (HTTP/1.1 keep-alive, one per thread): the
store-mode hot path issues a ready-POST and a poll per negotiation
cycle, and a fresh TCP handshake per request would dominate small-op
latency.

Transient fabric failures — dropped keep-alives, a coordinator
restarting, a 5xx burst — retry with bounded exponential backoff +
jitter (``HOROVOD_FABRIC_RETRY_ATTEMPTS`` /
``HOROVOD_FABRIC_RETRY_DEADLINE_SECONDS``); every retry is counted in
``horovod_fabric_retries_total{verb}``.  A ``TimeoutError`` is retried
only on the verbs whose server-side handling is deduplicated by a
client-supplied id (ready/join via rid/jid, heartbeat naturally
idempotent) — replaying anything else could double-deliver.  The
chaos subsystem's fault middleware (chaos/inject.py) hooks in right
before the wire, so injected faults exercise exactly this machinery.
"""

import hashlib
import hmac
import http.client
import json
import random
import threading
import time

from ...common import env as env_mod


class _HTTPError(Exception):
    def __init__(self, code, msg=""):
        super().__init__(f"HTTP {code} {msg}")
        self.code = code


class _DroppedRequest(ConnectionError):
    """Chaos middleware swallowed the request before the wire — the
    client-visible symptom of a lost packet/connection."""


#: Re-exported from the shared contract module (one definition for
#: client, server and checkers — see contract.py for the invariant);
#: kept as a module attribute because tests and callers import it
#: from here historically.
from .contract import (  # noqa: F401 — re-export
    REPLAY_SAFE_VERBS, REPLAY_SAFE_KV_VERBS)


def _count_retry(verb):
    """One retry attempt on the fabric, into the process-current
    registry (telemetry.count_fabric_retry owns the family)."""
    try:
        from ...telemetry import count_fabric_retry
        count_fabric_retry(verb)
    except Exception:  # noqa: BLE001 — accounting must never fail a retry
        pass


class StoreClient:
    def __init__(self, addr: str, port: int, secret: bytes = None,
                 timeout: float = 30.0):
        self.addr = addr
        self.port = port
        self.secret = secret
        self.timeout = timeout
        self._tls = threading.local()
        #: chaos fault middleware (chaos/inject.py FaultInjector); its
        #: ``before_request(method, path)`` may drop, delay, duplicate
        #: or synthesize an HTTP error before the wire
        self.middleware = None
        # retry budget: attempts AND a wall deadline bound every
        # request's total retry time (env-tunable; docs/fault_tolerance)
        self.retry_attempts = env_mod.get_int(
            env_mod.HOROVOD_FABRIC_RETRY_ATTEMPTS, 8)
        self.retry_deadline = env_mod.get_float(
            env_mod.HOROVOD_FABRIC_RETRY_DEADLINE_SECONDS, 30.0)
        # coordinator-outage budget (docs/fault_tolerance.md
        # "Coordinator crash survival"): CONNECTION-SHAPE failures —
        # the server is gone, the request never completed server-side,
        # so replay is safe on every verb — and safe-timeout replays
        # keep retrying up to this wall deadline instead of the per-
        # request one, spanning a rendezvous-service restart.  5xx
        # keeps the tight budget: a server answering sick is not an
        # outage.
        self.outage_deadline = env_mod.get_float(
            env_mod.HOROVOD_COORD_OUTAGE_DEADLINE_SECONDS, 120.0)
        self._retry_base = 0.05     # first backoff step (seconds)
        self._retry_cap = 2.0       # per-step ceiling

    # -- connection management ----------------------------------------------

    def _conn(self, timeout):
        conn = getattr(self._tls, "conn", None)
        if conn is None:
            conn = http.client.HTTPConnection(self.addr, self.port,
                                              timeout=timeout)
            self._tls.conn = conn
        elif conn.sock is not None:
            # adjust the live socket instead of reconnecting: the hot
            # path alternates ready-POST (default timeout) with
            # long-poll (larger timeout) on the same connection
            conn.sock.settimeout(timeout)
        else:
            conn.timeout = timeout
        return conn

    def _drop_conn(self):
        conn = getattr(self._tls, "conn", None)
        if conn is not None:
            conn.close()
        self._tls.conn = None
        self._tls.timeout = None

    # Connection-shape failures: safe to replay on every verb (the
    # request never completed server-side, or the verb is idempotent /
    # id-deduplicated).  A TIMEOUT is retried only for
    # REPLAY_SAFE_VERBS — the request may still be processing
    # server-side, so re-sending anything else could double-deliver.
    _RETRYABLE = (http.client.RemoteDisconnected,
                  http.client.CannotSendRequest,
                  http.client.BadStatusLine,
                  ConnectionResetError, ConnectionRefusedError,
                  ConnectionAbortedError, BrokenPipeError,
                  _DroppedRequest)

    def _backoff(self, attempt):
        """Exponential backoff with jitter, capped per step."""
        step = min(self._retry_cap, self._retry_base * (2 ** attempt))
        time.sleep(step * (0.5 + random.random()))

    # hvdlint: blocking
    def _send_once(self, method, path, body, headers, timeout,
                   duplicate=False):
        conn = self._conn(timeout)
        conn.request(method, path, body=body or None, headers=headers)
        resp = conn.getresponse()
        data = resp.read()
        if duplicate:
            # chaos 'duplicate': re-send the identical request on the
            # same connection (a replayed POST after a dropped
            # keep-alive) and serve the replay's response — the
            # server's rid/jid dedup is what keeps this harmless
            conn.request(method, path, body=body or None,
                         headers=headers)
            resp = conn.getresponse()
            data = resp.read()
        return resp.status, data

    def _request(self, method, path, body=b"", timeout=None,
                 verb=None, retry_timeout=False, budget=None):
        """One logical request with bounded retries.  ``verb`` labels
        the retry counter; ``retry_timeout`` opts the verb into
        TimeoutError replays (REPLAY_SAFE_VERBS only).  ``budget`` is
        an explicit ``(attempts, seconds)`` override that ALSO caps
        the outage deadline — teardown-path callers (final metrics
        push, heartbeat bye) use it so a dead coordinator can never
        wedge a clean worker exit."""
        timeout = timeout or self.timeout
        verb = verb or method.lower()
        headers = dict(self._auth_headers(body))
        if body:
            headers["Content-Type"] = "application/json"
        attempts, deadline_s = budget or (self.retry_attempts,
                                          self.retry_deadline)
        start = time.monotonic()
        deadline = start + deadline_s
        # connection-shape failures (and safe-timeout replays) span a
        # coordinator outage: the server is down/restarting, not
        # answering sick, so keep retrying up to the outage deadline —
        # unless the caller pinned an explicit budget
        outage_deadline = start + (deadline_s if budget is not None
                                   else max(deadline_s,
                                            self.outage_deadline))
        attempt = 0
        while True:
            exhausted = (attempt + 1 >= max(attempts, 1)
                         or time.monotonic() > deadline)
            try:
                action = None
                mw = self.middleware
                if mw is not None:
                    action = mw.before_request(method, path)
                if action is not None and action[0] == "drop":
                    raise _DroppedRequest(
                        f"chaos: dropped {method} {path}")
                if action is not None and action[0] == "error":
                    status, data = action[1], b"chaos: injected error"
                else:
                    if action is not None and action[0] == "delay":
                        time.sleep(action[1])
                    status, data = self._send_once(
                        method, path, body, headers, timeout,
                        duplicate=(action is not None
                                   and action[0] == "duplicate"))
                if status >= 500 and not exhausted:
                    # transient server failure (restart, overload,
                    # injected burst): the response was fully read, so
                    # the keep-alive connection stays usable
                    _count_retry(verb)
                    self._backoff(attempt)
                    attempt += 1
                    continue
                return status, data
            except TimeoutError:
                self._drop_conn()
                if not retry_timeout \
                        or time.monotonic() > outage_deadline:
                    raise
            except self._RETRYABLE:
                # stale keep-alive, server restart, or injected drop:
                # reconnect and replay under the outage deadline (the
                # request never completed server-side, so replay is
                # safe on every verb)
                self._drop_conn()
                if attempt == 0:
                    # the first connection-shape failure is routinely
                    # just an idle-closed keep-alive: reconnect and
                    # replay IMMEDIATELY, even past the deadline (a
                    # long-poll GET can outlive it legitimately) —
                    # the pre-backoff code's unconditional single
                    # reconnect, preserved.  Waiting is for servers
                    # that answered sick, not for a dropped socket.
                    _count_retry(verb)
                    attempt = 1
                    continue
                if time.monotonic() > outage_deadline:
                    raise
            _count_retry(verb)
            self._backoff(attempt)
            attempt += 1

    def _auth_headers(self, body: bytes):
        if self.secret is None:
            return {}
        digest = hmac.new(self.secret, body, hashlib.sha256).hexdigest()
        return {"X-HVD-Auth": digest}

    # -- API -----------------------------------------------------------------

    def put(self, key: str, value: bytes, budget=None):
        # KV puts are last-writer-wins: replaying a timed-out put is
        # safe, so the full retry surface applies.  ``budget`` caps
        # the retries for teardown-path callers (final metrics push).
        status, _ = self._request("PUT", key, value, verb="kv_put",
                                  retry_timeout=True, budget=budget)
        if status != 200:
            raise _HTTPError(status, f"PUT {key}")

    def get(self, key: str, wait: float = 0.0):
        path = key + (f"?wait={wait}" if wait else "")
        status, data = self._request(
            "GET", path, timeout=max(self.timeout, wait + 5),
            verb="kv_get", retry_timeout=True)
        if status == 404:
            return None
        if status != 200:
            raise _HTTPError(status, f"GET {key}")
        return data

    def delete(self, key: str):
        status, _ = self._request("DELETE", key, verb="kv_delete")
        if status != 200:
            raise _HTTPError(status, f"DELETE {key}")

    def coord(self, verb: str, payload: dict, timeout: float = None,
              budget=None):
        body = json.dumps(payload).encode()
        status, data = self._request(
            "POST", f"/coord/{verb}", body, timeout=timeout, verb=verb,
            # ready/join are rid/jid-deduplicated server-side and
            # heartbeat is naturally idempotent: a slow reply on those
            # POSTs is retried instead of killing the job
            retry_timeout=verb in REPLAY_SAFE_VERBS, budget=budget)
        if status != 200:
            raise _HTTPError(status, f"coord/{verb}: "
                                     f"{data[:200].decode(errors='replace')}")
        return json.loads(data or b"{}")


class TieredStoreClient:
    """Two-route fabric client for the per-host aggregator tier
    (docs/fault_tolerance.md "Per-host aggregator tier"): the PRIMARY
    route is the host's aggregator, the FALLBACK the coordinator
    itself.  An aggregator that stops answering — connection refused,
    timeout, or a 5xx it returns when IT cannot reach upstream —
    triggers a one-way switch to direct mode for this worker:
    degradation, never deadlock.  The aggregator client's retry
    budget is pinned tight (``HOROVOD_AGG_FALLBACK_DEADLINE_SECONDS``)
    so the fallback fires in seconds, while the direct client keeps
    the coordinator-outage-spanning budget.

    ``maybe_probe()`` (clocked by the engine's heartbeat loop)
    re-pings a fallen-back aggregator occasionally and re-attaches
    when it answers — an ``agg_restart`` heals back to the batched
    path without a round reset.  Route changes invoke
    ``on_route_change(reason)`` so the StoreController can run its
    resync handshake: falling back (or re-attaching) mid-stream is
    recovered exactly like an epoch bump — resync, drain, re-report."""

    #: seconds between re-attach probes after a fallback
    PROBE_SECS = 10.0

    def __init__(self, agg_client: StoreClient,
                 direct_client: StoreClient):
        self.agg = agg_client
        self.direct = direct_client
        self.via_agg = True
        self.on_route_change = None
        self._route_lock = threading.Lock()
        self._fell_back_at = None

    # chaos middleware rides BOTH routes (one injector, one request
    # counter — the deterministic trigger stream must not depend on
    # which route a request took)
    @property
    def middleware(self):
        return self.direct.middleware

    @middleware.setter
    def middleware(self, mw):
        self.agg.middleware = mw
        self.direct.middleware = mw

    @staticmethod
    def _falls_back(exc):
        if isinstance(exc, _HTTPError):
            return exc.code >= 500
        return isinstance(exc, (OSError, TimeoutError,
                                http.client.HTTPException))

    def _call(self, name, args, kwargs):
        primary = self.agg if self.via_agg else self.direct
        try:
            return getattr(primary, name)(*args, **kwargs)
        except Exception as exc:  # noqa: BLE001 — classified below
            if primary is not self.agg or not self._falls_back(exc):
                raise
            self._fall_back(exc)
            return getattr(self.direct, name)(*args, **kwargs)

    def coord(self, verb, payload, timeout=None, budget=None):
        return self._call("coord", (verb, payload),
                          {"timeout": timeout, "budget": budget})

    def put(self, key, value, budget=None):
        return self._call("put", (key, value), {"budget": budget})

    def get(self, key, wait=0.0):
        return self._call("get", (key,), {"wait": wait})

    def delete(self, key):
        return self._call("delete", (key,), {})

    def _fall_back(self, exc):
        with self._route_lock:
            if not self.via_agg:
                return
            self.via_agg = False
            self._fell_back_at = time.monotonic()
        import logging
        logging.getLogger("horovod_tpu").warning(
            "aggregator route failed (%s: %s); falling back to "
            "direct coordinator mode", type(exc).__name__, exc)
        try:
            from ...telemetry import count_agg_fallback
            count_agg_fallback("direct")
        except Exception:  # noqa: BLE001 — accounting only
            pass
        self._notify("direct")

    def maybe_probe(self):
        """Probe a fallen-back aggregator (bounded, spaced) and
        re-attach when it answers.  Returns True on a re-attach."""
        with self._route_lock:
            if self.via_agg or self._fell_back_at is None or \
                    time.monotonic() - self._fell_back_at < \
                    self.PROBE_SECS:
                return False
            self._fell_back_at = time.monotonic()   # space the probes
        try:
            self.agg.coord("clock", {}, timeout=2.0, budget=(1, 2.5))
        except Exception:  # noqa: BLE001 — still down; stay direct
            return False
        with self._route_lock:
            self.via_agg = True
        import logging
        logging.getLogger("horovod_tpu").warning(
            "aggregator answering again; re-attaching to the "
            "batched control-plane route")
        try:
            from ...telemetry import count_agg_fallback
            count_agg_fallback("reattach")
        except Exception:  # noqa: BLE001 — accounting only
            pass
        self._notify("reattach")
        return True

    def _notify(self, reason):
        cb = self.on_route_change
        if cb is None:
            return
        try:
            cb(reason)
        except Exception:  # noqa: BLE001 — the route change already
            # happened; the controller's next fenced verb recovers
            pass


# -- reference-shaped module functions (horovod/runner/http/http_client.py
#    read_data_from_kvstore :22 / put_data_into_kvstore :35).  Values are
#    base64-pickled (codec module); the signing key comes from
#    HOROVOD_SECRET_KEY when the server enforces HMAC. ----------------------

def _env_secret():
    secret_hex = env_mod.get_str(env_mod.HOROVOD_SECRET_KEY)
    try:
        return bytes.fromhex(secret_hex) if secret_hex else None
    except ValueError:
        return None


def read_data_from_kvstore(addr, port, scope, key):
    from ..common.util import codec
    try:
        client = StoreClient(addr, port, _env_secret())
        raw = client.get(f"/{scope}/{key}")
    except Exception as e:  # noqa: BLE001 — reference raises RuntimeError
        raise RuntimeError("Read data from KVStore server failed.", e)
    if raw is None:
        raise RuntimeError(
            f"Read data from KVStore server failed: no value at "
            f"/{scope}/{key}")
    return codec.loads_base64(raw)


def put_data_into_kvstore(addr, port, scope, key, value):
    from ..common.util import codec
    try:
        client = StoreClient(addr, port, _env_secret())
        client.put(f"/{scope}/{key}",
                   codec.dumps_base64(value, to_ascii=False))
    except Exception as e:  # noqa: BLE001 — reference raises RuntimeError
        raise RuntimeError("Put data input KVStore server failed.", e)

"""Control-plane wire contract: THE definitions shared by the client
(http_client.py), the server (http_server.py), the worker-side
controller (core/store_controller.py) and the bypass state machine
(core/bypass.py).

Every constant here encodes a cross-component invariant that used to
live as a copy on each side of the wire — one drifting copy is a
silent replay-unsafety or cache-divergence bug, so the copies were
hoisted into this module and ``tools/hvdlint`` (checker ``replay``)
mechanically rejects any re-definition elsewhere.  The runtime
contract test (tests/test_chaos.py ``test_replay_safe_verbs_contract``)
validates the SAME single definition dynamically.
"""

#: Verbs whose POSTs the coordinator deduplicates on a client id
#: (rid/jid), on idempotent per-slot state (resync session
#: registration, bypass_ready votes), or that are naturally idempotent
#: (heartbeat) — the only coordinator verbs where retrying a TIMEOUT
#: is safe (the original may still have landed).  Across a coordinator
#: restart the epoch fence rejects any blind replay BEFORE its verb
#: runs, so the contract holds outage-spanning too.
#:
#: The aggregator tier (runner/http/aggregator.py) batches worker
#: verbs into the ``agg_*`` upstream verbs; each inherits the dedup
#: of the per-proc reports it carries (``agg_ready``: per-proc rid,
#: ``agg_heartbeat``: naturally idempotent beats, ``agg_resync``:
#: idempotent per-(agg, sid) registration), so the SAME contract holds
#: across all three tiers — worker↔aggregator, aggregator↔coordinator,
#: and the direct worker↔coordinator fallback.
REPLAY_SAFE_VERBS = ("ready", "join", "heartbeat", "resync",
                     "bypass_ready", "agg_ready", "agg_heartbeat",
                     "agg_resync")

#: KV-path pseudo-verbs that are replay-safe by DATA MODEL rather than
#: by dedup: puts are last-writer-wins and gets are reads, so a
#: timed-out request can be blindly re-sent.  (kv_delete is excluded:
#: delete-then-recreate races a replayed delete.)
REPLAY_SAFE_KV_VERBS = ("kv_put", "kv_get")

#: The server-side dedup / idempotency structure each replay-safe verb
#: handler must route through (attribute names on the Coordinator).
#: hvdlint checker ``replay`` statically verifies every ``_on_<verb>``
#: handler touches its declared structure; the chaos contract test
#: proves single-apply under identical replay at runtime.
REPLAY_DEDUP_ATTRS = {
    "ready": ("_ready_seen",),          # rid high-water + cached reply
    "join": ("_join_seen",),            # per-(ps, proc) jid sets
    "heartbeat": ("_beats",),           # last-beat map: re-beat = update
    "resync": ("_proc_sid",),           # session re-registration
    "bypass_ready": ("_bypass_votes",),  # per-proc vote slot
    # aggregator-tier verbs: the batch envelope dedups through the
    # per-proc structures of the reports it carries
    "agg_ready": ("_ready_seen",),      # per-proc rid high-waters
    "agg_heartbeat": ("_beats",),       # beats are idempotent updates
    "agg_resync": ("_agg_sid",),        # per-agg session registration
}

#: Verbs that bypass the coordinator epoch fence: ``clock`` is a
#: lock-free, state-free NTP ping that must answer with minimal
#: jitter; ``resync`` IS the fence's recovery handshake (it cannot be
#: fenced by the epoch it exists to re-learn), and ``agg_resync`` is
#: the same handshake for the aggregator tier — a restarted
#: aggregator re-registers through it to learn the epochs it will
#: fence everything else with.  Every other verb must be rejected on
#: an epoch mismatch BEFORE its handler runs — hvdlint checker
#: ``replay`` verifies the dispatch order.
EPOCH_EXEMPT_VERBS = ("clock", "resync", "agg_resync")

#: Long-poll stream verbs: fenced like any other verb, NEVER
#: timeout-replayed (a long poll legitimately outlives the request
#: timeout), and idempotent by cursor — re-polling a cursor re-serves
#: the same log suffix.  Every ``_on_<verb>`` handler on a
#: coordinator-shaped class must be classified in exactly one of
#: REPLAY_SAFE_VERBS / EPOCH_EXEMPT_VERBS / STREAM_VERBS — hvdlint
#: checker ``replay`` (``replay-unclassified-verb``) rejects a new
#: verb that skips the classification, on all three tiers.
STREAM_VERBS = ("poll", "agg_poll")

#: Negotiation-meta types eligible for the coordinator response cache
#: AND the steady-state bypass (reference response_cache.cc
#: eligibility): metas identical across steps.  Shared by the server's
#: cache admission, the worker controller's hit path and the bypass
#: eligibility filter — three sites that previously each held a copy.
CACHEABLE_TYPES = ("ALLREDUCE", "ADASUM")

"""Control-plane wire contract: THE definitions shared by the client
(http_client.py), the server (http_server.py), the worker-side
controller (core/store_controller.py) and the bypass state machine
(core/bypass.py).

Every constant here encodes a cross-component invariant that used to
live as a copy on each side of the wire — one drifting copy is a
silent replay-unsafety or cache-divergence bug, so the copies were
hoisted into this module and ``tools/hvdlint`` (checker ``replay``)
mechanically rejects any re-definition elsewhere.  The runtime
contract test (tests/test_chaos.py ``test_replay_safe_verbs_contract``)
validates the SAME single definition dynamically.
"""

#: Verbs whose POSTs the coordinator deduplicates on a client id
#: (rid/jid), on idempotent per-slot state (resync session
#: registration, bypass_ready votes), or that are naturally idempotent
#: (heartbeat) — the only coordinator verbs where retrying a TIMEOUT
#: is safe (the original may still have landed).  Across a coordinator
#: restart the epoch fence rejects any blind replay BEFORE its verb
#: runs, so the contract holds outage-spanning too.
REPLAY_SAFE_VERBS = ("ready", "join", "heartbeat", "resync",
                     "bypass_ready")

#: KV-path pseudo-verbs that are replay-safe by DATA MODEL rather than
#: by dedup: puts are last-writer-wins and gets are reads, so a
#: timed-out request can be blindly re-sent.  (kv_delete is excluded:
#: delete-then-recreate races a replayed delete.)
REPLAY_SAFE_KV_VERBS = ("kv_put", "kv_get")

#: The server-side dedup / idempotency structure each replay-safe verb
#: handler must route through (attribute names on the Coordinator).
#: hvdlint checker ``replay`` statically verifies every ``_on_<verb>``
#: handler touches its declared structure; the chaos contract test
#: proves single-apply under identical replay at runtime.
REPLAY_DEDUP_ATTRS = {
    "ready": ("_ready_seen",),          # rid high-water + cached reply
    "join": ("_join_seen",),            # per-(ps, proc) jid sets
    "heartbeat": ("_beats",),           # last-beat map: re-beat = update
    "resync": ("_proc_sid",),           # session re-registration
    "bypass_ready": ("_bypass_votes",),  # per-proc vote slot
}

#: Verbs that bypass the coordinator epoch fence: ``clock`` is a
#: lock-free, state-free NTP ping that must answer with minimal
#: jitter; ``resync`` IS the fence's recovery handshake (it cannot be
#: fenced by the epoch it exists to re-learn).  Every other verb must
#: be rejected on an epoch mismatch BEFORE its handler runs —
#: hvdlint checker ``replay`` verifies the dispatch order.
EPOCH_EXEMPT_VERBS = ("clock", "resync")

#: Negotiation-meta types eligible for the coordinator response cache
#: AND the steady-state bypass (reference response_cache.cc
#: eligibility): metas identical across steps.  Shared by the server's
#: cache admission, the worker controller's hit path and the bypass
#: eligibility filter — three sites that previously each held a copy.
CACHEABLE_TYPES = ("ALLREDUCE", "ADASUM")

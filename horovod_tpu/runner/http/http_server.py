"""HTTP KV store + rendezvous + coordinator service.

Reference: ``horovod/runner/http/http_server.py`` (KVStoreServer :35,
RendezvousServer :192) — the launcher-hosted store Gloo workers
rendezvous against, doubled as the elastic control plane.

Here it additionally hosts the **coordinator** role the reference runs
on rank 0's background thread (``controller.cc:74-474``): worker
processes POST locally-ready tensor lists; the server counts readiness
across processes, validates cross-process consistency, fuses ready
allreduces under the fusion threshold, and appends fused responses to
an ordered log every worker polls.  Ordering the log **is** the
collective schedule: every process issues the same compiled XLA
programs in the same order, which is exactly the invariant SPMD
execution needs.

Requests are HMAC-signed (reference runner/common/util/network.py:56:
every message carries an HMAC digest of the payload keyed by the
job secret).
"""

import hashlib
import hmac
import json
import logging
import os
import threading
import time
import socket
import socketserver
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler

from . import journal as journal_mod

logger = logging.getLogger("horovod_tpu")

OK = 200
BAD_REQUEST = 400
FORBIDDEN = 403
NOT_FOUND = 404

# Ops whose negotiation meta is identical across processes and steps
# (fixed shape): eligible for the response-cache fast path.  Allgather
# metas carry per-proc first dims and alltoall metas carry splits, so
# those are never cached (client sends full metas; server skips the
# LRU so uncacheable entries can't evict hot allreduce templates).
# ONE definition, shared with the worker controller and the bypass
# eligibility filter (contract.py); re-exported for back-compat.
from .contract import (  # noqa: F401 — re-export
    CACHEABLE_TYPES, EPOCH_EXEMPT_VERBS, REPLAY_DEDUP_ATTRS)


def autotune_kwargs(env=None):
    """RendezvousServer coordinator settings from a ``HOROVOD_*`` env
    mapping (default: os.environ) — shared by every launcher that
    hosts a coordinator (static, elastic, spark, ray).  Besides the
    autotune knobs this carries the stall-inspector warning time, so
    the coordinator's global stall attribution fires on the same
    clock as the workers' local inspectors."""
    env = os.environ if env is None else env
    on = str(env.get("HOROVOD_AUTOTUNE", "")).strip().lower() \
        in ("1", "true", "yes", "on")
    kwargs = {
        "autotune": on,
        "autotune_log": env.get("HOROVOD_AUTOTUNE_LOG") or None,
        "cycle_time_ms": float(env.get("HOROVOD_CYCLE_TIME") or 1.0),
    }
    cap = env.get("HOROVOD_CACHE_CAPACITY")
    if cap is not None and str(cap).strip() != "":
        # 0 = response cache disabled (--disable-cache)
        kwargs["cache_capacity"] = int(cap)
    disabled = str(env.get("HOROVOD_STALL_CHECK_DISABLE", "")) \
        .strip().lower() in ("1", "true", "yes", "on")
    if disabled:
        kwargs["stall_warning_secs"] = 0.0
    else:
        try:
            kwargs["stall_warning_secs"] = float(
                env.get("HOROVOD_STALL_CHECK_TIME_SECONDS") or 60.0)
        except ValueError:
            kwargs["stall_warning_secs"] = 60.0
    # worker liveness (docs/fault_tolerance.md): the coordinator
    # declares a proc dead once its heartbeats stop for the window
    # (default 1.5x the interval — detection inside 2x the interval);
    # interval 0 disables.  Shared with workers through the same env.
    try:
        kwargs["heartbeat_secs"] = float(
            env.get("HOROVOD_HEARTBEAT_INTERVAL_SECONDS") or 5.0)
    except ValueError:
        kwargs["heartbeat_secs"] = 5.0
    try:
        kwargs["heartbeat_window"] = float(
            env.get("HOROVOD_HEARTBEAT_WINDOW_SECONDS") or 0.0)
    except ValueError:
        kwargs["heartbeat_window"] = 0.0
    # coordinator crash survival (docs/fault_tolerance.md): journal
    # control-plane transitions to this path so a restarted rendezvous
    # service replays them (epoch-fenced).  REPLAY=1 opts a FRESH
    # server into replaying an existing file (a restarted launcher);
    # by default a new job truncates a stale journal on its path.
    journal = env.get("HOROVOD_COORD_JOURNAL")
    if journal:
        kwargs["journal_path"] = journal
        kwargs["journal_replay"] = str(
            env.get("HOROVOD_COORD_JOURNAL_REPLAY", "")).strip().lower() \
            in ("1", "true", "yes", "on")
    return kwargs


def _digest(secret: bytes, payload: bytes) -> str:
    return hmac.new(secret, payload, hashlib.sha256).hexdigest()


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # silence
        pass

    @property
    def store(self):
        return self.server.store

    def _verify(self, body: bytes) -> bool:
        secret = self.server.secret
        if secret is None:
            return True
        given = self.headers.get("X-HVD-Auth", "")
        return hmac.compare_digest(given, _digest(secret, body))

    def _reply(self, code, payload=b"", content_type="application/octet-stream"):
        self.send_response(code)
        self.send_header("Content-Length", str(len(payload)))
        self.send_header("Content-Type", content_type)
        self.end_headers()
        if payload:
            self.wfile.write(payload)

    def do_PUT(self):
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        if not self._verify(body):
            return self._reply(FORBIDDEN)
        self.store.put(self.path, body)
        self._reply(OK)

    def do_GET(self):
        path, _, query = self.path.partition("?")
        if path in ("/metrics", "/metrics.json"):
            # job-wide exposition: merge the snapshots workers push
            # over the KV fabric.  Deliberately UNAUTHENTICATED —
            # Prometheus scrapers cannot HMAC-sign, and the payload is
            # read-only operational metadata (docs/observability.md).
            return self._serve_job_metrics(path)
        if path == "/timeline":
            # job-wide merged trace: ask every worker to dump its
            # flight-recorder ring, then clock-align + merge the
            # buffers into one Perfetto-loadable JSON.  Unauthenticated
            # for the same reason as /metrics (docs/timeline.md).
            return self._serve_job_timeline(query)
        if not self._verify(b""):
            return self._reply(FORBIDDEN)
        params = dict(p.split("=", 1) for p in query.split("&") if "=" in p)
        wait = float(params.get("wait", 0))
        value = self.store.get(path, timeout=wait)
        if value is None:
            return self._reply(NOT_FOUND)
        self._reply(OK, value)

    def _serve_job_metrics(self, path):
        """One scrape covers the whole job: counters sum across
        workers, gauges expose per-worker max/min (an ``agg`` label),
        histograms merge bucket-wise (telemetry.merge_snapshots).
        Only pushed worker snapshots participate — the launcher
        process's own registry may belong to an unrelated embedding
        application (spark/ray drivers)."""
        from ...telemetry import (
            CONTENT_TYPE_LATEST, TELEMETRY_KV_PREFIX, merge_snapshots,
            render_json, render_prometheus,
        )

        coord = self.server.coordinator
        snaps = []
        for key, raw in sorted(
                self.store.scope(TELEMETRY_KV_PREFIX).items()):
            try:
                payload = json.loads(raw)
                # stale pushes must not haunt the aggregate: a worker
                # that left in an elastic downsize (proc id beyond the
                # current world) or pushed during a previous round
                # keeps its final snapshot in the KV store forever
                proc = payload.get("proc")
                rnd = payload.get("round")
                if rnd is not None and rnd != coord.round_id:
                    continue
                if proc is not None and 0 < coord.world_size <= proc:
                    continue
                snaps.append(payload.get("families", {}))
            except (ValueError, AttributeError):
                continue    # half-written/foreign value: skip, not 500
        # coordinator-derived liveness + server-side chaos accounting
        # join the aggregate (a dead worker can't push its own 0)
        snaps.append(coord.liveness_snapshot())
        merged = merge_snapshots(snaps)
        if path == "/metrics.json":
            self._reply(OK, render_json(merged).encode(),
                        "application/json")
        else:
            self._reply(OK, render_prometheus(merged).encode(),
                        CONTENT_TYPE_LATEST)

    def _serve_job_timeline(self, query):
        """Collect per-worker flight-recorder buffers, clock-align and
        merge them (utils/trace_merge.py), serve one job trace.

        A fresh dump request rides the coordinator's response log;
        workers poll every engine cycle, so buffers land within a
        cycle or two.  If a worker never answers (dead, or the very
        stall being debugged has wedged its user threads — the engine
        background thread still polls, so even stalled workers
        normally dump), the handler serves whatever buffers exist
        after ``?wait=`` seconds rather than nothing."""
        from ...utils.trace_merge import TRACE_KV_PREFIX, merge_traces

        coord = self.server.coordinator
        params = dict(p.split("=", 1) for p in query.split("&")
                      if "=" in p)
        try:
            wait = float(params.get("wait", 15.0))
        except ValueError:
            wait = 15.0
        if not (0.0 <= wait <= 120.0):
            # unauthenticated endpoint: an unclamped (or NaN/inf) wait
            # would pin a launcher thread forever when a worker is dead
            wait = 15.0 if wait != wait or wait < 0 else 120.0
        dump_id = coord.request_trace_dump(reason="http")
        deadline = time.monotonic() + wait
        world = max(coord.world_size, 1)
        bufs = {}
        seen_raw = {}       # key -> raw bytes already parsed (rings
        #                     are MBs; re-parsing unchanged buffers
        #                     every poll tick would melt the launcher)
        while True:
            for key, raw in self.store.scope(TRACE_KV_PREFIX).items():
                if seen_raw.get(key) == raw:
                    continue
                seen_raw[key] = raw
                try:
                    payload = json.loads(raw)
                    proc = payload.get("proc")
                except (ValueError, AttributeError):
                    continue    # half-written value: skip, not 500
                if proc is None:
                    continue
                rnd = payload.get("round")
                if rnd is not None and rnd != coord.round_id:
                    continue    # stale elastic round
                if 0 < coord.world_size <= proc:
                    # a worker removed in an elastic downsize keeps its
                    # final buffer in the KV store forever (same guard
                    # as _serve_job_metrics): don't show a pid lane for
                    # a rank that no longer exists
                    continue
                bufs[proc] = payload
            fresh = sum(1 for p in bufs.values()
                        if (p.get("dump_id") or 0) >= dump_id)
            if fresh >= world or time.monotonic() > deadline:
                break
            time.sleep(0.1)
        merged = merge_traces(
            [p.get("events") or [] for _, p in sorted(bufs.items())])
        self._reply(OK, json.dumps(merged).encode(), "application/json")

    def do_DELETE(self):
        if not self._verify(b""):
            return self._reply(FORBIDDEN)
        self.store.delete(self.path)
        self._reply(OK)

    def do_POST(self):
        """Coordinator RPCs: /coord/<verb>, JSON body."""
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        if self.path == "/trace/dump":
            # on-demand flight-recorder dump trigger (curl-able like
            # /metrics and /timeline: unauthenticated, bounded work —
            # each worker pushes its ring once); fetch the merged
            # result from GET /timeline
            did = self.server.coordinator.request_trace_dump(
                reason="request")
            return self._reply(OK,
                               json.dumps({"dump_id": did}).encode(),
                               "application/json")
        if not self._verify(body):
            return self._reply(FORBIDDEN)
        if not self.path.startswith("/coord/"):
            return self._reply(BAD_REQUEST)
        verb = self.path[len("/coord/"):]
        try:
            req = json.loads(body) if body else {}
            # coordinator-side fault injection (fault-plan events with
            # side="coord"): reject or stall this request before the
            # verb runs — the client's backoff is what must recover
            act = self.server.coordinator.chaos_check(verb, req)
            if act is not None and act[0] == "error":
                return self._reply(
                    act[1], b"chaos: injected coordinator error")
            if act is not None and act[0] == "stall":
                time.sleep(act[1] / 1000.0)
            resp = self.server.coordinator.handle(verb, req)
        except Exception as exc:  # noqa: BLE001 — reported to caller
            return self._reply(BAD_REQUEST,
                               json.dumps({"error": str(exc)}).encode(),
                               "application/json")
        self._reply(OK, json.dumps(resp).encode(), "application/json")


class KVStore:
    """Blocking-get key/value store (reference KVStoreHandler).

    With a coordinator journal attached (``journal`` attribute, set by
    RendezvousServer AFTER any replay so restored entries are not
    re-journaled), every small write is recorded so a restarted
    service resurrects the KV state — elastic round assignments, user
    scopes — under the journal's size cap.  The bulky ephemeral
    namespaces (telemetry pushes, trace buffers) are excluded."""

    def __init__(self):
        self._data = {}
        self._cv = threading.Condition()  # hvdlint: lock[store:1]
        self.journal = None

    def _journal_write(self, key, value):
        j = self.journal
        if j is None or key.startswith(journal_mod.KV_EXCLUDE_PREFIXES):
            return
        if value is not None and len(value) > j.kv_max_bytes:
            logger.debug("journal: skipping oversized KV value %s "
                         "(%d bytes)", key, len(value))
            return
        if value is None:
            j.append({"k": "kvdel", "key": key})  # hvdlint: acquires[journal]
        else:
            j.append({"k": "kv", "key": key,  # hvdlint: acquires[journal]
                      "v": journal_mod._b64(value)})

    def put(self, key, value: bytes):
        with self._cv:
            self._data[key] = value
            self._journal_write(key, value)
            self._cv.notify_all()

    def get(self, key, timeout=0.0):
        deadline = None
        with self._cv:
            while True:
                if key in self._data:
                    return self._data[key]
                if timeout <= 0:
                    return None
                import time
                if deadline is None:
                    deadline = time.monotonic() + timeout
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._cv.wait(remaining)

    def delete(self, key):
        with self._cv:
            self._data.pop(key, None)
            self._journal_write(key, None)
            self._cv.notify_all()

    def restore(self, key, value: bytes):
        """Journal replay: restore an entry without re-journaling."""
        with self._cv:
            if value is None:
                self._data.pop(key, None)
            else:
                self._data[key] = value
            self._cv.notify_all()

    def scope(self, prefix):
        with self._cv:
            return {k: v for k, v in self._data.items()
                    if k.startswith(prefix)}


class Coordinator:
    """Server-side negotiation engine (the reference's rank-0
    coordinator, controller.cc ComputeResponseList/FuseResponses,
    relocated into the launcher's store service — same protocol, one
    fewer hop).

    Response cache (reference response_cache.{h,cc}): batch responses
    assign each tensor a cache id workers learn from the response; on
    repeat iterations a worker reports ``{"key", "c": id}`` instead of
    the full negotiation meta, and entries whose reports all carry the
    same id skip cross-process validation — the steady-state fast path
    that replaces the reference's two-bitvector CoordinateCacheAndState
    sync.  The LRU is capacity-bounded; reports naming an evicted id
    get the key back in ``uncached`` and resend the full meta."""

    def __init__(self, world_size: int,
                 fusion_threshold_bytes: int = 128 * 1024 * 1024,
                 cache_capacity: int = 1024, autotune: bool = False,
                 autotune_log: str = None, cycle_time_ms: float = 1.0,
                 stall_warning_secs: float = 60.0,
                 heartbeat_secs: float = 5.0,
                 heartbeat_window: float = 0.0,
                 journal=None):
        self.world_size = world_size
        self.fusion_threshold = fusion_threshold_bytes
        self.cache_capacity = cache_capacity
        self.round_id = 0
        # crash-survival state (docs/fault_tolerance.md "Coordinator
        # crash survival"): coord_epoch is a monotonic generation id
        # bumped on every journal replay; StoreClients carry it on
        # every verb and a mismatch triggers ONE resync handshake
        # instead of blind replay.  The journal records state-changing
        # transitions so restore_journal can rebuild this object.
        self.coord_epoch = 1
        self._journal = journal
        self._replaying = False
        self._store = None              # attach_store (KV for snapshots)
        self._journal_replayed = {}     # record kind -> replay count
        self._last_tuned_journaled = None
        # post-restart liveness grace: beats are only EXPECTED after a
        # proc's first post-restart beat, and no death is declared
        # before this instant — beats missed during the outage must
        # not read as deaths
        self._grace_until = 0.0
        # steady-state negotiation bypass (core/bypass.py): per-proc
        # cycle-fingerprint votes; when every proc votes the same
        # fingerprint a ``bypass_arm`` record rides the response log —
        # the coordinated instant all workers switch to the
        # coordinator-free fast path
        self._bypass_votes = {}
        self._bypass_armed_fp = None
        # coordinator-side stall inspector (reference
        # stall_inspector.cc relocated with the coordinator): an entry
        # pending past this age gets a ``stall`` response naming the
        # GLOBAL ranks of the processes that never reported it.
        # 0 disables (HOROVOD_STALL_CHECK_DISABLE).
        self.stall_warning_secs = stall_warning_secs
        # worker liveness (docs/fault_tolerance.md): workers beat via
        # the ``heartbeat`` verb; a proc whose beats stop for the
        # window (default 1.5x the interval) is declared dead — its
        # pending negotiations fail IMMEDIATELY with an error naming
        # the global ranks it hosts, instead of stall-timeout limbo.
        # A proc is only expected to beat after its FIRST beat, so
        # slow starters are never false-positived.  0 disables.
        self.heartbeat_secs = heartbeat_secs
        self.heartbeat_window = heartbeat_window
        # suspect grace for aggregator-routed procs: when a proc's
        # beats rode a now-silent aggregator, death is withheld for
        # the time its direct-fallback probing needs — the worker's
        # tight aggregator retry budget (the SAME env knob the
        # workers read: launcher and workers share the handoff) plus
        # one beat interval for the first direct beat to land
        from ...common import env as _env_mod
        self._agg_probe_grace = _env_mod.get_float(
            _env_mod.HOROVOD_AGG_FALLBACK_DEADLINE_SECONDS, 5.0) \
            + max(heartbeat_secs, 0.0)
        # Coordinator-side autotune (reference: the coordinator tunes
        # and SynchronizeParameters broadcasts, controller.cc:40-54):
        # fusion threshold is applied directly here — fusing IS this
        # server's job — and the tuned cycle time rides back to every
        # worker in poll replies.  Both tunables are seeded from the
        # user-configured values so the first broadcast doesn't clobber
        # them.
        self._autotuner = None
        if autotune:
            import types
            from ...core.autotune import ParameterManager
            self._tuned_params = types.SimpleNamespace(
                fusion_threshold_bytes=fusion_threshold_bytes,
                cycle_time_ms=cycle_time_ms,
                pack_mt_threshold_bytes=8 << 20,
                cache_capacity=cache_capacity)
            # tune_wire=False / tune_algorithm=False: wire dtype and
            # reduction algorithm are worker-side knobs with no safe
            # distribution channel from this coordinator (workers
            # applying a new default at different cycles would fail
            # the cross-process consistency check) — sweeping them
            # here would burn samples on dimensions nothing applies
            # (engine-side autotune owns both)
            self._autotuner = ParameterManager(self._tuned_params,
                                               log_path=autotune_log,
                                               tune_wire=False,
                                               tune_algorithm=False)
        self._lock = threading.Condition()  # hvdlint: lock[coord:0]
        # key -> {proc_id -> meta}
        self._pending: "OrderedDict[str, dict]" = OrderedDict()
        # Ordered response log.  Client cursors are absolute; entries
        # every process has polled past are garbage-collected and
        # _log_base keeps absolute cursors valid (without this the log
        # grows with every collective for the lifetime of the round —
        # millions of dicts over a long job).
        self._log = []
        self._log_base = 0
        self._cursors = {}      # proc_id -> highest absolute cursor seen
        self._joined = {}       # ps_id -> set of (proc, rank) joined
        self._proc_joined = {}  # ps_id -> {proc -> join count}
        self._exhausted = {}    # ps_id -> set of procs fully joined
        self._join_seen = {}    # (ps, proc) -> set of seen join ids
        self._ready_seen = {}   # proc -> highest seen ready-report id
        self._ready_reply = {}  # proc -> response of that ready report
        self._proc_sid = {}     # proc -> controller session id
        self._session_base = {}  # proc -> log index its session starts at
        self._errors = {}       # key -> error string
        self._pending_since = {}     # key -> first-report monotonic
        self._stall_warned_keys = set()  # once-per-stall dedup
        self._cache = OrderedDict()  # cache_id -> meta template (LRU)
        self._cache_by_key = {}      # key -> cache_id
        self._next_cache_id = 0
        # job-unique trace ids, one per scheduled negotiation entry:
        # batch responses carry them so every rank's flow events for
        # one collective chain on the same id (docs/timeline.md)
        self._next_trace_id = 0
        # flight-recorder dump requests appended to the response log
        # (stall auto-dumps, POST /trace/dump, GET /timeline)
        self._next_dump_id = 0
        # liveness state: proc -> last beat monotonic / hosted global
        # ranks / hostname; _dead holds declared-dead procs until the
        # next round reset (the elastic driver reads it to blacklist)
        self._beats = {}
        self._proc_ranks = {}
        self._proc_hosts = {}
        self._dead = {}
        # per-host aggregator tier (docs/fault_tolerance.md "Per-host
        # aggregator tier"): each host's aggregator registers a
        # session (agg_resync) and batches its workers' verbs into
        # agg_ready / agg_heartbeat / agg_poll.  _agg_epoch[agg] is
        # the tier's OWN generation id — bumped on every NEW session
        # of the same aggregator id, so a restarted (stateless)
        # aggregator fences its workers exactly like a restarted
        # coordinator fences everyone.  _proc_via_agg records each
        # proc's last-known beat route: a silent aggregator makes its
        # hosted procs SUSPECT, not dead — they get one extra liveness
        # window to fall back to direct beats before any verdict.
        self._agg_sid = {}      # agg -> session id
        self._agg_epoch = {}    # agg -> generation (monotonic per agg)
        self._agg_procs = {}    # agg -> hosted proc ids
        self._agg_hosts = {}    # agg -> hostname
        self._agg_beats = {}    # agg -> last upstream-contact monotonic
        self._agg_warned = set()  # once-per-silence warning dedup
        self._proc_via_agg = {}   # proc -> agg id (None = direct)
        # control-plane fan-in accounting ((verb, tier) -> requests),
        # exported through liveness_snapshot — the scale harness's
        # "coordinator load scales with hosts, not procs" evidence
        self._verb_counts = {}
        # coordinator-side chaos rules (fault-plan events with
        # side="coord": reject or stall a chosen proc's requests) and
        # the per-rule injection accounting exported via /metrics
        self._chaos_rules = []
        self._chaos_injected = {}

    def close(self):
        if self._autotuner is not None:
            self._autotuner.close()
        if self._journal is not None:
            self._journal.close()

    # -- journal plumbing (docs/fault_tolerance.md) --------------------------

    def attach_store(self, store):
        """Give the coordinator its paired KV store, for journal
        replay (restoring KV records) and compaction snapshots."""
        self._store = store

    def _j(self, rec):
        """Journal one record (no-op without a journal / during
        replay)."""
        if self._journal is not None and not self._replaying:
            self._journal.append(rec)  # hvdlint: acquires[journal]

    def _log_append(self, rec):
        """THE response-log append point: journals the record with its
        absolute index so a restarted service replays the log workers
        have not consumed yet (their cursors stay valid).  Suppressed
        during replay — replayed joins must not re-emit the join_done
        records the journal already holds.  Must hold the lock."""
        if self._replaying:
            return
        idx = self._log_base + len(self._log)
        self._log.append(rec)
        self._j({"k": "log", "i": idx, "r": rec})

    def procs_seen(self) -> int:
        """How many worker processes have polled this round — the
        round-formation signal the elastic driver's re-init timeout
        watches."""
        with self._lock:
            return len(self._cursors)

    def reset(self, world_size: int, round_id: int = 0):
        """New elastic round: fresh negotiation state; stale-round
        requests are rejected (reference: a new gloo context per
        rendezvous, gloo_context.cc:168-206)."""
        with self._lock:
            self._j({"k": "reset", "world": world_size,
                     "round": round_id})
            self.world_size = world_size
            self.round_id = round_id
            self._bypass_votes.clear()
            self._bypass_armed_fp = None
            self._pending.clear()
            self._log.clear()
            self._log_base = 0
            self._cursors.clear()
            self._joined.clear()
            self._proc_joined.clear()
            self._exhausted.clear()
            self._join_seen.clear()
            self._ready_seen.clear()
            self._ready_reply.clear()
            self._proc_sid.clear()
            self._session_base.clear()
            self._errors.clear()
            self._pending_since.clear()
            self._stall_warned_keys.clear()
            self._cache.clear()
            self._cache_by_key.clear()
            self._beats.clear()
            self._proc_ranks.clear()
            self._proc_hosts.clear()
            self._dead.clear()
            # aggregator sessions are round-scoped (surviving
            # aggregators re-register on the stale reply, which bumps
            # their agg_epoch and re-fences their workers into the
            # new round); only the epoch counters survive — they are
            # monotonic per agg id for the life of the coordinator
            self._agg_sid.clear()
            self._agg_procs.clear()
            self._agg_beats.clear()
            self._agg_warned.clear()
            self._proc_via_agg.clear()
            # chaos rules persist across rounds (the plan describes
            # the whole job) but their request counters restart with
            # the round's fresh proc numbering
            for rule in self._chaos_rules:
                rule["n"] = 0
            self._lock.notify_all()

    def handle(self, verb, req):
        if verb == "clock":
            # NTP-style ping target (utils/clock_sync.py): the
            # launcher's wall clock is THE reference clock every
            # worker's timeline epoch is mapped onto.  Round-agnostic
            # and lock-free — it must answer with minimal jitter.
            return {"t": time.time()}
        with self._lock:
            # fan-in accounting: one count per handled request, split
            # by tier (agg_* verbs arrive once per HOST per cycle, the
            # rest once per PROC) — the ratio liveness_snapshot
            # exports and ci.sh scale gates
            key = (verb, "agg" if verb.startswith("agg_")
                   else "worker")
            self._verb_counts[key] = self._verb_counts.get(key, 0) + 1
        epoch = req.get("epoch")
        if epoch is not None and epoch != self.coord_epoch \
                and verb not in EPOCH_EXEMPT_VERBS:
            # epoch fence: a request minted against a pre-restart
            # coordinator generation is rejected BEFORE any verb runs
            # — the cross-outage dedup blind HTTP replays rely on.
            # The client answers with one resync handshake.
            return {"epoch_mismatch": True, "epoch": self.coord_epoch}
        if req.get("round", self.round_id) != self.round_id:
            return {"stale": True, "round": self.round_id}
        if verb == "ready":
            return self._on_ready(req)
        if verb == "poll":
            return self._on_poll(req)
        if verb == "join":
            return self._on_join(req)
        if verb == "heartbeat":
            return self._on_heartbeat(req)
        if verb == "resync":
            return self._on_resync(req)
        if verb == "bypass_ready":
            return self._on_bypass_ready(req)
        if verb == "agg_resync":
            return self._on_agg_resync(req)
        if verb == "agg_ready":
            return self._on_agg_ready(req)
        if verb == "agg_heartbeat":
            return self._on_agg_heartbeat(req)
        if verb == "agg_poll":
            return self._on_agg_poll(req)
        raise ValueError(f"unknown coordinator verb {verb}")

    def request_trace_dump(self, reason="request"):
        """Append a flight-recorder dump request to the response log;
        every worker's next poll sees it and pushes its ring to the KV
        store (``/trace/buf/<proc>``).  Returns the dump id workers
        echo, so ``GET /timeline`` can tell fresh buffers from stale
        ones."""
        with self._lock:
            self._next_dump_id += 1
            did = self._next_dump_id
            self._log_append({"kind": "trace_dump", "id": did,
                              "reason": reason})
            self._lock.notify_all()
        return did

    # -- worker liveness (docs/fault_tolerance.md "Liveness") ---------------

    def _on_heartbeat(self, req):
        """Record a worker's liveness beat.  The first beat registers
        the proc (and the global ranks / hostname it carries, so a
        later death can be attributed); ``bye`` deregisters on clean
        shutdown — an elastic teardown must not read as a death.  A
        beat from an already-declared-dead proc (a hang that woke up,
        a network partition that healed) gets ``{"dead": true}`` back:
        its peers' collectives were already failed, so the only safe
        move for that worker is to restart into the next round.

        A DIRECT beat (this verb, as opposed to one relayed inside
        ``agg_heartbeat``) also clears the proc's aggregator route:
        it is the "direct-fallback probing succeeded" signal that
        takes the proc off a silent aggregator's suspect list."""
        proc = req.get("proc")
        if proc is None:
            return {}
        with self._lock:
            out = self._apply_heartbeat_locked(req, via=None)
            # beats are a liveness-scan clock too (AFTER recording
            # this beat — the caller is alive by definition): while
            # every worker is armed on the negotiation bypass nobody
            # polls, and a poll-clocked-only scan would never declare
            # a hung bypassed worker dead.  The elastic driver's
            # reaper reads dead_procs() in-process, so the verdict
            # reaches it — and reaping the hung process is what
            # unblocks the survivors' agreement collective.
            self._scan_heartbeats()
        return out

    def _apply_heartbeat_locked(self, req, via=None):
        """Beat-state mutation shared by the direct verb and the
        aggregator relay (``via`` = relaying agg id, None = direct).
        Must hold the lock."""
        proc = req.get("proc")
        if proc is None:
            return {}
        if req.get("bye"):
            # the bye INTENT is journaled: a restarted coordinator
            # must never re-arm liveness for a worker that already
            # said goodbye (its bye would otherwise be lost with
            # the in-memory beat table and the replayed first-beat
            # expectation would read its silence as a death)
            if self._beats.pop(proc, None) is not None or \
                    proc in self._proc_ranks:
                self._j({"k": "bye", "proc": proc})
            self._proc_ranks.pop(proc, None)
            self._proc_hosts.pop(proc, None)
            self._proc_via_agg.pop(proc, None)
            return {}
        if proc in self._dead:
            return {"dead": True}
        if proc not in self._beats:
            # first beat registers the proc: journaled so a
            # restarted coordinator keeps the rank/host attribution
            # (liveness itself re-arms only on a post-restart beat)
            self._j({"k": "hb", "proc": proc,
                     "ranks": req.get("ranks"),
                     "host": req.get("host")})
        self._beats[proc] = time.monotonic()
        self._proc_via_agg[proc] = via
        if req.get("ranks") is not None:
            self._proc_ranks[proc] = list(req["ranks"])
        if req.get("host"):
            self._proc_hosts[proc] = req["host"]
        return {}

    # -- epoch fencing + steady-state bypass (docs/fault_tolerance.md) -------

    def _on_resync(self, req):
        """Epoch resync handshake: a worker whose request hit the
        epoch fence re-registers here ONCE instead of blindly
        replaying.  A journal-replayed session (same sid) keeps its
        log position — the worker drains the replayed response log
        from its own absolute cursor, then re-reports whatever is
        still awaiting; a brand-new session starts at the log end as
        usual.  Idempotent: re-sending the same (proc, sid) changes
        nothing (REPLAY_SAFE_VERBS contract).

        ``via_agg`` records the route the handshake arrived on (the
        aggregator forwards its workers' resyncs upstream, stamping
        its id): liveness treats beats whose route went silent as
        suspect rather than dead.  A direct resync clears the route —
        the worker fell back to the coordinator."""
        proc = req.get("proc")
        with self._lock:
            if proc is not None:
                self._check_session(proc, req.get("sid"))
                self._proc_via_agg[proc] = req.get("via_agg")
                if proc in self._beats:
                    # the handshake itself proves liveness: a worker
                    # resyncing off a dead aggregator route must not
                    # be killed for the beats that died with it —
                    # its own direct beats resume within one interval
                    self._beats[proc] = max(self._beats[proc],
                                            time.monotonic())
            return {"epoch": self.coord_epoch, "round": self.round_id,
                    "cursor": self._log_base + len(self._log)}

    def _on_bypass_ready(self, req):
        """One worker's vote that its negotiated response list has
        been stable (same fingerprint) for K cycles.  When EVERY proc
        has voted the same fingerprint, a ``bypass_arm`` record rides
        the response log — consumed in log order, it is the
        coordinated instant all workers switch to the coordinator-free
        fast path (core/bypass.py).  Idempotent per (proc, fp): a
        replayed vote re-writes the same slot and an armed coordinator
        never re-arms the same fingerprint."""
        proc = req.get("proc")
        fp = req.get("fp")
        if proc is None or not fp:
            return {}
        with self._lock:
            self._check_session(proc, req.get("sid"))
            if self._bypass_armed_fp == fp:
                return {"armed": True}
            self._bypass_votes[proc] = fp
            world = max(self.world_size, 1)
            if len(self._bypass_votes) >= world and \
                    len(set(self._bypass_votes.values())) == 1:
                self._bypass_armed_fp = fp
                self._bypass_votes = {}
                # entries reported in the race window right before the
                # arm are dropped: every proc executes them through
                # the bypass (they ARE the armed list), and a batch
                # scheduled after the arm record would be consumed by
                # fast pollers only.  Entries that turn out NOT to be
                # coverable get re-reported by the unanimous fallback.
                for key in list(self._pending):
                    del self._pending[key]
                    self._pending_since.pop(key, None)
                    self._stall_warned_keys.discard(key)
                logger.info(
                    "steady-state negotiation bypass armed "
                    "(fingerprint %s..., %d procs)", fp[:12], world)
                self._log_append({"kind": "bypass_arm", "fp": fp})
                self._lock.notify_all()
                return {"armed": True}
        return {}

    def _disarm_bypass_locked(self):
        if self._bypass_armed_fp is not None:
            logger.info("steady-state negotiation bypass disarmed")
        self._bypass_armed_fp = None
        self._bypass_votes.clear()

    # -- per-host aggregator tier (docs/fault_tolerance.md) ------------------

    def _touch_agg_locked(self, agg):
        """Any upstream contact from an aggregator is a liveness beat
        for the tier (and re-arms the once-per-silence warning).
        Must hold the lock."""
        if agg is None:
            return
        self._agg_beats[agg] = time.monotonic()
        self._agg_warned.discard(agg)

    def _on_agg_resync(self, req):
        """Aggregator session registration — the tier's resync
        handshake, exempt from the epoch fence for the same reason
        ``resync`` is (a restarted aggregator re-learns the epochs it
        will fence everything else with).  A NEW session of a known
        aggregator id bumps that aggregator's ``agg_epoch``: the
        stateless restart contract — workers fencing on the
        (coord_epoch, agg_epoch) pair get a mismatch on first contact
        with the successor and answer with one worker-level resync,
        exactly like a coordinator restart.  Idempotent per
        (agg, sid); journaled so a restarted COORDINATOR keeps the
        registration (and the epoch keeps climbing, never resets).
        Round-agnostic: the reply carries the current round, which is
        how a surviving aggregator follows an elastic reset."""
        agg = req.get("agg")
        sid = req.get("sid")
        with self._lock:
            self._touch_agg_locked(agg)
            if agg is not None and self._agg_sid.get(agg) != sid:
                self._agg_sid[agg] = sid
                self._agg_epoch[agg] = self._agg_epoch.get(agg, 0) + 1
                self._agg_procs[agg] = [int(p)
                                        for p in req.get("procs", [])]
                if req.get("host"):
                    self._agg_hosts[agg] = req["host"]
                now = time.monotonic()
                for p in self._agg_procs[agg]:
                    # a weak routing hint only: beats are authoritative
                    # (a worker that already fell back direct must not
                    # be re-attributed to the re-registered aggregator
                    # until it actually routes through it again)
                    self._proc_via_agg.setdefault(p, agg)
                    # liveness grace, per tier (the agg-level twin of
                    # the coordinator's post-restart _grace_until): a
                    # NEW session means the old aggregator died — its
                    # workers' beats were lost with it, and they need
                    # a full window to re-fence/re-attach before
                    # silence may read as death
                    if p in self._beats and \
                            self._proc_via_agg.get(p) == agg:
                        self._beats[p] = max(self._beats[p], now)
                self._j({"k": "aggsess", "agg": agg, "sid": sid,
                         "host": self._agg_hosts.get(agg),
                         "procs": self._agg_procs[agg],
                         "epoch": self._agg_epoch[agg]})
                logger.info(
                    "aggregator %s (host %s) registered: %d hosted "
                    "procs, agg_epoch %d", agg,
                    self._agg_hosts.get(agg),
                    len(self._agg_procs[agg]), self._agg_epoch[agg])
            return {"epoch": self.coord_epoch,
                    "agg_epoch": self._agg_epoch.get(agg, 0),
                    "round": self.round_id,
                    "cursor": self._log_base + len(self._log)}

    def _on_agg_ready(self, req):
        """One aggregator's batched ready stream: every hosted proc's
        reports of this flush window in ONE request — the fan-in that
        makes coordinator load scale with hosts, not procs.  Each
        inner report dedups through the same per-proc rid high-water
        as the direct verb (``_ready_seen``), so a replayed batch is
        single-apply report-by-report; scheduling (``_advance``) runs
        once per batch."""
        agg = req.get("agg")
        replies = {}
        with self._lock:
            self._touch_agg_locked(agg)
            for rep in req.get("reports", []):
                replies[str(rep.get("proc"))] = \
                    self._apply_ready_locked(rep)
            self._fail_dead_entries_locked()
            self._advance()
            self._lock.notify_all()
        return {"replies": replies}

    def _on_agg_heartbeat(self, req):
        """One aggregator's batched liveness relay: every hosted proc
        that beat since the last relay, in one request.  Beats apply
        through the same idempotent ``_beats`` update as the direct
        verb, stamped with the relaying aggregator id (the route the
        suspect logic consults); the reply names the hosted procs the
        coordinator has declared dead so the aggregator can answer
        their local beats with ``{"dead": true}``."""
        agg = req.get("agg")
        dead = []
        with self._lock:
            self._touch_agg_locked(agg)
            if req.get("host"):
                self._agg_hosts[agg] = req["host"]
            for beat in req.get("beats", []):
                out = self._apply_heartbeat_locked(beat, via=agg)
                if out.get("dead"):
                    dead.append(beat.get("proc"))
            self._scan_heartbeats()
        return {"dead": dead} if dead else {}

    def _on_agg_poll(self, req):
        """One aggregator's shared long-poll: ONE upstream poll per
        host mirrors the response log for every local worker.
        ``acked`` carries the hosted workers' own consumed cursors
        (clamped by their journaled session bases) so log GC — which
        waits on every proc — keeps working with zero direct polls.
        Clocks the stall / liveness / compaction scans exactly like
        worker polls (the coordinator has no thread of its own)."""
        cursor = req["cursor"]
        agg = req.get("agg")
        round_at_entry = req.get("round", self.round_id)
        timeout = req.get("wait", 10.0)
        deadline = time.monotonic() + timeout
        with self._lock:
            if self.round_id != round_at_entry:
                return {"stale": True, "round": self.round_id}
            self._touch_agg_locked(agg)
            self._scan_stalls()
            self._scan_heartbeats()
            self._maybe_compact_locked()
            for p, c in (req.get("acked") or {}).items():
                p = int(p)
                c = int(c)
                base = self._session_base.get(p)
                if base is not None and c < base:
                    c = base
                self._cursors[p] = max(self._cursors.get(p, 0), c)
            self._gc_log()
            while self._log_base + len(self._log) <= cursor:
                if self.round_id != round_at_entry:
                    return {"stale": True, "round": self.round_id}
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return {"responses": [], "cursor": cursor,
                            "epoch": self.coord_epoch,
                            "agg_epoch": self._agg_epoch.get(agg, 0)}
                self._lock.wait(remaining)
            if self.round_id != round_at_entry:
                return {"stale": True, "round": self.round_id}
            resp = self._log[max(0, cursor - self._log_base):]
            out = {"responses": resp,
                   "cursor": self._log_base + len(self._log),
                   "epoch": self.coord_epoch,
                   "agg_epoch": self._agg_epoch.get(agg, 0)}
            if self._autotuner is not None:
                out["tuned"] = {
                    "cycle_time_ms": self._tuned_params.cycle_time_ms,
                    "pack_mt_threshold_bytes":
                        self._tuned_params.pack_mt_threshold_bytes}
            return out

    # -- journal restore + compaction ----------------------------------------

    def restore_journal(self, records):
        """Rebuild control-plane state from journal records (the
        restarted-service path: RendezvousServer.restart_from_journal).
        Bumps the monotonic epoch and opens the liveness grace window;
        the in-flight pending table is deliberately NOT restored —
        workers re-report it after their resync handshake."""
        with self._lock:
            self._replaying = True
            try:
                for rec in records:
                    self._restore_record_locked(rec)
            finally:
                self._replaying = False
            self.coord_epoch += 1
            grace = self.heartbeat_window or 1.5 * self.heartbeat_secs
            self._grace_until = time.monotonic() + max(grace, 0.0)
        self._j({"k": "epoch", "epoch": self.coord_epoch})
        replayed = sum(self._journal_replayed.values())
        logger.warning(
            "coordinator restored from journal: %d records replayed, "
            "epoch %d, round %d, %d response-log entries, liveness "
            "grace %.1fs", replayed, self.coord_epoch, self.round_id,
            len(self._log), max(self._grace_until - time.monotonic(),
                                0.0))

    def _restore_record_locked(self, rec):
        kind = rec.get("k")
        self._journal_replayed[kind] = \
            self._journal_replayed.get(kind, 0) + 1
        if kind == "epoch":
            self.coord_epoch = int(rec["epoch"])
        elif kind == "reset":
            self.world_size = rec["world"]
            self.round_id = rec["round"]
            self._restore_clear_locked()
        elif kind == "log":
            if not self._log:
                self._log_base = int(rec["i"])
            self._log.append(rec["r"])
            r = rec["r"]
            if r.get("kind") == "dead":
                self._dead[r["proc"]] = {
                    "ranks": r.get("ranks", []), "age": 0.0,
                    "host": r.get("host")}
            elif r.get("kind") == "bypass_arm":
                self._bypass_armed_fp = r.get("fp")
        elif kind == "sess":
            self._proc_sid[rec["proc"]] = rec["sid"]
            self._session_base[rec["proc"]] = rec["base"]
        elif kind == "join":
            self._apply_join_locked(rec["req"])
        elif kind == "hb":
            if rec.get("ranks") is not None:
                self._proc_ranks[rec["proc"]] = list(rec["ranks"])
            if rec.get("host"):
                self._proc_hosts[rec["proc"]] = rec["host"]
        elif kind == "bye":
            self._proc_ranks.pop(rec["proc"], None)
            self._proc_hosts.pop(rec["proc"], None)
        elif kind == "aggsess":
            # a restarted coordinator keeps the aggregator tier's
            # registrations (sid + monotonic agg_epoch + hosted
            # procs): the surviving aggregators resync without an
            # epoch bump, so their workers are never re-fenced by a
            # coordinator-only outage.  Liveness (_agg_beats) re-arms
            # only on post-restart contact, like worker beats.
            self._agg_sid[rec["agg"]] = rec["sid"]
            self._agg_epoch[rec["agg"]] = int(rec["epoch"])
            self._agg_procs[rec["agg"]] = [int(p)
                                           for p in rec.get("procs",
                                                            [])]
            if rec.get("host"):
                self._agg_hosts[rec["agg"]] = rec["host"]
            for p in self._agg_procs[rec["agg"]]:
                self._proc_via_agg.setdefault(p, rec["agg"])
        elif kind == "kv":
            if self._store is not None:
                self._store.restore(rec["key"],
                                    journal_mod._unb64(rec["v"]))
        elif kind == "kvdel":
            if self._store is not None:
                self._store.restore(rec["key"], None)
        elif kind == "tuned":
            if self._autotuner is not None:
                for name, val in rec.get("p", {}).items():
                    setattr(self._tuned_params, name, val)
        elif kind == "snap":
            self._restore_snapshot_locked(rec["s"])

    def _restore_clear_locked(self):
        """Round-reset state clear during replay (mirrors reset())."""
        self._pending.clear()
        self._log.clear()
        self._log_base = 0
        self._joined.clear()
        self._proc_joined.clear()
        self._exhausted.clear()
        self._join_seen.clear()
        self._proc_sid.clear()
        self._session_base.clear()
        self._errors.clear()
        self._proc_ranks.clear()
        self._proc_hosts.clear()
        self._dead.clear()
        self._bypass_votes.clear()
        self._bypass_armed_fp = None
        self._agg_sid.clear()
        self._agg_procs.clear()
        self._agg_hosts.clear()
        self._agg_beats.clear()
        self._agg_warned.clear()
        self._proc_via_agg.clear()

    def _restore_snapshot_locked(self, s):
        self._restore_clear_locked()
        self.coord_epoch = s["epoch"]
        self.round_id = s["round"]
        self.world_size = s["world"]
        self._log = list(s.get("log", []))
        self._log_base = s.get("log_base", 0)
        for proc, sid, base in s.get("sess", []):
            self._proc_sid[proc] = sid
            self._session_base[proc] = base
        for ps, pairs in s.get("joined", {}).items():
            self._joined[int(ps)] = {(p, r) for p, r in pairs}
        for ps, counts in s.get("proc_joined", {}).items():
            self._proc_joined[int(ps)] = {int(p): c
                                          for p, c in counts.items()}
        for ps, procs in s.get("exhausted", {}).items():
            self._exhausted[int(ps)] = set(procs)
        for ps, proc, jids in s.get("join_seen", []):
            self._join_seen[(ps, proc)] = set(jids)
        self._proc_ranks = {int(p): r
                            for p, r in s.get("ranks", {}).items()}
        self._proc_hosts = {int(p): h
                            for p, h in s.get("hosts", {}).items()}
        self._dead = {int(p): dict(info)
                      for p, info in s.get("dead", {}).items()}
        for agg, sid, epoch, host, procs in s.get("aggs", []):
            self._agg_sid[agg] = sid
            self._agg_epoch[agg] = int(epoch)
            self._agg_hosts[agg] = host
            self._agg_procs[agg] = [int(p) for p in procs]
            for p in self._agg_procs[agg]:
                self._proc_via_agg.setdefault(p, agg)
        self._bypass_armed_fp = s.get("bypass_fp")
        if self._autotuner is not None and s.get("tuned"):
            for name, val in s["tuned"].items():
                setattr(self._tuned_params, name, val)
        if self._store is not None:
            for key, val in s.get("kv", {}).items():
                self._store.restore(key, journal_mod._unb64(val))

    def _journal_snapshot_locked(self):
        """Full current state for journal compaction (coordinator lock
        held; takes the store lock via scope() — lock order
        coordinator -> store everywhere, never the reverse)."""
        kv = {}
        if self._store is not None:
            for key, val in self._store.scope("").items():  # hvdlint: acquires[store]
                if key.startswith(journal_mod.KV_EXCLUDE_PREFIXES):
                    continue
                if len(val) > self._journal.kv_max_bytes:
                    continue
                kv[key] = journal_mod._b64(val)
        tuned = None
        if self._autotuner is not None:
            tuned = dict(vars(self._tuned_params))
        return {
            "epoch": self.coord_epoch, "round": self.round_id,
            "world": self.world_size,
            "log": list(self._log), "log_base": self._log_base,
            "sess": [[p, sid, self._session_base.get(p, 0)]
                     for p, sid in self._proc_sid.items()],
            "joined": {str(ps): sorted([p, r] for p, r in pairs)
                       for ps, pairs in self._joined.items()},
            "proc_joined": {str(ps): {str(p): c
                                      for p, c in counts.items()}
                            for ps, counts in self._proc_joined.items()},
            "exhausted": {str(ps): sorted(procs)
                          for ps, procs in self._exhausted.items()},
            "join_seen": [[ps, proc, sorted(jids)]
                          for (ps, proc), jids
                          in self._join_seen.items()],
            "ranks": {str(p): r for p, r in self._proc_ranks.items()},
            "hosts": {str(p): h for p, h in self._proc_hosts.items()},
            "dead": {str(p): dict(info)
                     for p, info in self._dead.items()},
            "aggs": [[agg, sid, self._agg_epoch.get(agg, 0),
                      self._agg_hosts.get(agg),
                      sorted(self._agg_procs.get(agg, []))]
                     for agg, sid in sorted(self._agg_sid.items())],
            "bypass_fp": self._bypass_armed_fp,
            "kv": kv, "tuned": tuned,
        }

    def _maybe_compact_locked(self):
        """Bound the journal: replace history with one snapshot record
        once the file exceeds its cap (clocked by worker polls, like
        the stall and liveness scans)."""
        if self._journal is None or not self._journal.needs_compaction():
            return
        self._journal.compact(self._journal_snapshot_locked())  # hvdlint: acquires[journal]

    def _journal_tuned_locked(self):
        """Journal the coordinator autotuner's current best config
        when it changes (cheap dict compare, clocked by _advance)."""
        if self._journal is None or self._autotuner is None:
            return
        params = dict(vars(self._tuned_params))
        if params != self._last_tuned_journaled:
            self._last_tuned_journaled = params
            self._j({"k": "tuned", "p": params})

    def _scan_heartbeats(self):
        """Declare procs whose beats stopped for the window dead and
        fail every negotiation blocked on them — fast explicit failure
        naming the dead GLOBAL ranks, instead of waiting for the stall
        timeout.  Clocked by worker polls like the stall scan (the
        coordinator has no thread of its own); detection latency is
        therefore window + one poll interval, under 2x the heartbeat
        interval with the default 1.5x window.  Must hold the lock."""
        if self.heartbeat_secs <= 0 or not self._beats:
            return
        now = time.monotonic()
        if now < self._grace_until:
            # post-restart grace: beats missed during the outage are
            # not deaths; liveness only counts beats after the window
            return
        window = self.heartbeat_window or 1.5 * self.heartbeat_secs
        died = False
        for proc, last in list(self._beats.items()):
            if proc in self._dead or now - last <= window:
                continue
            agg = self._proc_via_agg.get(proc)
            if agg is not None:
                agg_last = self._agg_beats.get(agg)
                if agg_last is None or now - agg_last > window:
                    # the proc's beats rode an aggregator that is
                    # itself silent: its hosted ranks are SUSPECT,
                    # not dead — withhold the verdict for the probe
                    # grace (the worker-side fallback budget + one
                    # beat interval; a direct beat or resync clears
                    # the route and normal rules resume); only a proc
                    # still silent PAST that grace failed the direct
                    # fallback too and is declared dead
                    if agg not in self._agg_warned:
                        self._agg_warned.add(agg)
                        logger.warning(
                            "aggregator %s (host %s) silent for "
                            "%.1fs; treating its %d hosted procs as "
                            "suspect pending direct-fallback probing",
                            agg, self._agg_hosts.get(agg),
                            (now - agg_last) if agg_last else
                            float("inf"),
                            len(self._agg_procs.get(agg, ())))
                    if now - last <= window + self._agg_probe_grace:
                        continue
            age = now - last
            ranks = self._proc_ranks.get(proc, [])
            self._dead[proc] = {"ranks": ranks, "age": round(age, 1),
                                "host": self._proc_hosts.get(proc)}
            logger.warning(
                "worker process %s (global ranks %s) missed heartbeats "
                "for %.1fs (interval %.1fs); failing its pending "
                "negotiations", proc, ranks or "unknown", age,
                self.heartbeat_secs)
            self._log_append({
                "kind": "dead", "proc": proc, "ranks": ranks,
                "host": self._proc_hosts.get(proc),
                "message": (f"worker process {proc} hosting global "
                            f"ranks {ranks} is unresponsive (missed "
                            f"heartbeats for {age:.1f}s)")})
            died = True
        if died:
            self._fail_dead_entries_locked()
            self._lock.notify_all()

    def _fail_dead_entries_locked(self):
        """Error-out pending entries blocked on a dead proc (and, via
        the _on_ready call site, entries reported AFTER the death).
        The error names the dead proc's global ranks so every waiting
        rank's exception points at the failed hardware."""
        if not self._dead:
            return
        for key in list(self._pending):
            ent = self._pending[key]
            meta = next(iter(ent.values()))
            members = meta.get("members") or {}
            for proc, info in self._dead.items():
                if proc in ent:
                    continue
                in_set = (str(proc) in members) if members \
                    else (0 <= proc < max(self.world_size, 1))
                if not in_set:
                    continue
                del self._pending[key]
                self._pending_since.pop(key, None)
                self._stall_warned_keys.discard(key)
                self._log_append({
                    "kind": "error", "key": key,
                    "message": (
                        f"worker process {proc} hosting global ranks "
                        f"{info.get('ranks', [])} is unresponsive "
                        f"(missed heartbeats); {key} cannot complete")})
                break

    def dead_procs(self):
        """Declared-dead procs this round: {proc: {ranks, host, age}}.
        The elastic driver polls this to blacklist hung hosts that
        never exit (runner/elastic/driver.py).  Doubles as a scan
        clock: with every worker bypassed (no polls) and ALL workers
        hung (no beats either), the driver's monitor loop is the only
        clock left."""
        with self._lock:
            self._scan_heartbeats()
            return {p: dict(info) for p, info in self._dead.items()}

    def liveness_snapshot(self):
        """Coordinator-derived families merged into the job-wide
        ``/metrics``: ``horovod_worker_alive{proc}`` (1 = beating,
        0 = declared dead) and the coordinator-side chaos injections
        (``horovod_faults_injected_total{kind="coord_*"}``), plus the
        crash-survival families: ``horovod_coord_epoch`` (bumped on
        every journal replay) and the per-kind journal replay
        counters."""
        from ...telemetry import (
            CONTROL_FANIN_FAMILY, CONTROL_FANIN_HELP,
            CONTROL_FANIN_LABELS,
            CONTROL_REQUESTS_FAMILY, CONTROL_REQUESTS_HELP,
            CONTROL_REQUESTS_LABELS,
            COORD_EPOCH_FAMILY, COORD_EPOCH_HELP,
            FAULTS_INJECTED_FAMILY, FAULTS_INJECTED_HELP,
            JOURNAL_REPLAYED_FAMILY, JOURNAL_REPLAYED_HELP,
            WORKER_ALIVE_FAMILY, WORKER_ALIVE_HELP,
        )

        with self._lock:
            alive = {p: (0.0 if p in self._dead else 1.0)
                     for p in set(self._beats) | set(self._dead)}
            injected = dict(self._chaos_injected)
            epoch = self.coord_epoch
            replayed = dict(self._journal_replayed)
            verb_counts = dict(self._verb_counts)
            # "currently attached" means LIVE: an aggregator silent
            # past the liveness window (killed, or its host died) must
            # drop out of the gauge, or an operator watching it never
            # sees the tier shrink
            now = time.monotonic()
            window = self.heartbeat_window or \
                1.5 * self.heartbeat_secs
            fanin = {
                "agg": float(sum(
                    1 for t in self._agg_beats.values()
                    if self.heartbeat_secs <= 0
                    or now - t <= window)),
                "direct": float(sum(
                    1 for p in self._beats
                    if self._proc_via_agg.get(p) is None)),
            }
        fams = {
            COORD_EPOCH_FAMILY: {
                "type": "gauge",
                "help": COORD_EPOCH_HELP,
                "labelnames": [],
                "samples": [{"labels": {}, "value": float(epoch)}]},
        }
        if replayed:
            fams[JOURNAL_REPLAYED_FAMILY] = {
                "type": "counter",
                "help": JOURNAL_REPLAYED_HELP,
                "labelnames": ["kind"],
                "samples": [{"labels": {"kind": k}, "value": float(v)}
                            for k, v in sorted(replayed.items())]}
        if alive:
            fams[WORKER_ALIVE_FAMILY] = {
                "type": "gauge",
                "help": WORKER_ALIVE_HELP,
                "labelnames": ["proc"],
                "samples": [{"labels": {"proc": str(p)}, "value": v}
                            for p, v in sorted(alive.items())]}
        if injected:
            fams[FAULTS_INJECTED_FAMILY] = {
                "type": "counter",
                "help": FAULTS_INJECTED_HELP,
                "labelnames": ["kind"],
                "samples": [{"labels": {"kind": k}, "value": float(v)}
                            for k, v in sorted(injected.items())]}
        if verb_counts:
            fams[CONTROL_REQUESTS_FAMILY] = {
                "type": "counter",
                "help": CONTROL_REQUESTS_HELP,
                "labelnames": list(CONTROL_REQUESTS_LABELS),
                "samples": [{"labels": {"verb": v, "tier": t},
                             "value": float(n)}
                            for (v, t), n
                            in sorted(verb_counts.items())]}
        fams[CONTROL_FANIN_FAMILY] = {
            "type": "gauge",
            "help": CONTROL_FANIN_HELP,
            "labelnames": list(CONTROL_FANIN_LABELS),
            "samples": [{"labels": {"tier": t}, "value": v}
                        for t, v in sorted(fanin.items())]}
        return fams

    # -- coordinator-side chaos (docs/fault_tolerance.md) -------------------

    def add_chaos_rule(self, kind, proc=None, verb=None, after=1,
                       count=1, code=503, ms=0.0, p=1.0, rng=None,
                       event=None):
        """Install one server-side fault rule: reject
        (``kind="http_error"``) or stall (``kind="delay_ms"``) the
        matching coordinator requests from the ``after``-th on, up to
        ``count`` firings — matching on verb and/or requesting proc.
        ``p`` gates each eligible request on a draw from ``rng`` (the
        plan's seeded per-event stream; skipped requests redraw at
        the next one, mirroring worker-side semantics).  Installed by
        launchers from fault-plan events with ``side: "coord"``.
        ``kind="signal"`` fires ``event.set()`` instead of perturbing
        the request — the hook the chaos CoordFaultRunner uses to
        trigger a coordinator kill/restart on the n-th request."""
        if kind not in ("http_error", "delay_ms", "signal"):
            raise ValueError(
                f"coordinator chaos supports http_error/delay_ms/"
                f"signal, not {kind}")
        if kind == "signal" and event is None:
            raise ValueError("signal rules need an event to set")
        import random as _random
        with self._lock:
            self._chaos_rules.append({
                "kind": kind, "proc": proc, "verb": verb,
                "after": int(after), "count": int(count),
                "code": int(code), "ms": float(ms),
                "p": float(p), "rng": rng or _random.Random(0),
                "event": event, "n": 0, "fires": 0})

    def chaos_check(self, verb, req):
        """Consulted by the HTTP handler before dispatching a verb.
        Returns None, ``("error", status)`` or ``("stall", ms)``."""
        if not self._chaos_rules:
            return None
        proc = req.get("proc") if isinstance(req, dict) else None
        action = None
        with self._lock:
            for rule in self._chaos_rules:
                if rule["verb"] not in (None, verb):
                    continue
                if rule["proc"] is not None and proc != rule["proc"]:
                    continue
                rule["n"] += 1
                if (action is not None and rule["kind"] != "signal") \
                        or rule["fires"] >= rule["count"] \
                        or rule["n"] < rule["after"]:
                    continue
                if rule["p"] < 1.0 and \
                        rule["rng"].random() >= rule["p"]:
                    continue    # probabilistic skip: redraw next time
                rule["fires"] += 1
                if rule["kind"] == "signal":
                    # trigger hook for the launcher-side coordinator
                    # fault runner (kill/restart on the n-th request);
                    # the request itself proceeds untouched
                    rule["event"].set()
                    continue
                if rule["kind"] == "http_error":
                    label = "coord_http_error"
                    action = ("error", rule["code"])
                else:
                    label = "coord_stall"
                    action = ("stall", rule["ms"])
                self._chaos_injected[label] = \
                    self._chaos_injected.get(label, 0) + 1
                logger.warning(
                    "chaos: coordinator injecting %s on %s from "
                    "proc %s", rule["kind"], verb, proc)
        return action

    def _check_session(self, proc, sid):
        """A fresh controller session (engine re-init against this
        live coordinator) restarts its report counters; drop the
        PER-PROCESS state of the previous session (locked by caller):

        * rid/jid dedup — or the new session's reports would be
          discarded as replays;
        * join/exhaustion flags — or the new session's collectives
          would complete without this process's contribution;
        * response-log position — or the new session's cursor-0 poll
          would replay the previous session's batches."""
        if sid is None:
            return
        if self._proc_sid.get(proc) != sid:
            self._proc_sid[proc] = sid
            self._ready_seen.pop(proc, None)
            self._ready_reply.pop(proc, None)
            for key in [k for k in self._join_seen if k[1] == proc]:
                del self._join_seen[key]
            # drop exactly THIS proc's join/exhaustion state
            # (_joined tracks (proc, rank) pairs so other procs'
            # fresh-session joins survive the cleanup)
            for ps_key in list(self._exhausted):
                self._exhausted[ps_key].discard(proc)
            for ps_key in list(self._proc_joined):
                self._proc_joined[ps_key].pop(proc, None)
            for ps_key in list(self._joined):
                self._joined[ps_key] = {
                    (p, rk) for (p, rk) in self._joined[ps_key]
                    if p != proc}
            # new sessions start polling at the CURRENT log end
            self._session_base[proc] = self._log_base + len(self._log)
            self._cursors.pop(proc, None)
            # journaled so a restarted coordinator recognizes the SAME
            # session (no state wipe, cursor fencing intact) instead of
            # treating the surviving worker as a fresh one
            self._j({"k": "sess", "proc": proc, "sid": sid,
                     "base": self._session_base[proc]})

    def _on_ready(self, req):
        """Worker announces locally-ready entries.
        req: {proc: int, nlocal: int, entries: [meta...]}
        meta: {key, type, dtype, shape, op, pre, post, ps, nbytes,
               names, root} — or the cache-hit form {key, c, aux}.
        Returns {uncached: [key...]} for cache ids this coordinator no
        longer holds (evicted / new round); the worker resends those
        with full metas."""
        with self._lock:
            reply = self._apply_ready_locked(req)
            # entries reported after a peer was declared dead must
            # fail now, not sit pending forever
            self._fail_dead_entries_locked()
            self._advance()
            self._lock.notify_all()
        return reply

    def _apply_ready_locked(self, req):
        """Ready-report mutation shared by the direct verb and the
        aggregator batch (``agg_ready`` applies one per report under a
        single lock hold).  Must hold the lock; the caller runs
        ``_fail_dead_entries_locked`` + ``_advance`` once per
        request."""
        proc = req["proc"]
        uncached = []
        self._check_session(proc, req.get("sid"))
        rid = req.get("rid")
        if rid is not None:
            # ready is only idempotent while the entry is still
            # pending; a replayed POST (dropped keep-alive or
            # timeout retry after the server processed the
            # original) could otherwise plant a phantom entry with
            # the PREVIOUS step's meta — dedup on the client's
            # monotonically increasing report id.  The CURRENT
            # rid's replay must get the ORIGINAL response back:
            # returning {} would swallow an ``uncached`` list and
            # strand the withheld metas forever (the client only
            # ever replays its latest report, so one slot per
            # proc suffices)
            last = self._ready_seen.get(proc, 0)
            if rid == last:
                return self._ready_reply.get(proc, {})
            if rid < last:
                return {}
            self._ready_seen[proc] = rid
        if req.get("entries"):
            # a worker reporting entries has left the bypass fast
            # path (the agreement vote made the exit unanimous):
            # disarm so a fresh stable phase must re-vote
            self._disarm_bypass_locked()
        for meta in req["entries"]:
            key = meta["key"]
            if "c" in meta:
                template = self._cache.get(meta["c"])
                if template is None or \
                        self._cache_by_key.get(key) != meta["c"]:
                    uncached.append(key)
                    continue
                self._cache.move_to_end(meta["c"])
                full = dict(template)
                full["aux"] = meta.get("aux", {})
                full["_cached"] = meta["c"]
                meta = full
            ent = self._pending.get(key)
            if ent is None:
                ent = self._pending[key] = {}
                self._pending_since[key] = time.monotonic()
            if proc not in ent:
                ent[proc] = meta
                if meta.get("error"):
                    # a process failed local validation: the whole
                    # tensor errors on every process
                    self._errors[key] = meta["error"]
                err = self._validate(key, ent)
                if err:
                    self._errors[key] = err
        reply = {"uncached": uncached} if uncached else {}
        if rid is not None:
            self._ready_reply[proc] = reply
        return reply

    def _validate(self, key, ent):
        """Cross-process consistency (reference ConstructResponse,
        controller.cc:496-843)."""
        metas = list(ent.values())
        first = metas[0]
        if all(m.get("_cached") is not None
               and m.get("_cached") == first.get("_cached")
               for m in metas):
            # every report resolved through the same cache entry:
            # the metas are one template by construction (fast path)
            return None
        for m in metas[1:]:
            for field, label in (("dtype", "data types"),
                                 ("op", "reduce ops"),
                                 ("pre", "prescale factors"),
                                 ("post", "postscale factors"),
                                 ("wire", "wire dtypes"),
                                 ("wi", "inner wire dtypes"),
                                 ("algo", "algorithms"),
                                 ("pp", "pipeline schedules"),
                                 ("sfp", "shard layouts"),
                                 ("root", "root ranks")):
                if m.get(field) != first.get(field):
                    return (f"Mismatched {label} for {key}: "
                            f"{m.get(field)} vs {first.get(field)}")
            if first["type"] in ("ALLREDUCE", "ADASUM", "BROADCAST",
                                 "REDUCESCATTER"):
                if m.get("shape") != first.get("shape"):
                    return (f"Mismatched shapes for {key}: "
                            f"{m.get('shape')} vs {first.get('shape')}")
                if m.get("gshapes") != first.get("gshapes"):
                    return (f"Mismatched group member shapes for {key}: "
                            f"{m.get('gshapes')} vs "
                            f"{first.get('gshapes')}")
            else:
                if m.get("shape", [])[1:] != first.get("shape", [])[1:]:
                    return f"Mismatched non-first dimensions for {key}"
                gs_a = m.get("gshapes") or []
                gs_b = first.get("gshapes") or []
                if len(gs_a) != len(gs_b) or any(
                        a[1:] != b[1:] for a, b in zip(gs_a, gs_b)):
                    return (f"Mismatched group member non-first "
                            f"dimensions for {key}")
        return None

    def _on_join(self, req):
        """A rank joined (ran out of data).  Tracks per-process
        exhaustion so entries become ready without the exhausted
        process's report, and emits join_done once every rank of the
        set joined (reference controller.cc:269-327,413-423)."""
        ps = req.get("ps", 0)
        proc = req.get("proc", -1)
        with self._lock:
            self._check_session(proc, req.get("sid"))
            if self._apply_join_locked(req):
                # journaled post-dedup: a restarted coordinator must
                # not lose joined/exhausted state (or the exhausted
                # proc's peers would wait for reports that never come),
                # and the replayed jid keeps outage-spanning join
                # retries single-apply
                self._j({"k": "join", "req": {
                    "ps": ps, "proc": proc, "rank": req.get("rank"),
                    "jid": req.get("jid"),
                    "proc_members": req.get("proc_members", 1),
                    "ps_size": req.get("ps_size", self.world_size)}})
            self._disarm_bypass_locked()
            self._advance()
            self._lock.notify_all()
        return {}

    def _apply_join_locked(self, req) -> bool:
        """Join-state mutation shared by the live verb and journal
        replay.  Returns False when the jid was already seen (dedup)."""
        ps = req.get("ps", 0)
        proc = req.get("proc", -1)
        jid = req.get("jid")
        if jid is not None:
            # joins are not naturally idempotent (per-proc counting
            # below); dedup on the client's join id so the http
            # client's reconnect-retry can safely re-send
            seen = self._join_seen.setdefault((ps, proc), set())
            if jid in seen:
                return False
            seen.add(jid)
        j = self._joined.setdefault(ps, set())
        j.add((proc, req["rank"]))
        pj = self._proc_joined.setdefault(ps, {})
        pj[proc] = pj.get(proc, 0) + 1
        if pj[proc] >= req.get("proc_members", 1):
            self._exhausted.setdefault(ps, set()).add(proc)
        if len(j) >= req.get("ps_size", self.world_size):
            self._log_append({"kind": "join_done", "ps": ps,
                              "last": req["rank"]})
            self._joined[ps] = set()
            self._proc_joined[ps] = {}
            self._exhausted[ps] = set()
        return True

    def _advance(self):
        """Move fully-ready entries (all non-exhausted processes
        reported) from pending to the ordered response log, fusing
        adjacent compatible allreduces (FuseResponses,
        controller.cc:901-1080).  Must hold the lock."""
        ready = []
        for key in list(self._pending.keys()):
            ent = self._pending[key]
            if len(ent) >= self._members_for(ent):
                meta = next(iter(ent.values()))
                del self._pending[key]
                self._pending_since.pop(key, None)
                # completion re-arms the once-per-stall warning for a
                # re-used tensor name (mirrors the worker-side
                # _discard_stall_mark contract)
                self._stall_warned_keys.discard(key)
                if key in self._errors:
                    self._log_append({"kind": "error", "key": key,
                                      "message": self._errors.pop(key)})
                else:
                    # merge per-process aux (allgather dims / alltoall
                    # splits) for the response
                    meta = dict(meta)
                    meta["aux_by_proc"] = {str(p): m.get("aux", {})
                                           for p, m in ent.items()}
                    ready.append(meta)
        # fuse
        bucket, bucket_bytes, sig = [], 0, None

        def flush():
            nonlocal bucket, bucket_bytes, sig
            if bucket:
                self._log_append(self._batch_response(bucket))
                if self._autotuner is not None:
                    # emission rate tracks collective throughput:
                    # workers only re-report after executing the
                    # previous responses, so scheduling is gated on
                    # completion (the reference scores bytes/sec the
                    # same indirect way, parameter_manager.cc)
                    self._autotuner.record_bytes(bucket_bytes)
                bucket, bucket_bytes, sig = [], 0, None

        if self._autotuner is not None:
            self.fusion_threshold = self._tuned_params.fusion_threshold_bytes
            self.cache_capacity = self._tuned_params.cache_capacity
            # a restarted coordinator must not re-learn from scratch:
            # the current best config rides the journal
            self._journal_tuned_locked()
        for meta in ready:
            if meta["type"] not in ("ALLREDUCE", "ADASUM",
                                    "ALLGATHER"):
                if self._exhausted.get(meta.get("ps", 0)):
                    # join only supports allreduce (reference
                    # controller.cc:413-423): other ops with joined
                    # processes error instead of hanging
                    self._log_append({
                        "kind": "error", "key": meta["key"],
                        "message": (f"{meta['type']} does not support "
                                    f"joined ranks")})
                    continue
                flush()
                self._log_append(self._batch_response([meta]))
                continue
            if meta["type"] == "ALLGATHER":
                if self._exhausted.get(meta.get("ps", 0)):
                    self._log_append({
                        "kind": "error", "key": meta["key"],
                        "message": "ALLGATHER does not support "
                                   "joined ranks"})
                    continue
                # same-dtype allgathers fuse like allreduces (the
                # reference packs allgather responses too,
                # controller.cc:901-1080); output-size accounting over
                # RANKS (nprocs undercounts by ranks_per_proc —
                # engine-side _fuse uses ps.size the same way)
                msig = ("ALLGATHER", meta["dtype"], meta["ps"])
                nbytes = meta["nbytes"] * max(
                    meta.get("nranks",
                             meta.get("nprocs", self.world_size)), 1)
            else:
                # wire pair and algorithm split buckets exactly like
                # the engine-side _fuse signature: a quantized or
                # hierarchical entry must not share a fused SPMD
                # program with a full-width / flat one, nor may two
                # halves of one bucket disagree on a hop's format
                msig = (meta["type"], meta["dtype"], meta["op"],
                        meta["pre"], meta["post"], meta["ps"],
                        meta.get("wire"), meta.get("wi"),
                        meta.get("algo"), meta.get("pp"))
                nbytes = meta["nbytes"]
            if bucket and (msig != sig or
                           bucket_bytes + nbytes >
                           self.fusion_threshold):
                flush()
            bucket.append(meta)
            bucket_bytes += nbytes
            sig = msig
        flush()

    def _batch_response(self, metas):
        cache_ids = {}
        templates = {}
        for m in metas:
            key = m["key"]
            # single filtered copy serves as both the wire meta and the
            # cache template, so the two can't drift apart
            templates[key] = {k: v for k, v in m.items()
                              if k not in ("aux", "aux_by_proc",
                                           "_cached")}
            if m["type"] not in CACHEABLE_TYPES \
                    or self.cache_capacity <= 0:
                # capacity 0 = cache disabled (an autotunable point —
                # the reference tunes cache on/off the same way)
                continue
            cid = self._cache_by_key.get(key)
            if cid is None:
                cid = self._next_cache_id
                self._next_cache_id += 1
                self._cache_by_key[key] = cid
                while len(self._cache) >= self.cache_capacity:
                    old_id, old_t = self._cache.popitem(last=False)
                    self._cache_by_key.pop(old_t["key"], None)
            self._cache[cid] = templates[key]
            self._cache.move_to_end(cid)
            cache_ids[key] = cid
        # job-unique trace ids, minted per negotiation entry at
        # scheduling time: every process receives the same id for the
        # same entry, so the flow events each rank emits chain into
        # one cross-rank arrow in the merged trace
        trace_ids = {}
        for m in metas:
            self._next_trace_id += 1
            trace_ids[m["key"]] = self._next_trace_id
        resp = {
            "kind": "batch",
            "keys": [m["key"] for m in metas],
            "metas": templates,
            "aux": {m["key"]: m.get("aux_by_proc", {}) for m in metas},
            "trace": trace_ids,
        }
        if cache_ids:
            resp["cache_ids"] = cache_ids
        return resp

    def _members_for(self, ent):
        meta = next(iter(ent.values()))
        nprocs = meta.get("nprocs", self.world_size)
        exhausted = self._exhausted.get(meta.get("ps", 0), set())
        return max(nprocs - len(exhausted), 1)

    def _scan_stalls(self):
        """Global stall attribution (reference stall_inspector.cc
        CheckForStalledTensors, which runs on the coordinator rank and
        names every missing rank): an entry some processes reported
        past the warning age is attributed to the GLOBAL ranks of the
        processes that never did (the ``members`` map each report
        carries), logged here and appended to the response log as a
        ``stall`` record — so every worker's warning (and exported
        ``horovod_stall_warnings_total`` labels) names the same
        ranks.  Once per stall; completion re-arms.  Must hold the
        lock; cheap (the pending table holds in-flight entries only),
        called from every poll."""
        if self.stall_warning_secs <= 0 or not self._pending:
            return
        now = time.monotonic()
        new_stalls = 0
        for key, ent in self._pending.items():
            t0 = self._pending_since.get(key)
            if t0 is None or now - t0 <= self.stall_warning_secs \
                    or key in self._stall_warned_keys:
                continue
            self._stall_warned_keys.add(key)
            meta = next(iter(ent.values()))
            ps = meta.get("ps", 0)
            members = meta.get("members") or {}
            exhausted = self._exhausted.get(ps, set())
            reported = set(ent.keys())
            if members:
                missing_procs = sorted(
                    int(p) for p in members
                    if int(p) not in reported
                    and int(p) not in exhausted)
            else:
                # report lacked the members map: fall back to the
                # world proc universe (exact for the global set)
                missing_procs = sorted(
                    set(range(self.world_size)) - reported - exhausted)
            missing_ranks = sorted(
                r for p in missing_procs
                for r in members.get(str(p), []))
            age = now - t0
            # attribution granularity is the PROCESS: a process only
            # reports once every local rank submitted, so the ranks
            # named are "hosted by a non-reporting process" — the
            # process's own local inspector narrows to the exact rank
            logger.warning(
                "One or more tensors were submitted to be reduced by "
                "some ranks but not all: %s stalled for %.0fs "
                "(non-reporting processes: %s, hosting global ranks: "
                "%s)", key, age, missing_procs,
                missing_ranks if members else "unknown")
            self._log_append({
                "kind": "stall", "key": key, "ps": ps,
                "age": round(age, 1),
                "missing_ranks": missing_ranks,
                "missing_procs": missing_procs,
            })
            new_stalls += 1
        if new_stalls:
            # every stall warning ships with the clock-aligned job
            # trace that explains it: ONE flight-recorder dump request
            # rides the log behind this scan's stall records (one
            # straggler can stall many tensors at once; per-key dump
            # requests would have every worker re-push its full ring
            # N times), so each worker — the straggler included, its
            # engine thread still polls — pushes its last-N-seconds
            # ring exactly once per stall burst
            self._next_dump_id += 1
            self._log_append({"kind": "trace_dump",
                              "id": self._next_dump_id,
                              "reason": "stall"})
            self._lock.notify_all()     # wake parked long-polls

    def _on_poll(self, req):
        """Long-poll for responses after cursor (absolute)."""
        cursor = req["cursor"]
        round_at_entry = req.get("round", self.round_id)
        timeout = req.get("wait", 10.0)
        proc = req.get("proc")
        deadline = time.monotonic() + timeout
        with self._lock:
            if self.round_id != round_at_entry:
                # a reset raced us past handle()'s unlocked check:
                # don't let a stale cursor poison the new round's GC
                return {"stale": True, "round": self.round_id}
            # polls arrive every worker cycle, so they are the stall
            # inspector's, the liveness scan's AND the journal
            # compactor's clock (the coordinator has no thread of its
            # own)
            self._scan_stalls()
            self._scan_heartbeats()
            self._maybe_compact_locked()
            if proc is not None:
                # a re-sessioned controller polls from cursor 0; its
                # session starts at the log position recorded when the
                # new session was first seen — never replay the
                # previous session's batches to it
                base = self._session_base.get(proc)
                if base is not None and cursor < base:
                    cursor = base
                # the client has consumed everything below its cursor
                self._cursors[proc] = max(self._cursors.get(proc, 0),
                                          cursor)
                self._gc_log()
            while self._log_base + len(self._log) <= cursor:
                if self.round_id != round_at_entry:
                    # an elastic reset happened while we were waiting:
                    # this worker's round is over — never hand it the
                    # new round's responses
                    return {"stale": True, "round": self.round_id}
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return {"responses": [], "cursor": cursor,
                            "epoch": self.coord_epoch}
                self._lock.wait(remaining)
            if self.round_id != round_at_entry:
                return {"stale": True, "round": self.round_id}
            resp = self._log[max(0, cursor - self._log_base):]
            # poll replies carry the epoch: the worker adopts it on
            # first contact and fences every later verb with it
            out = {"responses": resp,
                   "cursor": self._log_base + len(self._log),
                   "epoch": self.coord_epoch}
            if self._autotuner is not None:
                out["tuned"] = {
                    "cycle_time_ms": self._tuned_params.cycle_time_ms,
                    "pack_mt_threshold_bytes":
                        self._tuned_params.pack_mt_threshold_bytes}
            return out

    def _gc_log(self):
        """Drop log entries every process has polled past.  Must hold
        the lock.  Waits until all world_size processes have polled at
        least once so a late-starting process never misses entries."""
        if len(self._cursors) < max(self.world_size, 1):
            return
        low = min(self._cursors.values())
        drop = low - self._log_base
        if drop > 0:
            del self._log[:drop]
            self._log_base = low


class _ThreadingHTTPServer(socketserver.ThreadingMixIn,
                           socketserver.TCPServer):
    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, *args, **kwargs):
        # live keep-alive connections, so a coordinator kill/restart
        # can sever them: a handler thread parked on an old keep-alive
        # would otherwise keep serving the PRE-restart coordinator
        # object, quietly splitting the control plane in two
        self._conns = set()
        self._conns_lock = threading.Lock()
        super().__init__(*args, **kwargs)

    def process_request(self, request, client_address):
        with self._conns_lock:
            self._conns.add(request)
        super().process_request(request, client_address)

    def shutdown_request(self, request):
        with self._conns_lock:
            self._conns.discard(request)
        super().shutdown_request(request)

    def close_all_connections(self):
        with self._conns_lock:
            conns, self._conns = set(self._conns), set()
        for sock in conns:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass

    def handle_error(self, request, client_address):
        # keep-alive sockets torn down by exiting workers are routine,
        # not server errors — don't spray tracebacks on every shutdown
        import sys
        exc = sys.exc_info()[1]
        if isinstance(exc, (ConnectionResetError, BrokenPipeError,
                            ConnectionAbortedError, TimeoutError)):
            return
        super().handle_error(request, client_address)


class RendezvousServer:
    """KV + coordinator HTTP service hosted by the launcher (reference
    RendezvousServer, http_server.py:192)."""

    def __init__(self, secret: bytes = None, world_size: int = 0,
                 fusion_threshold_bytes: int = 128 * 1024 * 1024,
                 cache_capacity: int = 1024, autotune: bool = False,
                 autotune_log: str = None, cycle_time_ms: float = 1.0,
                 stall_warning_secs: float = 60.0,
                 heartbeat_secs: float = 5.0,
                 heartbeat_window: float = 0.0,
                 journal_path: str = None,
                 journal_replay: bool = False):
        self._coord_kwargs = dict(
            world_size=world_size,
            fusion_threshold_bytes=fusion_threshold_bytes,
            cache_capacity=cache_capacity, autotune=autotune,
            autotune_log=autotune_log, cycle_time_ms=cycle_time_ms,
            stall_warning_secs=stall_warning_secs,
            heartbeat_secs=heartbeat_secs,
            heartbeat_window=heartbeat_window)
        self._journal_path = journal_path
        self.secret = secret
        self._httpd = None
        self._thread = None
        self._bound_port = None
        self._build(replay=journal_replay)

    def _build(self, replay):
        """(Re)build store + coordinator.  With a journal path: a
        fresh job truncates whatever a previous job left there, while
        ``replay=True`` (restart_from_journal, or
        ``HOROVOD_COORD_JOURNAL_REPLAY=1`` for a restarted launcher)
        rebuilds the control plane from the records and bumps the
        epoch."""
        journal = records = None
        if self._journal_path:
            journal = journal_mod.CoordJournal(self._journal_path)
            if replay:
                records = journal.read()
            elif os.path.exists(self._journal_path):
                journal.truncate()
        self.store = KVStore()
        self.coordinator = Coordinator(journal=journal,
                                       **self._coord_kwargs)
        self.coordinator.attach_store(self.store)
        if journal is not None:
            if records:
                self.coordinator.restore_journal(records)
            else:
                # first record of a fresh journal: the base epoch
                self.coordinator._j(
                    {"k": "epoch",
                     "epoch": self.coordinator.coord_epoch})
            # KV journaling goes live only AFTER replay so restored
            # entries are not re-journaled
            self.store.journal = journal

    def start(self, port=0) -> int:
        if port == 0 and self._bound_port:
            # a restarted service must come back on the SAME port —
            # workers have the address baked into their env handoff
            port = self._bound_port
        self._httpd = _ThreadingHTTPServer(("0.0.0.0", port), _Handler)
        self._httpd.store = self.store
        self._httpd.coordinator = self.coordinator
        self._httpd.secret = self.secret
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="hvd-rendezvous", daemon=True)
        self._thread.start()
        self._bound_port = self._httpd.server_address[1]
        return self._bound_port

    @property
    def port(self):
        # while the HTTP service is down (coord_kill window) the bound
        # port is still the service's identity: an elastic round reset
        # mid-outage must bake the REAL port into worker env, not None
        if self._httpd is not None:
            return self._httpd.server_address[1]
        return self._bound_port

    def stop_http(self):
        """Tear down the HTTP service only (chaos ``coord_kill``):
        state and journal stay, workers see connection failures and
        ride the bypass / outage-deadline retry path."""
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            # sever live keep-alives too: their handler threads hold
            # the OLD coordinator object and would keep answering
            self._httpd.close_all_connections()
            self._httpd = None

    def restart_from_journal(self) -> int:
        """Crash-recovery drill (chaos ``coord_restart``): drop ALL
        in-memory state, rebuild store + coordinator purely from the
        journal (epoch bumped, liveness grace armed) and re-serve on
        the same port.  Proves the journal alone carries the control
        plane."""
        if not self._journal_path:
            raise RuntimeError(
                "restart_from_journal requires a journal "
                "(HOROVOD_COORD_JOURNAL)")
        self.stop_http()
        self.coordinator.close()
        self._build(replay=True)
        return self.start()

    def stop(self):
        self.coordinator.close()
        self.stop_http()


def free_port():
    """Probe an OS-assigned free TCP port on THIS host (shared by every
    launcher; probe where the service will bind, never on the driver
    for a worker-hosted service)."""
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def local_ip():
    """Best-effort routable local address (reference
    driver_service NIC probing, simplified)."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("8.8.8.8", 80))
        ip = s.getsockname()[0]
        s.close()
        return ip
    except OSError:
        return "127.0.0.1"


# -- reference-shaped aliases (horovod/runner/http/http_server.py):
#    one threaded HTTP service plays both the KVStore and Rendezvous
#    roles in this build, so the reference's four names map onto the
#    two classes above. ------------------------------------------------------

SINGLE_REQUEST_TIMEOUT = 5
TIMEOUT = 60

KVStoreHandler = _Handler
RendezvousHandler = _Handler
KVStoreHTTPServer = _ThreadingHTTPServer
RendezvousHTTPServer = _ThreadingHTTPServer
KVStoreServer = RendezvousServer

"""Per-host aggregator: the middle tier of the two-tier control plane
(docs/fault_tolerance.md "Per-host aggregator tier").

One coordinator per job is the classic control-plane scaling wall
(arXiv:1802.05799); the pod-scale playbook assumes control traffic
scales with HOSTS, not chips (arXiv:1909.09756).  This tier restores
that property for every path the steady-state bypass does not cover —
warm-up, resize, stall attribution, and every bypass fallback: each
host runs ONE aggregator (the same host map
``common/topology.plan_decomposition`` reshapes reduction meshes by),
its local workers speak the unchanged coordinator wire protocol to it
over the existing KV fabric, and the aggregator batches their
ready-reports, heartbeats and polls into one upstream stream
(``agg_ready`` / ``agg_heartbeat`` / ``agg_poll``), so the coordinator
handles O(hosts) requests per negotiation cycle instead of O(procs).

Fault tolerance COMPOSES per tier instead of multiplying:

* the aggregator is **stateless-restartable** — it holds only a
  mirror of the coordinator's response log plus per-proc dedup
  high-waters, all reconstructible from the coordinator (whose
  journal survives ITS crashes).  A restarted aggregator re-registers
  through the ``agg_resync`` handshake; the coordinator bumps that
  aggregator's ``agg_epoch``, and workers — which fence every verb on
  the ``(coord_epoch, agg_epoch)`` pair — recover with the SAME
  resync → drain-the-replayed-log → re-report sequence they already
  run for a coordinator restart;
* an aggregator **death is a resync, not a job death** — workers
  whose aggregator stops answering fall back to DIRECT coordinator
  mode (``TieredStoreClient``), and the coordinator treats a silent
  aggregator's hosted ranks as *suspect* (one extra liveness window
  for the fallback probing) rather than dead;
* a **coordinator** restart behind a surviving aggregator bumps only
  ``coord_epoch``: the aggregator resyncs upstream without an
  agg_epoch bump, and its workers are fenced once, exactly as in the
  flat topology.

Enabled by ``horovodrun --control-plane-tier host``
(``HOROVOD_CONTROL_PLANE_TIER=host``): the lowest-indexed worker
process of each host starts the aggregator as a daemon thread and
publishes its address under ``/agg/<host>`` in the launcher's KV
store; its co-hosted processes discover it there.  Chaos kinds
``agg_kill`` / ``agg_restart`` (chaos/inject.py ``AggFaultRunner``)
drill both failure modes deterministically; ``tools/scale_harness.py``
drives 1000 synthetic fabric clients through the tier and gates the
fan-in ratio in ``ci.sh scale``.
"""

import json
import logging
import threading
import time

from . import http_server as http_server_mod
from .contract import EPOCH_EXEMPT_VERBS
from .http_client import StoreClient
from ...common import env as env_mod

logger = logging.getLogger("horovod_tpu")

#: default time the flusher waits after the first queued report so
#: co-reporting local workers join the same upstream batch (the knob
#: trading one linger against one upstream request per proc)
DEFAULT_LINGER_MS = 2.0


class AggregatorUpstreamError(ConnectionError):
    """The aggregator could not complete a worker's request upstream
    (coordinator unreachable / flush failed).  Surfaced to the worker
    as HTTP 503 so its client retries — and, through the
    TieredStoreClient, falls back to direct coordinator mode."""


class _PendingReport:
    """One local ready-report waiting for the next upstream flush."""

    __slots__ = ("req", "event", "reply", "error")

    def __init__(self, req):
        self.req = req
        self.event = threading.Event()
        self.reply = None
        self.error = None


class Aggregator:
    """One host's aggregator core (transport-free; AggregatorServer
    wraps it in HTTP).  Local workers call :meth:`handle` with the
    unchanged coordinator verb vocabulary; upstream traffic is the
    batched ``agg_*`` stream."""

    def __init__(self, upstream: StoreClient, agg_id, host, procs,
                 round_id=0, poll_wait=5.0, linger_ms=None,
                 relay_secs=None):
        self.client = upstream
        self.agg_id = agg_id
        self.host = host
        self.procs = list(procs)
        self.round_id = round_id
        self.poll_wait = poll_wait
        if linger_ms is None:
            linger_ms = env_mod.get_float(
                env_mod.HOROVOD_AGG_LINGER_MS, DEFAULT_LINGER_MS)
        self._linger = max(linger_ms, 0.0) / 1000.0
        if relay_secs is None:
            # beats relayed at a quarter of the worker interval keep
            # each proc's upstream beat cadence safely inside the
            # coordinator's 1.5x-interval death window
            hb = env_mod.get_float(
                env_mod.HOROVOD_HEARTBEAT_INTERVAL_SECONDS, 5.0)
            relay_secs = max(0.2, hb / 4.0) if hb > 0 else 1.0
        self._relay_secs = relay_secs
        #: the (coord_epoch, agg_epoch) pair this tier fences with —
        #: learned from the upstream agg_resync handshake
        self.coord_epoch = None
        self.agg_epoch = None
        import secrets as _secrets
        self._sid = _secrets.token_hex(8)
        self._lock = threading.Condition()  # hvdlint: lock[agg:15]
        # mirror of the coordinator's response log at ABSOLUTE
        # indices: worker cursors stay valid across a direct fallback
        # (and back), because every tier serves the same cursor space
        self._log = []
        self._log_base = 0
        self._cursors = {}          # proc -> consumed cursor (acked up)
        self._gen = 0               # bumped on round/mirror resets
        # per-proc dedup (the same contract the coordinator enforces:
        # local retries of a landed report are answered, not re-sent)
        self._ready_seen = {}
        self._ready_reply = {}
        self._proc_sid = {}
        self._join_seen = {}        # proc -> forwarded jids
        self._bypass_votes = {}     # proc -> last forwarded fp
        self._beats = {}            # proc -> last local beat monotonic
        self._fresh_beats = {}      # proc -> payload since last relay
        self._dead = set()          # upstream-declared-dead procs
        self._batch = []            # pending _PendingReport
        self._tuned = None
        #: local requests handled — the ``after`` trigger counter for
        #: agg_kill/agg_restart chaos events (chaos/inject.py)
        self.requests = 0
        self._stop = threading.Event()
        self._threads = []

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        if not self._resync_upstream():
            raise AggregatorUpstreamError(
                f"aggregator {self.agg_id}: coordinator unreachable "
                f"at registration")
        for name, target in (("poll", self._poll_loop),
                             ("flush", self._flush_loop),
                             ("beat", self._relay_loop)):
            t = threading.Thread(target=target,
                                 name=f"hvd-agg-{name}", daemon=True)
            t.start()
            self._threads.append(t)
        logger.info("aggregator %s up: %d hosted procs, agg_epoch %s",
                    self.agg_id, len(self.procs), self.agg_epoch)
        return self

    def stop(self):
        self._stop.set()
        with self._lock:
            self._lock.notify_all()
        for p in self._drain_batch():
            p.error = AggregatorUpstreamError("aggregator stopping")
            p.event.set()

    def _drain_batch(self):
        with self._lock:
            batch, self._batch = self._batch, []
        return batch

    # -- upstream handshakes -------------------------------------------------

    def _resync_upstream(self):
        """(Re-)register this aggregator session upstream and adopt
        the epochs/round/cursor the coordinator answers with.  The
        tier-level twin of the worker resync handshake — and like it,
        exempt from the fences it exists to re-learn."""
        try:
            out = self.client.coord("agg_resync", {
                "agg": self.agg_id, "sid": self._sid,
                "host": self.host, "procs": self.procs})
        except Exception as exc:  # noqa: BLE001 — caller degrades
            logger.warning("aggregator %s: upstream resync failed: %s",
                           self.agg_id, exc)
            return False
        with self._lock:
            self.coord_epoch = out.get("epoch")
            self.agg_epoch = out.get("agg_epoch")
            rnd = out.get("round")
            if rnd is not None and rnd != self.round_id:
                self._clear_round_locked(rnd)
            if not self._log and self._log_base == 0:
                # fresh mirror: start at the coordinator's current log
                # end; anything older is served by cursor pass-through
                self._log_base = int(out.get("cursor", 0))
            self._lock.notify_all()
        try:
            from ...telemetry import (
                AGG_EPOCH_FAMILY, AGG_EPOCH_HELP, registry,
            )
            registry().gauge(AGG_EPOCH_FAMILY, AGG_EPOCH_HELP).set(
                self.agg_epoch or 0)
        except Exception:  # noqa: BLE001 — accounting only
            pass
        return True

    def _clear_round_locked(self, new_round):
        """Elastic reset: drop the old round's mirror and per-proc
        state; local workers' stale-round requests are answered
        ``{"stale": ...}`` exactly as the coordinator would."""
        self.round_id = new_round
        self._gen += 1
        self._log = []
        self._log_base = 0
        self._cursors.clear()
        self._ready_seen.clear()
        self._ready_reply.clear()
        self._proc_sid.clear()
        self._join_seen.clear()
        self._bypass_votes.clear()
        self._dead.clear()
        self._lock.notify_all()

    def _adopt_round(self, new_round):
        if new_round is None:
            return
        with self._lock:
            if new_round == self.round_id:
                return
            self._clear_round_locked(new_round)
        self._resync_upstream()

    def _upstream_verb(self, verb, payload, timeout=None):
        """Low-rate pass-through (join / bypass_ready / worker resync
        forwarding): attach the upstream epoch, absorb ONE epoch bump
        with a tier resync + retry."""
        payload = dict(payload)
        payload["epoch"] = self.coord_epoch
        out = self.client.coord(verb, payload, timeout=timeout)
        if out.get("epoch_mismatch"):
            self._resync_upstream()
            payload["epoch"] = self.coord_epoch
            out = self.client.coord(verb, payload, timeout=timeout)
        if out.get("stale"):
            self._adopt_round(out.get("round"))
        return out

    # -- local verb surface --------------------------------------------------

    def handle(self, verb, req):
        """Dispatch one local worker request (the coordinator's verb
        vocabulary, unchanged).  Every verb is fenced on the
        ``(coord_epoch, agg_epoch)`` pair BEFORE it runs — a worker
        holding either stale generation is told to resync, exactly
        like the coordinator's own epoch fence — except the exempt
        recovery/ping verbs."""
        with self._lock:
            self.requests += 1
        if verb == "clock":
            # pass-through: the coordinator's wall clock is THE
            # reference clock; the NTP midpoint method absorbs the
            # extra (symmetric) hop latency
            return self.client.coord("clock", {})
        epoch = req.get("epoch")
        agg_epoch = req.get("agg_epoch")
        if ((epoch is not None and epoch != self.coord_epoch)
                or (agg_epoch is not None
                    and agg_epoch != self.agg_epoch)) \
                and verb not in EPOCH_EXEMPT_VERBS:
            return {"epoch_mismatch": True, "epoch": self.coord_epoch,
                    "agg_epoch": self.agg_epoch}
        if req.get("round", self.round_id) != self.round_id:
            return {"stale": True, "round": self.round_id}
        if verb == "ready":
            return self._on_ready(req)
        if verb == "poll":
            return self._on_poll(req)
        if verb == "heartbeat":
            return self._on_heartbeat(req)
        if verb == "resync":
            return self._on_resync(req)
        if verb == "join":
            return self._on_join(req)
        if verb == "bypass_ready":
            return self._on_bypass_ready(req)
        raise ValueError(f"unknown aggregator verb {verb}")

    def _check_session_locked(self, proc, sid):
        """A fresh worker session restarts its local dedup counters
        (the coordinator applies the authoritative wipe when the new
        sid reaches it inside the next batch)."""
        if sid is None or self._proc_sid.get(proc) == sid:
            return
        self._proc_sid[proc] = sid
        self._ready_seen.pop(proc, None)
        self._ready_reply.pop(proc, None)
        self._join_seen.pop(proc, None)

    def _on_ready(self, req):
        """Queue one worker's ready report for the next batched
        upstream flush and block until that flush answers.  Local
        retries dedup on the per-proc rid high-water exactly like the
        coordinator's own handler, so a timed-out POST to THIS tier is
        replay-safe too."""
        proc = req.get("proc")
        rid = req.get("rid")
        with self._lock:
            self._check_session_locked(proc, req.get("sid"))
            if rid is not None:
                last = self._ready_seen.get(proc, 0)
                if rid == last:
                    return self._ready_reply.get(proc, {})
                if rid < last:
                    return {}
            pend = _PendingReport({
                "proc": proc, "rid": rid, "sid": req.get("sid"),
                "nlocal": req.get("nlocal"),
                "entries": req.get("entries", [])})
            self._batch.append(pend)
            self._lock.notify_all()
        # wait OUTSIDE the lock: the flusher needs it, and a parked
        # handler must never stall its co-reporters
        budget = self.client.retry_deadline + self._linger + 10.0
        if not pend.event.wait(budget) or pend.error is not None:
            # NOTHING committed: a failed/timed-out flush leaves the
            # rid high-water untouched, so the worker's 5xx retry
            # re-queues the report instead of being answered with a
            # stale cached reply (the upstream's own rid dedup keeps
            # a did-actually-land first flush single-apply)
            raise AggregatorUpstreamError(
                f"aggregator {self.agg_id}: upstream flush failed "
                f"({pend.error})")
        with self._lock:
            if rid is not None and \
                    rid > self._ready_seen.get(proc, 0):
                # dedup state commits ONLY once the flush answered —
                # the same only-idempotent-once-landed contract the
                # coordinator's _apply_ready_locked enforces
                self._ready_seen[proc] = rid
                self._ready_reply[proc] = pend.reply
        return pend.reply

    def _on_poll(self, req):
        """Serve the response-log mirror (absolute cursors).  A cursor
        below the mirror base — a worker older than this aggregator
        instance, draining what a restarted tier never fetched — is
        passed through to the coordinator verbatim, whose journaled
        log and session fencing remain the one source of truth."""
        proc = req.get("proc")
        cursor = req["cursor"]
        wait = req.get("wait", 10.0)
        round_at_entry = req.get("round", self.round_id)
        with self._lock:
            if proc is not None:
                self._cursors[proc] = max(
                    self._cursors.get(proc, 0), cursor)
            passthrough = cursor < self._log_base
        if passthrough:
            out = self._upstream_verb(
                "poll", {"proc": proc, "cursor": cursor, "wait": wait,
                         "round": round_at_entry},
                timeout=wait + 30)
            out.setdefault("agg_epoch", self.agg_epoch)
            return out
        deadline = time.monotonic() + wait
        with self._lock:
            while self._log_base + len(self._log) <= cursor:
                if self.round_id != round_at_entry:
                    return {"stale": True, "round": self.round_id}
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._stop.is_set():
                    return {"responses": [], "cursor": cursor,
                            "epoch": self.coord_epoch,
                            "agg_epoch": self.agg_epoch}
                self._lock.wait(remaining)
            if self.round_id != round_at_entry:
                return {"stale": True, "round": self.round_id}
            out = {"responses": self._log[cursor - self._log_base:],
                   "cursor": self._log_base + len(self._log),
                   "epoch": self.coord_epoch,
                   "agg_epoch": self.agg_epoch}
            if self._tuned is not None:
                out["tuned"] = self._tuned
            return out

    def _on_heartbeat(self, req):
        """Record a local beat for the next batched relay.  ``bye``
        forwards immediately (teardown must not wait a relay tick);
        a proc the coordinator declared dead learns it here from the
        cached relay verdict."""
        proc = req.get("proc")
        if proc is None:
            return {}
        if req.get("bye"):
            with self._lock:
                self._beats.pop(proc, None)
                self._fresh_beats.pop(proc, None)
            try:
                self.client.coord("agg_heartbeat", {
                    "agg": self.agg_id, "host": self.host,
                    "epoch": self.coord_epoch,
                    "beats": [{"proc": proc, "bye": True}]},
                    budget=(2, 3.0))
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
            return {}
        with self._lock:
            if proc in self._dead:
                return {"dead": True}
            self._beats[proc] = time.monotonic()
            self._fresh_beats[proc] = {
                k: req[k] for k in ("proc", "ranks", "host")
                if req.get(k) is not None}
        return {}

    def _on_resync(self, req):
        """Worker resync through the tier: forwarded upstream (the
        coordinator's session registry and journal stay authoritative
        — the drain cursor it answers covers records this mirror has
        not fetched yet), stamped with this aggregator's id so
        liveness knows the route, and augmented with the agg_epoch the
        worker will fence with from now on."""
        proc = req.get("proc")
        out = self.client.coord("resync", {
            "proc": proc, "sid": req.get("sid"),
            "round": self.round_id, "via_agg": self.agg_id})
        if out.get("stale"):
            self._adopt_round(out.get("round"))
            return out
        with self._lock:
            self._check_session_locked(proc, req.get("sid"))
            self.coord_epoch = out.get("epoch", self.coord_epoch)
        out = dict(out)
        out["agg_epoch"] = self.agg_epoch
        return out

    def _on_join(self, req):
        """Low-rate pass-through with local jid dedup: a jid is
        recorded only after the upstream accepted it, so a failed
        forward is retried, while a local retry of a landed join is
        answered without re-sending."""
        proc = req.get("proc")
        jid = req.get("jid")
        with self._lock:
            self._check_session_locked(proc, req.get("sid"))
            if jid is not None and \
                    jid in self._join_seen.get(proc, ()):
                return {}
        out = self._upstream_verb("join", {
            k: req[k] for k in ("ps", "rank", "ps_size", "proc",
                                "proc_members", "jid", "sid")
            if k in req})
        if jid is not None and not out.get("stale") \
                and not out.get("epoch_mismatch"):
            with self._lock:
                self._join_seen.setdefault(proc, set()).add(jid)
        return out

    def _on_bypass_ready(self, req):
        """Vote pass-through (idempotent per (proc, fp) upstream);
        the local slot only mirrors the last forwarded vote."""
        proc = req.get("proc")
        with self._lock:
            self._bypass_votes[proc] = req.get("fp")
        return self._upstream_verb("bypass_ready", {
            k: req[k] for k in ("proc", "sid", "fp") if k in req},
            timeout=5.0)

    # -- background loops ----------------------------------------------------

    def _poll_loop(self):
        """ONE upstream long-poll per host mirrors the response log
        for every local worker — the read-side fan-in.  Carries the
        hosted workers' consumed cursors (``acked``) so coordinator
        log GC keeps its every-proc guarantee with zero direct
        polls."""
        while not self._stop.is_set():
            with self._lock:
                cursor = self._log_base + len(self._log)
                acked = {str(p): c for p, c in self._cursors.items()}
                gen = self._gen
            try:
                out = self.client.coord("agg_poll", {
                    "agg": self.agg_id, "cursor": cursor,
                    "acked": acked, "wait": self.poll_wait,
                    "round": self.round_id,
                    "epoch": self.coord_epoch},
                    timeout=self.poll_wait + 30)
            except Exception:  # noqa: BLE001 — outage: the client
                # already retried with backoff; park briefly and try
                # again (workers fall back direct in the meantime)
                self._stop.wait(0.5)
                continue
            if out.get("stale"):
                self._adopt_round(out.get("round"))
                continue
            if out.get("epoch_mismatch"):
                self._resync_upstream()
                continue
            with self._lock:
                if self._gen != gen:
                    continue    # a reset raced this reply: drop it
                self.coord_epoch = out.get("epoch", self.coord_epoch)
                responses = out.get("responses", [])
                if responses:
                    self._log.extend(responses)
                if out.get("tuned") is not None:
                    self._tuned = out["tuned"]
                self._lock.notify_all()

    def _flush_loop(self):
        """The write-side fan-in: every local ready report queued
        within one linger window rides ONE ``agg_ready`` upstream.
        An epoch bump mid-flush is NEVER blindly replayed — the
        waiting workers get the mismatch reply and recover with
        resync + drain + re-report, the same rule their own clients
        follow (docs/fault_tolerance.md)."""
        from ...telemetry import observe_control_cycle

        procs_set = set(self.procs)
        while not self._stop.is_set():
            with self._lock:
                while not self._batch and not self._stop.is_set():
                    self._lock.wait(0.25)
                if self._stop.is_set():
                    break
                # linger for co-reporters — but FULL local coverage
                # (every hosted proc queued a report) releases early:
                # the common all-procs-report cycle pays no linger at
                # all, while a partial batch waits out the window for
                # stragglers before going upstream
                deadline = time.monotonic() + self._linger
                while not self._stop.is_set():
                    if {p.req.get("proc")
                            for p in self._batch} >= procs_set:
                        break
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._lock.wait(min(remaining, 0.05))
                batch, self._batch = self._batch, []
                epoch = self.coord_epoch
            if not batch:
                continue
            t0 = time.monotonic()
            try:
                out = self.client.coord("agg_ready", {
                    "agg": self.agg_id, "epoch": epoch,
                    "round": self.round_id,
                    "reports": [p.req for p in batch]})
            except Exception as exc:  # noqa: BLE001 — reported to the
                # parked handlers, which surface 503 to their workers
                for p in batch:
                    p.error = exc
                    p.event.set()
                continue
            try:
                observe_control_cycle("agg", time.monotonic() - t0)
            except Exception:  # noqa: BLE001 — accounting only
                pass
            if out.get("stale"):
                for p in batch:
                    p.reply = out
                    p.event.set()
                self._adopt_round(out.get("round"))
            elif out.get("epoch_mismatch"):
                self._resync_upstream()
                reply = {"epoch_mismatch": True,
                         "epoch": self.coord_epoch,
                         "agg_epoch": self.agg_epoch}
                for p in batch:
                    p.reply = reply
                    p.event.set()
            else:
                replies = out.get("replies", {})
                for p in batch:
                    p.reply = replies.get(str(p.req.get("proc")), {})
                    p.event.set()

    def _relay_loop(self):
        """Batched liveness relay: every proc that beat locally since
        the last tick rides ONE ``agg_heartbeat`` upstream.  Procs
        the coordinator declares dead are remembered so their next
        local beat is answered ``{"dead": true}``."""
        while not self._stop.wait(self._relay_secs):
            with self._lock:
                beats, self._fresh_beats = self._fresh_beats, {}
            if not beats:
                continue
            try:
                out = self.client.coord("agg_heartbeat", {
                    "agg": self.agg_id, "host": self.host,
                    "epoch": self.coord_epoch,
                    "beats": list(beats.values())}, timeout=5.0)
            except Exception:  # noqa: BLE001 — retried next tick with
                # the beats re-merged (newer local beats win)
                with self._lock:
                    for p, b in beats.items():
                        self._fresh_beats.setdefault(p, b)
                continue
            if out.get("epoch_mismatch"):
                self._resync_upstream()
                with self._lock:
                    for p, b in beats.items():
                        self._fresh_beats.setdefault(p, b)
                continue
            if out.get("dead"):
                with self._lock:
                    self._dead.update(out["dead"])


# -- HTTP transport ------------------------------------------------------------

OK = http_server_mod.OK
BAD_REQUEST = http_server_mod.BAD_REQUEST
FORBIDDEN = http_server_mod.FORBIDDEN
NOT_FOUND = http_server_mod.NOT_FOUND
UNAVAILABLE = 503


class _AggHandler(http_server_mod._Handler):
    """The worker-facing wire surface: same HMAC envelope and verb
    paths as the coordinator handler (workers cannot tell the tiers
    apart), with KV traffic proxied upstream verbatim — the
    aggregator caches nothing it cannot reconstruct."""

    @property
    def agg(self):
        return self.server.aggregator

    def do_PUT(self):
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        if not self._verify(body):
            return self._reply(FORBIDDEN)
        try:
            self.agg.client.put(self.path, body)
        except Exception:  # noqa: BLE001 — upstream outage
            return self._reply(UNAVAILABLE, b"agg: upstream put failed")
        self._reply(OK)

    def do_GET(self):
        path, _, query = self.path.partition("?")
        if not self._verify(b""):
            return self._reply(FORBIDDEN)
        params = dict(p.split("=", 1) for p in query.split("&")
                      if "=" in p)
        try:
            wait = float(params.get("wait", 0))
        except ValueError:
            wait = 0.0
        try:
            value = self.agg.client.get(path, wait=wait)
        except Exception:  # noqa: BLE001 — upstream outage
            return self._reply(UNAVAILABLE, b"agg: upstream get failed")
        if value is None:
            return self._reply(NOT_FOUND)
        self._reply(OK, value)

    def do_DELETE(self):
        if not self._verify(b""):
            return self._reply(FORBIDDEN)
        try:
            self.agg.client.delete(self.path)
        except Exception:  # noqa: BLE001 — upstream outage
            return self._reply(UNAVAILABLE,
                               b"agg: upstream delete failed")
        self._reply(OK)

    def do_POST(self):
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        if not self._verify(body):
            return self._reply(FORBIDDEN)
        if not self.path.startswith("/coord/"):
            return self._reply(BAD_REQUEST)
        verb = self.path[len("/coord/"):]
        try:
            req = json.loads(body) if body else {}
            resp = self.agg.handle(verb, req)
        except AggregatorUpstreamError as exc:
            # 503, not 400: the worker's client retries 5xx under its
            # tight budget, then the TieredStoreClient falls back to
            # direct coordinator mode — degradation, never deadlock
            return self._reply(UNAVAILABLE, str(exc).encode())
        except Exception as exc:  # noqa: BLE001 — reported to caller
            return self._reply(BAD_REQUEST,
                               json.dumps({"error": str(exc)}).encode(),
                               "application/json")
        self._reply(OK, json.dumps(resp).encode(), "application/json")


class AggregatorServer:
    """HTTP wrapper around one Aggregator core.  ``restart()`` builds
    a FRESH core on the SAME port — the stateless-restart drill chaos
    ``agg_restart`` runs: the new core's new session id makes the
    coordinator bump ``agg_epoch``, which re-fences every worker."""

    def __init__(self, secret, make_core):
        self.secret = secret
        self._make_core = make_core
        self.aggregator = None
        self._httpd = None
        self._thread = None
        self._bound_port = None

    def start(self, port=0) -> int:
        if port == 0 and self._bound_port:
            # a restarted aggregator must come back on the SAME port —
            # workers discovered the address once, via the KV record
            port = self._bound_port
        self.aggregator = self._make_core()
        self.aggregator.start()
        self._httpd = http_server_mod._ThreadingHTTPServer(
            ("0.0.0.0", port), _AggHandler)
        self._httpd.aggregator = self.aggregator
        self._httpd.secret = self.secret
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="hvd-aggregator",
            daemon=True)
        self._thread.start()
        self._bound_port = self._httpd.server_address[1]
        return self._bound_port

    @property
    def port(self):
        if self._httpd is not None:
            return self._httpd.server_address[1]
        return self._bound_port

    def stop_http(self):
        """Tear the service down (chaos ``agg_kill``): local workers
        see connection failures and fall back to direct coordinator
        mode; the coordinator's liveness marks the hosted ranks
        suspect until their direct beats land."""
        if self.aggregator is not None:
            self.aggregator.stop()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            # sever live keep-alives: a handler thread parked on an
            # old connection would keep serving the dead core
            self._httpd.close_all_connections()
            self._httpd = None

    def restart(self) -> int:
        """Stateless restart (chaos ``agg_restart``): fresh core, same
        port, nothing carried over — everything the tier needs comes
        back from the coordinator through agg_resync."""
        self.stop_http()
        return self.start()

    def stop(self):
        self.stop_http()


# -- per-process bootstrap -----------------------------------------------------
#
# The lowest-indexed worker process of each host owns that host's
# aggregator (one per host — the same ownership rule the reference's
# hierarchical collectives use for the local root); co-hosted
# processes discover its address through the launcher's KV store.

_PROCESS_AGG = None
_PROCESS_AGG_FAULTS = None
_AGG_LOCK = threading.Lock()

AGG_KV_PREFIX = "/agg/"


def tier_enabled(env=None):
    """Whether the per-host aggregator tier is requested
    (``HOROVOD_CONTROL_PLANE_TIER=host``; ``flat``/unset = the
    single-coordinator topology)."""
    val = (env_mod.get_str(env_mod.HOROVOD_CONTROL_PLANE_TIER)
           if env is None else
           env.get(env_mod.HOROVOD_CONTROL_PLANE_TIER))
    return str(val or "").strip().lower() in ("host", "2", "two")


def ensure_host_aggregator(rdv_addr, rdv_port, secret, proc_id,
                           host_of_proc, round_id=0,
                           start_timeout=60.0):
    """Start (owner) or discover (co-hosted) this host's aggregator.
    Returns ``(addr, port, agg_id)``.  Idempotent per process: an
    elastic re-init reuses the running aggregator — a new round flows
    through its stale-round adoption, not through a re-spawn."""
    global _PROCESS_AGG, _PROCESS_AGG_FAULTS
    host = host_of_proc[proc_id]
    procs = [p for p, h in enumerate(host_of_proc) if h == host]
    agg_id = f"host{host}"
    key = AGG_KV_PREFIX + agg_id
    direct = StoreClient(rdv_addr, rdv_port, secret)
    if proc_id == min(procs):
        with _AGG_LOCK:
            if _PROCESS_AGG is None:
                hostname = env_mod.get_str(env_mod.HOROVOD_HOSTNAME) \
                    or agg_id

                def make_core():
                    return Aggregator(
                        StoreClient(rdv_addr, rdv_port, secret),
                        agg_id=agg_id, host=hostname, procs=procs,
                        round_id=round_id)

                server = AggregatorServer(secret, make_core)
                port = server.start()
                addr = "127.0.0.1" \
                    if rdv_addr in ("127.0.0.1", "localhost") \
                    else http_server_mod.local_ip()
                direct.put(key, json.dumps(
                    {"addr": addr, "port": port}).encode())
                _PROCESS_AGG = server
                if env_mod.get_str(env_mod.HOROVOD_FAULT_PLAN):
                    from ...chaos.inject import start_aggregator_faults
                    _PROCESS_AGG_FAULTS = start_aggregator_faults(
                        server, agg_index=host)
        raw = direct.get(key, wait=start_timeout)
    else:
        raw = direct.get(key, wait=start_timeout)
    if raw is None:
        raise RuntimeError(
            f"aggregator address for {agg_id} never appeared at "
            f"{key} (owner proc {min(procs)} failed to start it?)")
    info = json.loads(raw)
    return info["addr"], int(info["port"]), agg_id


def stop_process_aggregator():
    """Engine-shutdown hook: stop this process's aggregator (if it
    owns one).  Co-hosted workers still running fall back to direct
    coordinator mode — the same degradation an agg_kill drills."""
    global _PROCESS_AGG, _PROCESS_AGG_FAULTS
    with _AGG_LOCK:
        server, _PROCESS_AGG = _PROCESS_AGG, None
        faults, _PROCESS_AGG_FAULTS = _PROCESS_AGG_FAULTS, None
    if faults is not None:
        faults.stop()
    if server is not None:
        server.stop()

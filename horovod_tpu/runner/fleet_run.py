"""Fleet job entry — ``horovodrun --fleet-spec`` (docs/fleet.md).

Unlike a single elastic job (elastic_run.py), a fleet launch reads a
JSON spec declaring N jobs over one shared host pool and hands the
whole lifecycle to the :class:`~horovod_tpu.fleet.FleetController`:
per-job rendezvous services + elastic drivers, reconciliation,
preemption-by-elasticity, suspension, and the journaled-restart path
(``HOROVOD_FLEET_RESUME=1`` replays ``HOROVOD_FLEET_JOURNAL``).
"""

from ..common import env as env_mod
from .config_parser import set_env_from_args


def run_fleet(args):
    import sys

    from ..fleet import load_spec, FleetController

    source = args.fleet_spec or env_mod.get_str(
        env_mod.HOROVOD_FLEET_SPEC)
    if not source:
        print("horovodrun: --fleet-spec (or HOROVOD_FLEET_SPEC) "
              "required for a fleet launch", file=sys.stderr)
        return 2
    try:
        spec = load_spec(source)
    except (ValueError, OSError) as exc:
        print(f"horovodrun: invalid fleet spec: {exc}",
              file=sys.stderr)
        return 2
    env = {}
    set_env_from_args(env, args)
    controller = FleetController(
        spec, platform="cpu" if args.cpu else None,
        verbose=args.verbose, env=env)
    controller.start()
    controller.run()
    try:
        ok = controller.join()
    except KeyboardInterrupt:
        ok = False
    finally:
        controller.stop()
    return 0 if ok else 1

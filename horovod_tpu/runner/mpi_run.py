"""MPI launch surface (reference ``horovod/runner/mpi_run.py``).

TPU pods have no MPI (SURVEY §7.4 — the launcher accepts ``--mpi`` as
a compatibility no-op and uses the store controller).  The detection
predicates are real probes of the local ``mpirun``; ``mpi_run`` itself
fails loudly with the supported alternative instead of silently doing
something different from what the caller asked."""

from .common.util.tiny_shell_exec import execute as _exec


def _mpirun_version_output():
    result = _exec("mpirun --version")
    if result is None or result[1] != 0:
        return None
    return result[0]


def is_open_mpi():
    out = _mpirun_version_output()
    return out is not None and "Open MPI" in out


def is_spectrum_mpi():
    out = _mpirun_version_output()
    return out is not None and "IBM Spectrum MPI" in out


def is_mpich():
    out = _mpirun_version_output()
    return out is not None and ("MPICH" in out or "HYDRA" in out)


def is_intel_mpi():
    out = _mpirun_version_output()
    return out is not None and "Intel(R) MPI" in out


def mpi_available(env=None):
    return _mpirun_version_output() is not None


def mpi_run(settings, nics, env, command, stdout=None, stderr=None):
    raise RuntimeError(
        "MPI launch is not supported on the TPU runtime: there is no "
        "MPI data or control plane on TPU pods. Use the default "
        "launcher (horovodrun without --mpi, or "
        "horovod_tpu.runner.gloo_run.gloo_run) — it provides the same "
        "rendezvous/env-handoff contract over the store controller.")

"""``horovodrun`` CLI (reference ``horovod/runner/launch.py``:
arg surface :286-528, run_commandline :830, _run :806).

Static jobs spawn one worker process per slot with the full
``HOROVOD_*`` env handoff (proc_run.py); elastic jobs drive discovery
+ re-rendezvous (elastic/driver.py)."""

import argparse
import os
import sys

from .config_parser import parse_config_file, set_env_from_args
from .hosts import parse_host_files

#: Flags the LAUNCHER itself consumes (process topology, discovery,
#: output plumbing) — everything else must have a ``HOROVOD_*`` env
#: handoff in config_parser.set_env_from_args so workers see it.
#: hvdlint checker 5 (`knob-flag-unhandled`) enforces the split: a
#: new tuning flag that is neither handed off nor declared here
#: fails CI.
_LAUNCHER_ONLY_FLAGS = (
    "version", "np", "hosts", "hostfile", "ranks_per_proc",
    "cpu", "gloo", "mpi", "check_build", "start_timeout", "verbose",
    "output_filename", "config_file",
    # elastic driver settings (consumed launcher-side by
    # elastic/driver.py; elastic_timeout ALSO rides the env handoff
    # for the workers' init barrier)
    "min_np", "max_np", "host_discovery_script", "slots_per_host",
    "reset_limit", "blacklist_cooldown_range",
    # fleet controller (consumed launcher-side by fleet_run.py /
    # fleet/controller.py; per-job commands live in the spec)
    "fleet_spec",
    "command",
)


def parse_args(argv=None):
    parser = argparse.ArgumentParser(
        prog="horovodrun",
        description="Launch a horovod_tpu distributed job.")
    parser.add_argument("-v", "--version", action="store_true",
                        help="Shows horovod_tpu version.")
    parser.add_argument("-np", "--num-proc", type=int, dest="np",
                        help="Total number of training ranks.")
    parser.add_argument("-H", "--hosts", dest="hosts",
                        help="host1:slots,host2:slots list.")
    parser.add_argument("-hostfile", "--hostfile", dest="hostfile",
                        help="Host file with 'name slots=N' lines.")
    parser.add_argument("--ranks-per-worker", default=1,
                        dest="ranks_per_proc",
                        type=lambda s: s if s == "host" else int(s),
                        help="Rank threads per worker process (TPU hosts "
                             "drive all local chips from one process), "
                             "or 'host': one process per -H entry "
                             "driving that entry's slots — the "
                             "reference's heterogeneous h1:4,h2:2 "
                             "layout.")
    parser.add_argument("--cpu", action="store_true",
                        help="Force the CPU platform (virtual devices).")
    parser.add_argument("--gloo", action="store_true",
                        help="Accepted for reference compatibility; the "
                             "data plane is always compiled XLA.")
    parser.add_argument("--mpi", action="store_true",
                        help="Accepted for reference compatibility.")
    parser.add_argument("--check-build", action="store_true",
                        help="Show available framework frontends.")
    parser.add_argument("--start-timeout", type=float, default=None,
                        help="Seconds to wait for the job to finish "
                             "launching.")
    parser.add_argument("--verbose", action="store_true")
    parser.add_argument("--output-filename", default=None,
                        help="directory for per-rank output capture: "
                             "worker stdout/stderr are saved to "
                             "<dir>/rank.<rank>/{stdout,stderr} "
                             "(rank zero-padded)")
    parser.add_argument("--config-file", dest="config_file",
                        help="YAML file with launcher parameters.")
    # tunables (reference launch.py:373-431)
    parser.add_argument("--fusion-threshold-mb", type=float, default=None)
    parser.add_argument("--cycle-time-ms", type=float, default=None)
    parser.add_argument("--cache-capacity", type=int, default=None)
    # topology-aware collectives (the source fork's NCCL torus-
    # allreduce flag plus upstream's hierarchical toggle, mapped to
    # the same HOROVOD_* env names workers read)
    parser.add_argument("--torus-allreduce", action="store_true",
                        help="decompose float Sum/Average allreduces "
                             "over a 2-D torus factorization of the "
                             "ranks (HOROVOD_TORUS_ALLREDUCE)")
    parser.add_argument("--hierarchical-allreduce", action="store_true",
                        help="reducescatter within each host, "
                             "allreduce the shards across hosts, "
                             "allgather back "
                             "(HOROVOD_HIERARCHICAL_ALLREDUCE)")
    parser.add_argument("--allreduce-algorithm", default=None,
                        choices=["flat", "hierarchical", "torus"],
                        help="generic spelling of the algorithm knob "
                             "(HOROVOD_ALLREDUCE_ALGORITHM); the "
                             "boolean flags above win when both are "
                             "given")
    # per-hop quantized wire (docs/concepts.md "Per-hop wire")
    parser.add_argument("--wire-dtype", default=None,
                        choices=["f32", "fp16", "bf16", "int8",
                                 "int4"],
                        help="uniform wire shorthand for every "
                             "reduction (HOROVOD_WIRE_DTYPE): 16-bit "
                             "values apply to both hops of a "
                             "decomposed allreduce, int8/int4 to the "
                             "cross-host hop only")
    parser.add_argument("--wire-inner", default=None,
                        choices=["f32", "fp16", "bf16"],
                        help="intra-host/ICI hop wire of the per-hop "
                             "pair (HOROVOD_WIRE_INNER; quantized "
                             "formats are not legal on this hop)")
    parser.add_argument("--wire-outer", default=None,
                        choices=["f32", "fp16", "bf16", "int8",
                                 "int4"],
                        help="cross-host/DCN hop wire of the per-hop "
                             "pair (HOROVOD_WIRE_OUTER; wins over "
                             "--wire-dtype)")
    # MPMD pipeline runtime (docs/parallelism.md)
    parser.add_argument("--pipeline-stages", type=int, default=None,
                        help="carve the job into this many pipeline "
                             "stages backed by per-stage process "
                             "sets (HOROVOD_PP_STAGES; 1 = no "
                             "pipelining)")
    parser.add_argument("--num-microbatches", type=int, default=None,
                        help="microbatches per pipelined step "
                             "(HOROVOD_PP_MICROBATCHES; 0 = auto, "
                             "also the autotuner's seventh-dimension "
                             "sweep variable)")
    parser.add_argument("--pipeline-schedule", default=None,
                        choices=["gpipe", "1f1b", "interleaved"],
                        help="pipeline schedule the per-rank "
                             "instruction streams follow "
                             "(HOROVOD_PP_SCHEDULE; default 1f1b, "
                             "gpipe is the fill-drain fallback)")
    parser.add_argument("--pipeline-chunks", type=int, default=None,
                        help="model chunks per stage for the "
                             "interleaved schedule "
                             "(HOROVOD_PP_CHUNKS; 0 = auto: 2)")
    parser.add_argument("--autotune-cache-file", default=None,
                        help="local JSON warm-start cache of "
                             "converged autotune optima keyed by "
                             "(bucket signature, topology, world "
                             "size) (HOROVOD_AUTOTUNE_CACHE)")
    # timeline + job-wide tracing (docs/timeline.md)
    parser.add_argument("--timeline-filename", default=None)
    parser.add_argument("--timeline-mark-cycles", action="store_true")
    parser.add_argument("--trace-ring-events", type=int, default=None,
                        help="flight-recorder ring size per worker "
                             "(events; 0 disables) — the buffer stall "
                             "warnings auto-dump and GET /timeline "
                             "merges (HOROVOD_TRACE_RING_EVENTS)")
    parser.add_argument("--trace-dump-dir", default=None,
                        help="directory flight-recorder auto-dumps "
                             "are written into as stand-alone Chrome "
                             "traces (HOROVOD_TRACE_DUMP_DIR; unset = "
                             "KV push only)")
    parser.add_argument("--trace-clock-sync-seconds", type=float,
                        default=None,
                        help="cadence of the NTP-style clock re-sync "
                             "mapping each worker's timeline onto the "
                             "launcher's clock "
                             "(HOROVOD_TRACE_CLOCK_SYNC_SECONDS)")
    # telemetry (docs/observability.md)
    parser.add_argument("--metrics-port", type=int, default=None,
                        help="base port for per-worker Prometheus "
                             "/metrics endpoints (worker i binds "
                             "port+i on its host); also enables the "
                             "job-wide /metrics on the launcher's "
                             "rendezvous service "
                             "(HOROVOD_METRICS_PORT)")
    parser.add_argument("--metrics-push-seconds", type=float,
                        default=None,
                        help="cadence of worker snapshot pushes into "
                             "the job-wide aggregation "
                             "(HOROVOD_METRICS_PUSH_SECONDS)")
    # autotune
    parser.add_argument("--autotune", action="store_true")
    parser.add_argument("--autotune-log-file", default=None)
    parser.add_argument("--autotune-warmup-samples", type=int,
                        default=None)
    parser.add_argument("--autotune-steps-per-sample", type=int,
                        default=None)
    parser.add_argument("--autotune-bayes-opt-max-samples", type=int,
                        default=None)
    parser.add_argument("--disable-cache", action="store_true",
                        help="disable the coordinator response cache "
                             "(HOROVOD_CACHE_CAPACITY=0)")
    # chaos + liveness (docs/fault_tolerance.md)
    parser.add_argument("--fault-plan", default=None,
                        help="seeded fault-injection plan: inline "
                             "JSON, @/path, or a path to a JSON file "
                             "(HOROVOD_FAULT_PLAN); worker-side "
                             "events ride the env handoff, "
                             "coordinator-side events install into "
                             "the launcher's rendezvous service")
    parser.add_argument("--fault-seed", type=int, default=None,
                        help="override the plan's RNG seed "
                             "(HOROVOD_FAULT_SEED)")
    parser.add_argument("--heartbeat-interval-seconds", type=float,
                        default=None,
                        help="worker liveness heartbeat cadence; the "
                             "coordinator fails a silent worker's "
                             "pending collectives after ~1.5x this "
                             "(0 disables; "
                             "HOROVOD_HEARTBEAT_INTERVAL_SECONDS)")
    parser.add_argument("--heartbeat-window-seconds", type=float,
                        default=None,
                        help="explicit missed-beat death window "
                             "(default 1.5x the interval; "
                             "HOROVOD_HEARTBEAT_WINDOW_SECONDS)")
    # coordinator crash survival + steady-state bypass
    # (docs/fault_tolerance.md "Coordinator crash survival")
    parser.add_argument("--coord-journal", default=None,
                        help="path for the launcher-side control-plane "
                             "journal; a restarted rendezvous service "
                             "replays it (epoch-fenced) instead of "
                             "killing every healthy worker "
                             "(HOROVOD_COORD_JOURNAL)")
    parser.add_argument("--coord-outage-deadline-seconds", type=float,
                        default=None,
                        help="how long replay-safe fabric requests "
                             "keep retrying across a coordinator "
                             "outage (default 120; "
                             "HOROVOD_COORD_OUTAGE_DEADLINE_SECONDS)")
    parser.add_argument("--bypass-after-cycles", type=int, default=None,
                        help="identical negotiation cycles before the "
                             "ranks bypass the coordinator via a "
                             "bitvector agreement on the collective "
                             "path (0 disables; default 5; "
                             "HOROVOD_BYPASS_AFTER_CYCLES)")
    parser.add_argument("--bypass-wait-seconds", type=float,
                        default=None,
                        help="bound on each bypass cycle's wait for "
                             "the cached tensors before forcing full "
                             "renegotiation "
                             "(HOROVOD_BYPASS_WAIT_SECONDS)")
    # per-host aggregator tier (docs/fault_tolerance.md "Per-host
    # aggregator tier"): coordinator load scales with hosts, not procs
    parser.add_argument("--control-plane-tier", default=None,
                        choices=["flat", "host"],
                        help="control-plane topology: 'flat' fans "
                             "every proc into the coordinator; "
                             "'host' runs one aggregator per host "
                             "that batches its workers' ready-"
                             "reports/heartbeats/polls upstream "
                             "(HOROVOD_CONTROL_PLANE_TIER)")
    parser.add_argument("--agg-linger-ms", type=float, default=None,
                        help="aggregator batching window: how long "
                             "the upstream flusher waits for "
                             "co-reporting local workers "
                             "(HOROVOD_AGG_LINGER_MS)")
    parser.add_argument("--agg-fallback-deadline-seconds",
                        type=float, default=None,
                        help="how long a worker's requests retry "
                             "against a silent aggregator before "
                             "falling back to direct coordinator "
                             "mode (HOROVOD_AGG_FALLBACK_DEADLINE_"
                             "SECONDS)")
    # serving tier (docs/serving.md): --serve marks the job as an
    # inference fleet — workers run hvd.serving.start() replicas, the
    # knobs ride the same HOROVOD_SERVING_* env handoff as every other
    # launcher setting, and (elastic jobs) the launcher attaches the
    # SLO autoscaler to the elastic driver
    parser.add_argument("--serve", action="store_true",
                        help="serving job: enable the serving env "
                             "handoff and (with elastic flags) the "
                             "SLO-driven autoscaler "
                             "(HOROVOD_SERVING=1)")
    parser.add_argument("--serve-port", type=int, default=None,
                        help="base port for per-replica HTTP predict "
                             "frontends (replica i on a host binds "
                             "port+i; HOROVOD_SERVING_PORT)")
    parser.add_argument("--serve-max-batch-size", type=int,
                        default=None,
                        help="dynamic batcher: max requests per "
                             "device batch "
                             "(HOROVOD_SERVING_MAX_BATCH_SIZE)")
    parser.add_argument("--serve-max-latency-ms", type=float,
                        default=None,
                        help="dynamic batcher: max time a request "
                             "waits for co-riders "
                             "(HOROVOD_SERVING_MAX_LATENCY_MS)")
    parser.add_argument("--serve-batch-buckets", default=None,
                        help="comma-separated bucketed batch sizes "
                             "the compiled path pads to (default: "
                             "powers of two up to the max; "
                             "HOROVOD_SERVING_BATCH_BUCKETS)")
    parser.add_argument("--serve-slo-p99-ms", type=float, default=None,
                        help="p99 latency SLO the autoscaler defends "
                             "(HOROVOD_SERVING_SLO_P99_MS)")
    parser.add_argument("--serve-queue-high", type=int, default=None,
                        help="queue-depth high-water mark that also "
                             "triggers scale-up "
                             "(HOROVOD_SERVING_QUEUE_HIGH)")
    parser.add_argument("--serve-autoscale-seconds", type=float,
                        default=None,
                        help="autoscaler evaluation cadence "
                             "(HOROVOD_SERVING_AUTOSCALE_SECONDS)")
    parser.add_argument("--serve-drain-seconds", type=float,
                        default=None,
                        help="max time a draining replica waits for "
                             "queued requests before shutdown "
                             "(HOROVOD_SERVING_DRAIN_SECONDS)")
    # stall check
    parser.add_argument("--no-stall-check", action="store_true")
    parser.add_argument("--stall-check-warning-time-seconds", type=float,
                        default=None)
    parser.add_argument("--stall-check-shutdown-time-seconds", type=float,
                        default=None)
    parser.add_argument("--log-level", default=None,
                        choices=["TRACE", "DEBUG", "INFO", "WARNING",
                                 "ERROR", "FATAL"])
    # elastic (reference launch.py elastic group)
    parser.add_argument("--min-np", type=int, default=None)
    parser.add_argument("--max-np", type=int, default=None)
    parser.add_argument("--host-discovery-script", default=None)
    parser.add_argument("--slots-per-host", type=int, default=None)
    parser.add_argument("--reset-limit", type=int, default=None)
    # default None (not 600): the env handoff in set_env_from_args
    # only fires when the flag is given, so an exported
    # HOROVOD_ELASTIC_TIMEOUT keeps flowing through untouched; the
    # 600 s fallback lives in the driver and the worker init barrier
    parser.add_argument("--elastic-timeout", type=float, default=None,
                        help="bound on each round's (re-)initialization "
                             "after a membership change; a round whose "
                             "workers never all rendezvous restarts "
                             "(never bounds healthy training; "
                             "default 600)")
    parser.add_argument("--blacklist-cooldown-range", type=int, nargs=2,
                        default=None)
    # multi-tenant fleet (docs/fleet.md): N jobs over one shared host
    # pool; per-job commands/env live in the spec, so the ordinary
    # -np/command surface is not used
    parser.add_argument("--fleet-spec", default=None,
                        help="JSON fleet spec (inline, @/path, or a "
                             "bare path): jobs + shared host pool for "
                             "the multi-tenant fleet controller "
                             "(HOROVOD_FLEET_SPEC); see docs/fleet.md")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="Command to run on each rank.")
    args = parser.parse_args(argv)
    if args.config_file:
        parse_config_file(args.config_file, args)
    return args


def check_build():
    from ..version import __version__
    lines = [f"Horovod-TPU v{__version__}:", "",
             "Available frameworks:"]
    for name, mod in (("TensorFlow", "tensorflow"), ("PyTorch", "torch"),
                      ("JAX", "jax")):
        try:
            __import__(mod)
            lines.append(f"    [X] {name}")
        except ImportError:
            lines.append(f"    [ ] {name}")
    lines += ["", "Available controllers:", "    [X] XLA (http store)",
              "", "Available tensor operations:",
              "    [X] XLA collectives (psum/all_gather/all_to_all/"
              "psum_scatter over ICI/DCN)"]
    print("\n".join(lines))


def _run_elastic(args):
    from .elastic_run import run_elastic
    return run_elastic(args)


def _run_static(args):
    from .proc_run import launch_procs
    env = {}
    set_env_from_args(env, args)
    fusion = int((args.fusion_threshold_mb or 64) * 1024 * 1024)
    codes = launch_procs(
        args.command, np=args.np, hosts=args.hosts,
        ranks_per_proc=args.ranks_per_proc, env=env,
        platform="cpu" if args.cpu else None,
        verbose=args.verbose, fusion_threshold_bytes=fusion,
        start_timeout=args.start_timeout,
        output_filename=args.output_filename,
        # a serving fleet DEGRADES on a replica death (survivors keep
        # answering; docs/serving.md) — only training jobs collapse
        stop_on_failure=not getattr(args, "serve", False))
    return max(codes) if codes else 0


def run_commandline(argv=None):
    args = parse_args(argv)
    if args.version:
        from ..version import __version__
        print(__version__)
        return 0
    if args.check_build:
        check_build()
        return 0
    if getattr(args, "fleet_spec", None):
        # fleet launches carry their jobs' commands in the spec
        from .fleet_run import run_fleet
        return run_fleet(args)
    if not args.command:
        print("horovodrun: no command given", file=sys.stderr)
        return 2
    if args.command and args.command[0] == "--":
        args.command = args.command[1:]
    if args.hostfile:
        args.hosts = parse_host_files(args.hostfile)
    if args.np is None:
        print("horovodrun: -np is required", file=sys.stderr)
        return 2
    if args.host_discovery_script or args.min_np or args.max_np:
        return _run_elastic(args)
    return _run_static(args)


def main():
    sys.exit(run_commandline())


if __name__ == "__main__":
    main()


# -- reference launch.py surface (constants, argparse action factories,
#    controller selection) ---------------------------------------------------

CACHE_FOLDER = os.path.join(os.path.expanduser("~"), ".horovod")
CACHE_STALENESS_THRESHOLD_MINUTES = 60
SSH_ATTEMPTS = 3
SSH_CONNECT_TIMEOUT_S = 10


def is_gloo_used(use_gloo=None, use_mpi=None, use_jsrun=None):
    """Reference launch.py is_gloo_used: gloo (the store-controller
    role here) is the launcher unless MPI/jsrun was explicitly
    requested — which the TPU runtime doesn't support, so it is
    effectively always True; kept for call-site parity."""
    return bool(use_gloo) or not (use_mpi or use_jsrun)


def run_controller(use_gloo, gloo_run_fn, use_mpi, mpi_run_fn,
                   use_jsrun, js_run_fn, verbosity=0):
    """Pick and invoke the launch path (reference launch.py
    run_controller).  On TPU the gloo-role path is the only live one;
    explicit --mpi/--jsrun fall through to their run fns, which raise
    with guidance."""
    if use_mpi:
        return mpi_run_fn()
    if use_jsrun:
        return js_run_fn()
    return gloo_run_fn()


def make_override_action(override_args):
    """argparse action recording which flags the user set explicitly,
    so config-file values don't clobber them (reference launch.py
    make_override_action; consumed by
    common.util.config_parser.set_args_from_config)."""

    class StoreOverrideAction(argparse.Action):
        def __init__(self, option_strings, dest, default=None,
                     type=None, choices=None, required=False,
                     help=None, nargs=None, const=None, metavar=None):
            super().__init__(option_strings=option_strings, dest=dest,
                             default=default, type=type,
                             choices=choices, required=required,
                             help=help, nargs=nargs, const=const,
                             metavar=metavar)

        def __call__(self, parser, args, values, option_string=None):
            override_args.add(self.dest)
            setattr(args, self.dest, values)

    return StoreOverrideAction


def make_override_bool_action(override_args, bool_value):
    """Const-storing flag action (reference launch.py:185): --flag
    pairs register one action with True and its --no-flag twin with
    False, both recording the override."""

    class StoreOverrideBoolAction(argparse.Action):
        def __init__(self, option_strings, dest, required=False,
                     help=None):
            super().__init__(option_strings=option_strings, dest=dest,
                             const=bool_value, nargs=0, default=None,
                             required=required, help=help)

        def __call__(self, parser, args, values, option_string=None):
            override_args.add(self.dest)
            setattr(args, self.dest, self.const)

    return StoreOverrideBoolAction


def make_override_true_action(override_args):
    return make_override_bool_action(override_args, True)


def make_override_false_action(override_args):
    return make_override_bool_action(override_args, False)


def make_deprecated_bool_action(override_args, replacement_option):
    class DeprecatedBoolAction(argparse.Action):
        def __init__(self, option_strings, dest, **kwargs):
            kwargs.setdefault("nargs", 0)
            kwargs.pop("const", None)
            super().__init__(option_strings, dest, **kwargs)

        def __call__(self, parser, args, values, option_string=None):
            import warnings
            warnings.warn(
                f"Argument {option_string} is deprecated; use "
                f"{replacement_option} instead", DeprecationWarning)
            override_args.add(self.dest)
            setattr(args, self.dest, True)

    return DeprecatedBoolAction


def make_check_build_action(np_arg):
    class CheckBuildAction(argparse.Action):
        def __init__(self, option_strings, dest, **kwargs):
            kwargs.setdefault("nargs", 0)
            super().__init__(option_strings, dest, **kwargs)

        def __call__(self, parser, args, values, option_string=None):
            check_build()
            parser.exit()

    return CheckBuildAction


def make_nic_action(_override_args=None):
    class StoreNicAction(argparse.Action):
        def __call__(self, parser, args, values, option_string=None):
            if _override_args is not None:
                _override_args.add(self.dest)
            setattr(args, self.dest,
                    set(v.strip() for v in str(values).split(",")
                        if v.strip()))

    return StoreNicAction

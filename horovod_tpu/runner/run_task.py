"""Run-function worker entrypoint (reference
``horovod/runner/run_task.py``): fetch the pickled function from the
launcher's KV store, execute it, publish the result under this rank.
Used by ``horovod.run``'s process-per-rank function mode."""

import sys

from .common.util.env import get_env_rank_and_size
from .http.http_client import (
    put_data_into_kvstore, read_data_from_kvstore,
)


def main(addr, port):
    func = read_data_from_kvstore(addr, port, "runfunc", "func")
    try:
        ret_val = func()
    except BaseException as e:
        sys.stderr.write(f"User function raise error: {e}")
        raise
    rank, _ = get_env_rank_and_size()
    put_data_into_kvstore(addr, port, "runfunc_result", str(rank),
                          ret_val)


if __name__ == "__main__":
    _, driver_addr, run_func_server_port_str = sys.argv
    main(driver_addr, int(run_func_server_port_str))

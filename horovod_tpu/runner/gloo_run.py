"""Gloo-role launch surface (reference
``horovod/runner/gloo_run.py``).

The gloo controller's role (rendezvous + per-slot env handoff +
worker spawn) is played here by the HMAC-HTTP store controller and
``proc_run.launch_procs``; this module keeps the reference's entry
points and helpers on top of that machinery so programmatic callers
and ported tooling keep working.
"""

import os
import signal
import threading

from .hosts import SlotInfo
from .proc_run import launch_procs


class MultiFile:
    """Fan-out file object (reference gloo_run.py:53) — writes go to
    every underlying stream."""

    def __init__(self, files):
        self._files = files

    def write(self, text):
        for f in self._files:
            f.write(text)

    def flush(self):
        for f in self._files:
            f.flush()


def create_slot_env_vars(slot_info):
    """Per-slot identity env (reference gloo_run.py:66) — the same
    names proc_run.slot_env hands every worker."""
    return {
        "HOROVOD_HOSTNAME": slot_info.hostname,
        "HOROVOD_RANK": str(slot_info.rank),
        "HOROVOD_SIZE": str(slot_info.size),
        "HOROVOD_LOCAL_RANK": str(slot_info.local_rank),
        "HOROVOD_LOCAL_SIZE": str(slot_info.local_size),
        "HOROVOD_CROSS_RANK": str(slot_info.cross_rank),
        "HOROVOD_CROSS_SIZE": str(slot_info.cross_size),
    }


def create_run_env_vars(server_ip, nics, port, elastic=False):
    """Rendezvous-location env (reference gloo_run.py:203).  The gloo
    names are kept verbatim — common/env.py reads either spelling —
    plus the TPU launcher's own names."""
    env = {
        "HOROVOD_GLOO_RENDEZVOUS_ADDR": server_ip,
        "HOROVOD_GLOO_RENDEZVOUS_PORT": str(port),
        "HOROVOD_CONTROLLER": "http",
        "HOROVOD_CPU_OPERATIONS": "cpu",
        "HOROVOD_RENDEZVOUS_ADDR": server_ip,
        "HOROVOD_RENDEZVOUS_PORT": str(port),
    }
    if nics:
        env["HOROVOD_GLOO_IFACE"] = list(nics)[0]
    if elastic:
        env["HOROVOD_ELASTIC"] = "1"
    return env


def get_run_command(command, server_ip, nics, port, elastic=False):
    """``env k=v ... command`` string (reference gloo_run.py:218)."""
    env_vars = create_run_env_vars(server_ip, nics, port, elastic)
    env_string = " ".join(f"{k}={v}" for k, v in env_vars.items())
    if isinstance(command, (list, tuple)):
        command = " ".join(command)
    return f"env {env_string} {command}"


def register_shutdown_event():
    """SIGTERM -> event (reference gloo_run.py:230) so the launcher
    can tear down worker trees on job-manager termination."""
    event = threading.Event()

    def handler(signum, frame):
        event.set()

    signal.signal(signal.SIGTERM, handler)
    return event


def create_slot_env_vars_list(slots):
    return [create_slot_env_vars(s) for s in slots]


def _settings_to_kwargs(settings, env, command):
    kwargs = dict(
        command=list(command) if isinstance(command, (list, tuple))
        else [command],
        np=settings.num_proc,
        hosts=getattr(settings, "hosts", None),
        env=dict(env or os.environ),
        verbose=bool(settings.verbose),
        output_filename=settings.output_filename,
    )
    if settings.start_timeout is not None:
        remaining = getattr(settings.start_timeout, "remaining", None)
        kwargs["start_timeout"] = remaining() if callable(remaining) \
            else float(settings.start_timeout)
    return kwargs


def launch_gloo(command, exec_command, settings, nics, env, server_ip):
    """Static launch (reference gloo_run.py:242).  ``exec_command`` /
    ``nics`` / ``server_ip`` belong to the reference's ssh+gloo
    machinery; the store-controller launcher owns rendezvous and spawn
    internally, so they are accepted and unused."""
    exit_codes = launch_procs(**_settings_to_kwargs(settings, env,
                                                    command))
    failed = [(i, c) for i, c in enumerate(exit_codes) if c != 0]
    if failed:
        raise RuntimeError(
            f"Horovod detected that one or more processes exited with "
            f"non-zero status: {failed}")


def gloo_run(settings, nics, env, server_ip, command):
    """Reference gloo_run.py:295."""
    launch_gloo(command, None, settings, nics, env, server_ip)


def launch_gloo_elastic(command_or_func, exec_command, settings, env,
                        get_common_interfaces, rendezvous,
                        executable=None):
    """Elastic launch (reference gloo_run.py:303) — delegates to the
    elastic driver + KV rendezvous (runner/elastic_run.py)."""
    from argparse import Namespace

    from .elastic_run import run_elastic

    discovery = getattr(settings, "discovery", None)
    args = Namespace(
        np=settings.num_proc,
        min_np=getattr(settings, "min_num_proc", None),
        max_np=getattr(settings, "max_num_proc", None),
        hosts=getattr(settings, "hosts", None),
        discovery=discovery if not isinstance(discovery, str)
        else None,
        host_discovery_script=discovery
        if isinstance(discovery, str)
        else getattr(settings, "discovery_script", None),
        slots_per_host=getattr(settings, "slots", None),
        blacklist_cooldown_range=getattr(settings, "cooldown_range",
                                         None),
        command=command_or_func
        if isinstance(command_or_func, (list, tuple))
        else [command_or_func],
        verbose=bool(settings.verbose),
        start_timeout=None,
        output_filename=settings.output_filename,
        reset_limit=getattr(settings, "reset_limit", None),
        elastic_timeout=getattr(settings, "elastic_timeout", None)
        or 600,
        cpu=False,
        ranks_per_worker=1,
        extra_env=dict(env) if env else None,
    )
    return run_elastic(args)


def gloo_run_elastic(settings, env, command_or_func, executable=None):
    """Reference gloo_run.py:370."""
    return launch_gloo_elastic(command_or_func, None, settings, env,
                               None, None, executable)

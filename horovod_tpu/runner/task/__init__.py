"""Reference package path ``horovod.runner.task``."""

"""horovodrun's per-host task service (reference
``horovod/runner/task/task_service.py``) — the BasicTaskService plus
the task-to-task address-check handshake the NIC probe uses.  TPU pods
have a single fabric so the launcher never runs the probe
(SURVEY §7.4), but the service is fully functional for tooling that
drives it."""

from ..common.service import task_service


class TaskToTaskAddressCheckFinishedSignal:
    def __init__(self, index):
        self.index = index


class TaskToTaskAddressCheckFinishedSignalResponse:
    def __init__(self, index):
        self.index = index


class HorovodRunTaskService(task_service.BasicTaskService):
    NAME_FORMAT = "horovod task service #%d"

    def __init__(self, index, key, nics=None):
        super().__init__(HorovodRunTaskService.NAME_FORMAT % index,
                         index, key, nics)
        self.index = index
        self._task_to_task_address_check_completed = False

    def _handle(self, req, client_address):
        if isinstance(req, TaskToTaskAddressCheckFinishedSignal):
            with self._wait_cond:
                self._task_to_task_address_check_completed = True
                self._wait_cond.notify_all()
            return TaskToTaskAddressCheckFinishedSignalResponse(
                self.index)
        return super()._handle(req, client_address)

    def wait_for_task_to_task_address_check_finish_signal(self,
                                                          timeout):
        with self._wait_cond:
            while not self._task_to_task_address_check_completed:
                self._wait_cond.wait(timeout.remaining())
                timeout.check_time_out_for("Task to task address check")


class HorovodRunTaskClient(task_service.BasicTaskClient):
    def __init__(self, index, task_addresses, key, verbose=0,
                 match_intf=False, attempts=3):
        super().__init__(HorovodRunTaskService.NAME_FORMAT % index,
                         task_addresses, key, verbose,
                         match_intf=match_intf, attempts=attempts)
        self.index = index

    def task_to_task_address_check_completed(self):
        resp = self._send(TaskToTaskAddressCheckFinishedSignal(
            self.index))
        return resp.index

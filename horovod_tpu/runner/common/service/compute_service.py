"""Compute-cluster coordination service (reference
``horovod/runner/common/service/compute_service.py``).

Synchronizes data-service dispatchers and their workers with the
training job: dispatchers register their addresses, workers register
per dispatcher, trainers wait for registration, and anyone can
initiate/await shutdown.  The TPU-native data path
(``horovod_tpu.data.service``) carries the batches; this service is
the registration/shutdown control plane in reference shape.
"""

import threading

from ..util import network
from ..util.timeout import TimeoutException


class RegisterDispatcherRequest:
    def __init__(self, dispatcher_id, dispatcher_address):
        self.dispatcher_id = dispatcher_id
        self.dispatcher_address = dispatcher_address


class WaitForDispatcherRegistrationRequest:
    def __init__(self, dispatcher_id, timeout):
        self.dispatcher_id = dispatcher_id
        self.timeout = timeout


class WaitForDispatcherRegistrationResponse:
    def __init__(self, dispatcher_address):
        self.dispatcher_address = dispatcher_address


class RegisterDispatcherWorkerRequest:
    def __init__(self, dispatcher_id, worker_id):
        self.dispatcher_id = dispatcher_id
        self.worker_id = worker_id


class WaitForDispatcherWorkerRegistrationRequest:
    def __init__(self, dispatcher_id, timeout):
        self.dispatcher_id = dispatcher_id
        self.timeout = timeout


class ShutdownRequest:
    pass


class WaitForShutdownRequest:
    pass


class ComputeService(network.BasicService):
    NAME = "Compute service"

    def __init__(self, dispatchers, workers_per_dispatcher, key,
                 nics=None):
        if dispatchers <= 0:
            raise ValueError(
                f"The number of dispatchers must be larger than 0: "
                f"{dispatchers}")
        if workers_per_dispatcher <= 0:
            raise ValueError(
                f"The number of workers per dispatcher must be larger "
                f"than 0: {workers_per_dispatcher}")
        self._max_dispatcher_id = dispatchers - 1
        self._dispatcher_addresses = [None] * dispatchers
        self._workers_per_dispatcher = workers_per_dispatcher
        self._dispatcher_worker_ids = [set() for _ in
                                       range(dispatchers)]
        self._shutdown = False
        self._wait_cond = threading.Condition()
        super().__init__(ComputeService.NAME, key, nics)

    def _check_dispatcher(self, dispatcher_id):
        if not 0 <= dispatcher_id <= self._max_dispatcher_id:
            return IndexError(
                f"Dispatcher id must be within "
                f"[0..{self._max_dispatcher_id}]: {dispatcher_id}")
        return None

    def _handle(self, req, client_address):
        if isinstance(req, RegisterDispatcherRequest):
            with self._wait_cond:
                err = self._check_dispatcher(req.dispatcher_id)
                if err is not None:
                    return err
                current = self._dispatcher_addresses[req.dispatcher_id]
                if current is not None and \
                        current != req.dispatcher_address:
                    return ValueError(
                        f"Dispatcher with id {req.dispatcher_id} has "
                        f"already been registered under different "
                        f"address {current}: {req.dispatcher_address}")
                self._dispatcher_addresses[req.dispatcher_id] = \
                    req.dispatcher_address
                self._wait_cond.notify_all()
            return network.AckResponse()

        if isinstance(req, WaitForDispatcherRegistrationRequest):
            with self._wait_cond:
                err = self._check_dispatcher(req.dispatcher_id)
                if err is not None:
                    return err
                if not self._wait_cond.wait_for(
                        lambda: self._dispatcher_addresses[
                            req.dispatcher_id] is not None,
                        timeout=req.timeout):
                    return TimeoutException(
                        f"Timed out waiting for dispatcher "
                        f"{req.dispatcher_id} to register. Try to "
                        f"find out what takes the dispatcher so long "
                        f"to register or increase timeout. Timeout "
                        f"after {req.timeout} seconds.")
                return WaitForDispatcherRegistrationResponse(
                    self._dispatcher_addresses[req.dispatcher_id])

        if isinstance(req, RegisterDispatcherWorkerRequest):
            with self._wait_cond:
                err = self._check_dispatcher(req.dispatcher_id)
                if err is not None:
                    return err
                self._dispatcher_worker_ids[req.dispatcher_id].add(
                    req.worker_id)
                self._wait_cond.notify_all()
            return network.AckResponse()

        if isinstance(req, WaitForDispatcherWorkerRegistrationRequest):
            with self._wait_cond:
                err = self._check_dispatcher(req.dispatcher_id)
                if err is not None:
                    return err
                if not self._wait_cond.wait_for(
                        lambda: len(self._dispatcher_worker_ids[
                            req.dispatcher_id]) >=
                        self._workers_per_dispatcher,
                        timeout=req.timeout):
                    return TimeoutException(
                        f"Timed out waiting for workers for "
                        f"dispatcher {req.dispatcher_id} to register. "
                        f"Try to find out what takes the workers so "
                        f"long to register or increase timeout. "
                        f"Timeout after {req.timeout} seconds.")
            return network.AckResponse()

        if isinstance(req, ShutdownRequest):
            with self._wait_cond:
                self._shutdown = True
                self._wait_cond.notify_all()
            return network.AckResponse()

        if isinstance(req, WaitForShutdownRequest):
            with self._wait_cond:
                self._wait_cond.wait_for(lambda: self._shutdown)
            return network.AckResponse()

        return super()._handle(req, client_address)

    def shutdown(self):
        # wake parked WaitForShutdown handlers BEFORE draining the
        # server: block_on_close joins handler threads, and a handler
        # waiting on the condition would deadlock the teardown
        # (reference compute_service.py shutdown() sets the flag too)
        with self._wait_cond:
            self._shutdown = True
            self._wait_cond.notify_all()
        super().shutdown()


class ComputeClient(network.BasicClient):
    def __init__(self, addresses, key, verbose=0):
        super().__init__(ComputeService.NAME, addresses, key, verbose)

    def _send_checked(self, req):
        resp = self._send(req)
        if isinstance(resp, Exception):
            raise resp
        return resp

    def register_dispatcher(self, dispatcher_id, dispatcher_address):
        self._send_checked(RegisterDispatcherRequest(
            dispatcher_id, dispatcher_address))

    def wait_for_dispatcher_registration(self, dispatcher_id,
                                         timeout=60):
        return self._send_checked(WaitForDispatcherRegistrationRequest(
            dispatcher_id, timeout)).dispatcher_address

    def register_worker_for_dispatcher(self, dispatcher_id, worker_id):
        self._send_checked(RegisterDispatcherWorkerRequest(
            dispatcher_id, worker_id))

    def wait_for_dispatcher_worker_registration(self, dispatcher_id,
                                                timeout=60):
        self._send_checked(WaitForDispatcherWorkerRegistrationRequest(
            dispatcher_id, timeout))

    def shutdown(self):
        self._send_checked(ShutdownRequest())

    def wait_for_shutdown(self):
        self._send_checked(WaitForShutdownRequest())

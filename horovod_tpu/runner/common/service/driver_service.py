"""Task-registration driver service (reference
``horovod/runner/common/service/driver_service.py``).

Tasks dial the driver, register their service addresses and host
hash; the driver groups tasks by host and answers address queries.
The TPU launcher's own registration rides the HMAC-HTTP KV store
(spark/runner.py register→plan flow) — this service is the
reference-shaped surface for tooling built on the TCP framework.
"""

import threading

from ..util import network


class RegisterTaskRequest:
    def __init__(self, index, task_addresses, host_hash):
        self.index = index
        self.task_addresses = task_addresses
        self.host_hash = host_hash


class RegisterTaskToTaskAddressesRequest:
    def __init__(self, index, task_addresses):
        self.index = index
        self.task_addresses = task_addresses


class AllTaskAddressesRequest:
    def __init__(self, index):
        self.index = index


class AllTaskAddressesResponse:
    def __init__(self, all_task_addresses):
        self.all_task_addresses = all_task_addresses


class BasicDriverService(network.BasicService):
    def __init__(self, num_proc, name, key, nics=None):
        super().__init__(name, key, nics)
        self._num_proc = num_proc
        self._all_task_addresses = {}
        self._task_addresses_for_driver = {}
        self._task_addresses_for_tasks = {}
        self._task_index_host_hash = {}
        self._task_host_hash_indices = {}
        self._wait_cond = threading.Condition()

    def _handle(self, req, client_address):
        if isinstance(req, RegisterTaskRequest):
            with self._wait_cond:
                assert 0 <= req.index < self._num_proc
                self._all_task_addresses[req.index] = req.task_addresses
                self._task_addresses_for_driver[req.index] = \
                    self._filter_by_ip(req.task_addresses,
                                       client_address[0])
                earlier = self._task_index_host_hash.get(req.index)
                if earlier is not None and earlier != req.host_hash:
                    self._task_host_hash_indices[earlier].remove(
                        req.index)
                self._task_index_host_hash[req.index] = req.host_hash
                indices = self._task_host_hash_indices.setdefault(
                    req.host_hash, [])
                if req.index not in indices:
                    indices.append(req.index)
                    indices.sort()
                self._wait_cond.notify_all()
            return network.AckResponse()

        if isinstance(req, RegisterTaskToTaskAddressesRequest):
            self.register_task_to_task_addresses(req.index,
                                                 req.task_addresses)
            return network.AckResponse()

        if isinstance(req, AllTaskAddressesRequest):
            return AllTaskAddressesResponse(
                self._all_task_addresses[req.index])

        return super()._handle(req, client_address)

    def _filter_by_ip(self, addresses, target_ip):
        for intf, intf_addresses in addresses.items():
            for ip, port in intf_addresses:
                if ip == target_ip:
                    return {intf: [(ip, port)]}
        # target behind NAT: fall back to everything it declared so the
        # client probe decides, instead of guaranteeing failure
        return dict(addresses)

    def all_task_addresses(self, index):
        with self._wait_cond:
            return dict(self._all_task_addresses[index])

    def task_addresses_for_driver(self, index):
        with self._wait_cond:
            return dict(self._task_addresses_for_driver[index])

    def task_addresses_for_tasks(self, index):
        with self._wait_cond:
            return dict(self._task_addresses_for_tasks[index])

    def register_task_to_task_addresses(self, index, task_addresses):
        with self._wait_cond:
            assert 0 <= index < self._num_proc
            self._task_addresses_for_tasks[index] = task_addresses
            self._wait_cond.notify_all()

    def task_indices(self):
        with self._wait_cond:
            return list(self._task_index_host_hash.keys())

    def task_host_hash_indices(self):
        with self._wait_cond:
            return dict(self._task_host_hash_indices)

    def task_index_host_hash(self, index):
        with self._wait_cond:
            return self._task_index_host_hash[index]

    def wait_for_initial_registration(self, timeout):
        with self._wait_cond:
            while len(self._all_task_addresses) < self._num_proc:
                self._wait_cond.wait(timeout.remaining())
                timeout.check_time_out_for("tasks to start")

    def wait_for_task_to_task_address_updates(self, timeout):
        with self._wait_cond:
            while len(self._task_addresses_for_tasks) < self._num_proc:
                self._wait_cond.wait(timeout.remaining())
                timeout.check_time_out_for(
                    "tasks to update task-to-task addresses")


class BasicDriverClient(network.BasicClient):
    def __init__(self, name, driver_addresses, key, verbose=0,
                 match_intf=False):
        super().__init__(name, driver_addresses, key, verbose,
                         match_intf=match_intf)

    def register_task(self, index, task_addresses, host_hash):
        self._send(RegisterTaskRequest(index, task_addresses,
                                       host_hash))

    def all_task_addresses(self, index):
        return self._send(
            AllTaskAddressesRequest(index)).all_task_addresses

    def register_task_to_task_addresses(self, index, task_addresses):
        self._send(RegisterTaskToTaskAddressesRequest(index,
                                                      task_addresses))

"""Per-task command-execution service (reference
``horovod/runner/common/service/task_service.py``).

A task service runs on each allocated host/slot; the driver sends it
exactly one command to execute (idempotent — re-sends are ignored),
can stream the command's captured stdout/stderr, poll or block on the
exit code, and abort the process tree.  The spark/ray integration
layers drive remote workers through this protocol.
"""

import struct
import threading

from ..util import network, safe_shell_exec
from ..util.timeout import Timeout
from ...util.streams import Pipe
from ...util.threads import in_thread

WAIT_FOR_COMMAND_MIN_DELAY = 0.1


class RunCommandRequest:
    def __init__(self, command, env, capture_stdout=False,
                 capture_stderr=False,
                 prefix_output_with_timestamp=False):
        self.command = command
        self.env = env
        self.capture_stdout = capture_stdout
        self.capture_stderr = capture_stderr
        self.prefix_output_with_timestamp = prefix_output_with_timestamp


class StreamCommandOutputRequest:
    pass


class StreamCommandStdOutRequest(StreamCommandOutputRequest):
    pass


class StreamCommandStdErrRequest(StreamCommandOutputRequest):
    pass


class CommandOutputNotCaptured(Exception):
    pass


class AbortCommandRequest:
    pass


class CommandExitCodeRequest:
    pass


class CommandExitCodeResponse:
    def __init__(self, terminated, exit_code):
        self.terminated = terminated
        self.exit_code = exit_code


class WaitForCommandExitCodeRequest:
    def __init__(self, delay):
        self.delay = delay


class WaitForCommandExitCodeResponse:
    def __init__(self, exit_code):
        self.exit_code = exit_code


class NotifyInitialRegistrationCompleteRequest:
    pass


class RegisterCodeResultRequest:
    def __init__(self, result):
        self.result = result


class BasicTaskService(network.BasicService):
    def __init__(self, name, index, key, nics=None, command_env=None,
                 verbose=0):
        self._initial_registration_complete = False
        self._wait_cond = threading.Condition()
        self._service_shutdown = False
        self._index = index
        self._command_env = command_env
        self._command_thread = None
        self._command_abort = None
        self._command_stdout = None
        self._command_stderr = None
        self._command_exit_code = None
        self._fn_result = None
        self._verbose = verbose
        super().__init__(name, key, nics)

    def _run_command(self, command, env, event, stdout, stderr,
                     prefix_output_with_timestamp=False):
        self._command_exit_code = safe_shell_exec.execute(
            command, env=env, stdout=stdout, stderr=stderr,
            index=self._index, events=[event],
            prefix_output_with_timestamp=prefix_output_with_timestamp)
        with self._wait_cond:
            if stdout:
                stdout.close()
            if stderr:
                stderr.close()
            self._wait_cond.notify_all()

    def _handle(self, req, client_address):
        if isinstance(req, RunCommandRequest):
            with self._wait_cond:
                if self._command_thread is None:
                    env = dict(self._command_env or {})
                    for k, v in (req.env or {}).items():
                        if v is None:
                            env.pop(k, None)
                        else:
                            env[k] = v
                    self._command_abort = threading.Event()
                    self._command_stdout = \
                        Pipe() if req.capture_stdout else None
                    self._command_stderr = \
                        Pipe() if req.capture_stderr else None
                    self._command_thread = in_thread(
                        self._run_command,
                        (req.command, env, self._command_abort,
                         self._command_stdout, self._command_stderr,
                         req.prefix_output_with_timestamp))
                self._wait_cond.notify_all()
            return network.AckResponse()

        if isinstance(req, StreamCommandOutputRequest):
            self.wait_for_command_start()
            if self._command_thread is None:
                # service shutting down before any command started
                return CommandOutputNotCaptured()
            stream = self._command_stdout \
                if isinstance(req, StreamCommandStdOutRequest) \
                else self._command_stderr
            if stream is None:
                return CommandOutputNotCaptured()
            return network.AckStreamResponse(), stream

        if isinstance(req, AbortCommandRequest):
            with self._wait_cond:
                if self._command_thread is not None:
                    self._command_abort.set()
                for stream in (self._command_stdout,
                               self._command_stderr):
                    if stream is not None:
                        stream.close()
            return network.AckResponse()

        if isinstance(req, NotifyInitialRegistrationCompleteRequest):
            with self._wait_cond:
                self._initial_registration_complete = True
                self._wait_cond.notify_all()
            return network.AckResponse()

        if isinstance(req, CommandExitCodeRequest):
            with self._wait_cond:
                terminated = (self._command_thread is not None and
                              not self._command_thread.is_alive())
                return CommandExitCodeResponse(
                    terminated,
                    self._command_exit_code if terminated else None)

        if isinstance(req, WaitForCommandExitCodeRequest):
            with self._wait_cond:
                # a RUNNING command is waited out even through
                # shutdown (the draining contract,
                # test_service.py:143: the caller gets the real exit
                # code); only a never-started command releases on
                # shutdown so teardown cannot hang forever
                while (self._command_thread is None
                       and not self._service_shutdown) or \
                        (self._command_thread is not None
                         and self._command_thread.is_alive()):
                    self._wait_cond.wait(
                        max(req.delay, WAIT_FOR_COMMAND_MIN_DELAY))
                return WaitForCommandExitCodeResponse(
                    self._command_exit_code)

        if isinstance(req, RegisterCodeResultRequest):
            self._fn_result = req.result
            return network.AckResponse()

        return super()._handle(req, client_address)

    # -- driver-side accessors (same object when in-process) ------------------

    def fn_result(self):
        return self._fn_result

    def wait_for_initial_registration(self, timeout=None):
        with self._wait_cond:
            while not self._initial_registration_complete:
                if timeout:
                    self._wait_cond.wait(timeout.remaining())
                    timeout.check_time_out_for("tasks to start")
                else:
                    self._wait_cond.wait()

    def wait_for_command_start(self, timeout=None):
        with self._wait_cond:
            while self._command_thread is None and \
                    not self._service_shutdown:
                if timeout:
                    self._wait_cond.wait(timeout.remaining())
                    timeout.check_time_out_for("command to run")
                else:
                    self._wait_cond.wait()

    def check_for_command_start(self, seconds):
        with self._wait_cond:
            tmout = Timeout(seconds, "Timed out waiting for {activity}")
            while self._command_thread is None:
                remaining = tmout.remaining()
                if remaining == 0:
                    return False
                self._wait_cond.wait(remaining)
            return True

    def wait_for_command_termination(self):
        self._command_thread.join()

    def shutdown(self):
        # wake every parked waiter (command-start, exit-code) before
        # the draining server joins handler threads; in-flight command
        # handlers still finish (test_service.py:143 contract) —
        # running commands are not aborted, only waits are released
        with self._wait_cond:
            self._service_shutdown = True
            self._wait_cond.notify_all()
        super().shutdown()

    def command_exit_code(self):
        return self._command_exit_code


class BasicTaskClient(network.BasicClient):
    def __init__(self, service_name, task_addresses, key, verbose=0,
                 match_intf=False, attempts=3):
        super().__init__(service_name, task_addresses, key, verbose,
                         match_intf=match_intf, attempts=attempts)

    def run_command(self, command, env, capture_stdout=False,
                    capture_stderr=False,
                    prefix_output_with_timestamp=False):
        self._send(RunCommandRequest(command, env, capture_stdout,
                                     capture_stderr,
                                     prefix_output_with_timestamp))

    def stream_command_output(self, stdout=None, stderr=None):
        def send(req, stream):
            # a broken client-side stream (or dropped connection)
            # re-requests the stream and resumes from the live pipe —
            # some lines are lost, the command keeps running
            # (reference test_task_service.py reconnect contract);
            # only after the attempt budget does it abort the command
            for attempt in range(self._attempts):
                try:
                    self._send(req, stream=stream)
                    return
                except (OSError, EOFError, struct.error) as exc:
                    # connection-level failure: _send already burned
                    # its own retry budget — don't square it
                    try:
                        self.abort_command()
                    finally:
                        raise exc
                except Exception:
                    # mid-stream failure (e.g. the caller's stream
                    # object raised): re-request and resume from the
                    # live pipe, losing some lines
                    if attempt == self._attempts - 1:
                        try:
                            self.abort_command()
                        finally:
                            raise

        return (in_thread(send, (StreamCommandStdOutRequest(), stdout))
                if stdout else None,
                in_thread(send, (StreamCommandStdErrRequest(), stderr))
                if stderr else None)

    def abort_command(self):
        self._send(AbortCommandRequest())

    def notify_initial_registration_complete(self):
        self._send(NotifyInitialRegistrationCompleteRequest())

    def command_result(self):
        resp = self._send(CommandExitCodeRequest())
        return resp.terminated, resp.exit_code

    def wait_for_command_exit_code(self, delay=1.0):
        return self._send(
            WaitForCommandExitCodeRequest(delay)).exit_code

    def register_code_result(self, result):
        self._send(RegisterCodeResultRequest(result))

    def wait_for_command_termination(self, delay=1.0):
        while True:
            terminated, _ = self.command_result()
            if terminated:
                return
            import time
            time.sleep(delay)

"""Reference package path ``horovod.runner.common.service``."""

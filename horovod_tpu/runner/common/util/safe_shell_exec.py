"""Process-tree-safe shell execution (reference
``horovod/runner/common/util/safe_shell_exec.py``).

``execute`` runs a shell command in its own session (process group) so
termination reaps the whole tree — the property the launcher depends
on when one worker's death must take down the others (proc_run.py
ProcessPool uses the same discipline for worker processes).
"""

import datetime
import os
import signal
import subprocess
import sys
import threading
import time

GRACEFUL_TERMINATION_TIME_S = 5


def terminate_executor_shell_and_children(pid):
    """SIGTERM the process group of ``pid``, escalate to SIGKILL after
    GRACEFUL_TERMINATION_TIME_S (reference safe_shell_exec.py:33)."""
    try:
        pgid = os.getpgid(pid)
    except OSError:
        return
    try:
        os.killpg(pgid, signal.SIGTERM)
    except OSError:
        return
    deadline = time.monotonic() + GRACEFUL_TERMINATION_TIME_S
    while time.monotonic() < deadline:
        try:
            # group leader still alive?
            os.kill(pid, 0)
        except OSError:
            return
        time.sleep(0.1)
    try:
        os.killpg(pgid, signal.SIGKILL)
    except OSError:
        pass


def prefix_connection(src, dst_stream, prefix, index,
                      prefix_output_with_timestamp):
    """Copy lines from file object ``src`` to ``dst_stream``, prefixed
    ``[index]<prefix>`` and optionally timestamped (reference
    safe_shell_exec.py:83 — the driver's per-rank output labelling,
    also available via the launcher's --output-filename capture)."""
    for line in iter(src.readline, b""):
        text = line.decode("utf-8", errors="replace")
        tag = f"[{index}]<{prefix}>" if index is not None else ""
        if prefix_output_with_timestamp:
            tag = datetime.datetime.now().isoformat() + tag
        dst_stream.write(f"{tag}:{text}" if tag else text)
        dst_stream.flush()


def execute(command, env=None, stdout=None, stderr=None, index=None,
            events=None, prefix_output_with_timestamp=False):
    """Run ``command`` in a shell; returns the exit code.  ``events``
    (threading.Event objects) trigger tree termination when set
    (reference safe_shell_exec.py:188)."""
    capture = stdout is not None or stderr is not None or \
        prefix_output_with_timestamp or index is not None
    proc = subprocess.Popen(
        command, shell=True, env=env,
        stdout=subprocess.PIPE if capture else None,
        stderr=subprocess.PIPE if capture else None,
        start_new_session=True)

    pumps = []
    if capture:
        for src, dst, name in ((proc.stdout, stdout or sys.stdout,
                                "stdout"),
                               (proc.stderr, stderr or sys.stderr,
                                "stderr")):
            t = threading.Thread(
                target=prefix_connection,
                args=(src, dst, name, index,
                      prefix_output_with_timestamp),
                daemon=True)
            t.start()
            pumps.append(t)

    stop_watch = threading.Event()
    watchers = []
    for event in events or []:
        def _watch(ev=event):
            while not stop_watch.is_set():
                if ev.wait(0.1):
                    terminate_executor_shell_and_children(proc.pid)
                    return
        t = threading.Thread(target=_watch, daemon=True)
        t.start()
        watchers.append(t)

    try:
        proc.wait()
    finally:
        stop_watch.set()
        for t in pumps:
            t.join(timeout=2)
    return proc.returncode

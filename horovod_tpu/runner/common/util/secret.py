"""Shared-secret helpers (reference
``horovod/runner/common/util/secret.py``): every control-plane message
in this build is HMAC-signed with a per-job key, the same policy the
reference applies to its network services."""

import hmac
import hashlib
import secrets as _secrets

SECRET_LENGTH = 32
DIGEST_LENGTH = 32
HOROVOD_SECRET_KEY = "HOROVOD_SECRET_KEY"


def make_secret_key():
    return _secrets.token_bytes(SECRET_LENGTH)


def compute_digest(key, message):
    return hmac.new(key, message, hashlib.sha256).digest()


def check_digest(key, message, digest):
    return hmac.compare_digest(compute_digest(key, message), digest)

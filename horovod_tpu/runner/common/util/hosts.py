"""Reference import path ``horovod.runner.common.util.hosts`` — the
host/slot allocation lives in ``horovod_tpu.runner.hosts``; this module
adds the reference's remaining helpers on top of it."""

from ...hosts import (  # noqa: F401
    HostInfo, SlotInfo, parse_hosts, parse_host_files,
)
from ...hosts import get_host_assignments as _assign

INVALID_SLOT_INFO = SlotInfo(hostname="", rank=-1, local_rank=-1,
                             local_size=-1, cross_rank=-1,
                             cross_size=-1, size=-1)


def parse_hosts_and_slots(hosts):
    """``h1:2,h2:4`` -> ``([h1, h2], {h1: 2, h2: 4})`` (reference
    hosts.py:71)."""
    infos = parse_hosts(hosts)
    return [h.hostname for h in infos], \
        {h.hostname: h.slots for h in infos}


def get_host_assignments(hosts, min_num_proc, max_num_proc=None):
    """Reference hosts.py:100 — allocate as many slots as available,
    bounded by ``max_num_proc``, failing below ``min_num_proc`` (the
    elastic form of the static allocator)."""
    # static call: one argument means exactly that many slots
    if max_num_proc is None:
        return _assign(hosts, min_num_proc)
    total = sum(h.slots for h in hosts)
    np = min(total, max_num_proc)
    if np < min_num_proc:
        raise ValueError(
            f"Requested at least {min_num_proc} processes but only "
            f"{total} slots are available across {len(hosts)} hosts")
    return _assign(hosts, np)

"""Host identity hash (reference
``horovod/runner/common/util/host_hash.py``): short hostname plus a
digest of the full hostname + namespace links, so two containers on
one machine hash differently.  Used by the spark/elastic layers to
group ranks by physical host."""

import hashlib
import os
import socket

NAMESPACE_PATH = "/proc/self/ns"


def _namespaces():
    if not os.path.exists(NAMESPACE_PATH):
        return ""
    links = []
    for entry in sorted(os.listdir(NAMESPACE_PATH)):
        try:
            links.append(os.readlink(os.path.join(NAMESPACE_PATH, entry)))
        except OSError:
            continue
    return " ".join(links)


def host_hash(salt=None):
    hostname = socket.gethostname()
    host = hostname.split(".")[0]
    host_info = f"{hostname}-{_namespaces()}"
    if salt:
        host_info = f"{host_info}-{salt}"
    digest = hashlib.md5(host_info.encode("ascii",
                                          errors="replace")).hexdigest()
    return f"{host}-{digest}"

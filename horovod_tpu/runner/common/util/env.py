"""Environment-handoff helpers (reference
``horovod/runner/common/util/env.py``).  The launcher hands workers a
filtered copy of its environment (proc_run.py carries the same
``HOROVOD_*`` contract); these predicates decide what crosses."""

import os
import re

from . import secret

LOG_LEVEL_STR = ["FATAL", "ERROR", "WARNING", "INFO", "DEBUG", "TRACE"]

IGNORE_REGEXES = {"BASH_FUNC_.*", "OLDPWD", secret.HOROVOD_SECRET_KEY}

KUBEFLOW_MPI_EXEC = "/etc/mpi/kubexec.sh"


def is_exportable(v):
    return not any(re.match(r, v) for r in IGNORE_REGEXES)


def get_env_rank_and_size():
    """Rank/size of this process from whichever launcher env contract
    is present (reference env.py:33).  TPU-native jobs publish
    HOROVOD_RANK/HOROVOD_SIZE; the MPI/PMI names are honored for
    scripts arriving from other launchers."""
    rank_env = ["HOROVOD_RANK", "OMPI_COMM_WORLD_RANK", "PMI_RANK"]
    size_env = ["HOROVOD_SIZE", "OMPI_COMM_WORLD_SIZE", "PMI_SIZE"]
    for rank_var, size_var in zip(rank_env, size_env):
        rank = os.environ.get(rank_var)
        size = os.environ.get(size_var)
        if rank is not None and size is not None:
            return int(rank), int(size)
        if rank is not None or size is not None:
            raise RuntimeError(
                f"Could not determine process rank and size: only one "
                f"of {rank_var} and {size_var} found in environment")
    return 0, 1


def is_kubeflow_mpi():
    return os.environ.get("OMPI_MCA_plm_rsh_agent") == KUBEFLOW_MPI_EXEC

"""Reference import path
``horovod.runner.common.util.config_parser`` — the live implementation
is ``horovod_tpu.runner.config_parser``; this module re-exports it and
carries the reference's full env-name constant set (including the
NCCL/MPI-era names, which the TPU runtime accepts and ignores so
ported config files parse cleanly)."""

from ...config_parser import (  # noqa: F401
    HOROVOD_AUTOTUNE,
    HOROVOD_AUTOTUNE_LOG,
    HOROVOD_CACHE_CAPACITY,
    HOROVOD_CYCLE_TIME,
    HOROVOD_FUSION_THRESHOLD,
    HOROVOD_HIERARCHICAL_ALLREDUCE,
    HOROVOD_LOG_LEVEL,
    HOROVOD_STALL_CHECK_DISABLE,
    HOROVOD_STALL_CHECK_TIME_SECONDS,
    HOROVOD_STALL_SHUTDOWN_TIME_SECONDS,
    HOROVOD_TIMELINE,
    HOROVOD_TIMELINE_MARK_CYCLES,
    HOROVOD_TORUS_ALLREDUCE,
    parse_config_file,
    set_env_from_args,
)
from .env import LOG_LEVEL_STR as LOG_LEVELS  # noqa: F401

# autotune sampling knobs (live: core/autotune.py reads these)
HOROVOD_AUTOTUNE_WARMUP_SAMPLES = "HOROVOD_AUTOTUNE_WARMUP_SAMPLES"
HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE = "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE"
HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES = \
    "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES"
HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE = \
    "HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE"

# reference names with no TPU-side effect (accepted for config-file
# compatibility; the comm stack has no NCCL/MPI/gloo data plane).
# HOROVOD_HIERARCHICAL_ALLREDUCE / HOROVOD_TORUS_ALLREDUCE are LIVE
# (re-exported above): they pick the topology-aware reduction
# algorithm (common/env.py, core/engine._algo_plan).
HOROVOD_GLOO_TIMEOUT_SECONDS = "HOROVOD_GLOO_TIMEOUT_SECONDS"
HOROVOD_HIERARCHICAL_ALLGATHER = "HOROVOD_HIERARCHICAL_ALLGATHER"
HOROVOD_MPI_THREADS_DISABLE = "HOROVOD_MPI_THREADS_DISABLE"
HOROVOD_NUM_NCCL_STREAMS = "HOROVOD_NUM_NCCL_STREAMS"
HOROVOD_THREAD_AFFINITY = "HOROVOD_THREAD_AFFINITY"
HOROVOD_LOG_HIDE_TIME = "HOROVOD_LOG_HIDE_TIME"
NCCL_IB_DISABLE = "NCCL_IB_DISABLE"


def set_args_from_config(args, config, override_args):
    """Apply a parsed config dict onto the args namespace, skipping
    names the user overrode on the CLI (reference config_parser.py
    set_args_from_config)."""
    for key, value in (config or {}).items():
        attr = key.replace("-", "_")
        if attr in (override_args or set()):
            continue
        if hasattr(args, attr):
            setattr(args, attr, value)
    return args


def validate_config_args(args):
    """Reference config_parser.py validate_config_args — range checks
    on the tunables."""
    fusion = getattr(args, "fusion_threshold_mb", None)
    if fusion is not None and fusion < 0:
        raise ValueError("--fusion-threshold-mb must be >= 0")
    cycle = getattr(args, "cycle_time_ms", None)
    if cycle is not None and cycle <= 0:
        raise ValueError("--cycle-time-ms must be > 0")
    cache = getattr(args, "cache_capacity", None)
    if cache is not None and cache < 0:
        raise ValueError("--cache-capacity must be >= 0")

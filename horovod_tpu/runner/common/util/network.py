"""Pickled-message TCP service framework (reference
``horovod/runner/common/util/network.py``).

Request/response objects travel HMAC-signed over a TCP stream:
``digest (32B) | length (4B) | pickle body``.  ``BasicService``
dispatches typed requests in ``_handle``; ``BasicClient`` probes the
service's advertised addresses with a ping and uses whichever
responds.  The launcher's own control plane is the HMAC-HTTP KV store
(runner/http/) — this framework exists for the reference surfaces
built directly on it (driver/task/compute services, ray NIC probe) and
is fully functional.

All RPCs must be idempotent: the client retries on connection failure.
"""

import pickle
import queue
import shutil
import socket
import socketserver
import struct

from . import secret
from ...util.network import find_port, get_local_host_addresses
from ...util.threads import in_thread


class PingRequest:
    pass


def _intf_ipv4_addresses(intf):
    """IPv4 addresses bound to a REAL interface name, or None when the
    name does not resolve to a NIC on this host (pseudo keys like this
    framework's 'all' advertisement, or a platform without the
    ioctl).  stdlib-only (no psutil in this image): SIOCGIFADDR."""
    try:
        names = {name for _, name in socket.if_nameindex()}
    except OSError:
        return None
    if intf not in names:
        return None
    try:
        import fcntl

        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            packed = fcntl.ioctl(
                s.fileno(), 0x8915,        # SIOCGIFADDR
                struct.pack("256s", intf.encode()[:15]))
            return {socket.inet_ntoa(packed[20:24])}
        finally:
            s.close()
    except (OSError, ImportError):
        return None


class NoValidAddressesFound(Exception):
    pass


class PingResponse:
    def __init__(self, service_name, source_address):
        self.service_name = service_name
        self.source_address = source_address


class AckResponse:
    """Response carrying no data."""


class AckStreamResponse:
    """Marker: a utf8 text stream follows the response."""


class Wire:
    """Message framing + HMAC (reference network.py:55-97)."""

    def __init__(self, key):
        self._key = key or b""

    def write(self, obj, wfile):
        from .codec import _dumps
        message = _dumps(obj)
        wfile.write(secret.compute_digest(self._key, message))
        wfile.write(struct.pack("i", len(message)))
        wfile.write(message)
        wfile.flush()

    def stream(self, stream, wfile):
        from encodings.utf_8 import StreamWriter
        shutil.copyfileobj(stream, StreamWriter(wfile))
        wfile.flush()

    def read(self, rfile):
        digest = rfile.read(secret.DIGEST_LENGTH)
        (length,) = struct.unpack("i", rfile.read(4))
        message = rfile.read(length)
        if not secret.check_digest(self._key, message, digest):
            raise RuntimeError(
                "Security error: digest did not match the message.")
        return pickle.loads(message)


class _DrainingTCPServer(socketserver.ThreadingTCPServer):
    """shutdown() must wait for in-flight request handlers — the
    reference's services guarantee a long-running RPC completes before
    the server goes away (test_service.py:122-173 contract)."""

    daemon_threads = False
    block_on_close = True
    allow_reuse_address = True


class BasicService:
    def __init__(self, service_name, key, nics=None):
        self._service_name = service_name
        self._wire = Wire(key)
        self._nics = nics
        self._server, self._port = find_port(
            lambda addr: _DrainingTCPServer(
                addr, self._make_handler()))
        self._addresses = {
            "all": [(a, self._port)
                    for a in sorted(get_local_host_addresses())]}
        self._thread = in_thread(self._server.serve_forever)

    def _make_handler(self):
        service = self

        class _Handler(socketserver.StreamRequestHandler):
            def handle(self):
                try:
                    req = service._wire.read(self.rfile)
                    resp = service._handle(req, self.client_address)
                    if resp is None:
                        raise RuntimeError(
                            "Handler did not return a response.")
                    if isinstance(resp, tuple):
                        resp, stream = resp
                        service._wire.write(resp, self.wfile)
                        service._wire.stream(stream, self.wfile)
                    else:
                        service._wire.write(resp, self.wfile)
                except (EOFError, BrokenPipeError,
                        ConnectionResetError):
                    pass
                except RuntimeError as exc:
                    # bad digest: unauthorized caller — one log line,
                    # no traceback, connection dropped
                    import logging
                    logging.getLogger(__name__).warning(
                        "%s rejected request from %s: %s",
                        service._service_name, self.client_address,
                        exc)

        return _Handler

    def _handle(self, req, client_address):
        if isinstance(req, PingRequest):
            return PingResponse(self._service_name, client_address[0])
        raise NotImplementedError(req)

    def addresses(self):
        return {intf: list(addrs)
                for intf, addrs in self._addresses.items()}

    def shutdown(self):
        self._server.shutdown()
        self._server.server_close()
        self._thread.join()

    def get_port(self):
        return self._port


class BasicClient:
    def __init__(self, service_name, addresses, key, verbose=0,
                 match_intf=False, probe_timeout=20, attempts=3):
        self._service_name = service_name
        self._wire = Wire(key)
        self._verbose = verbose
        self._match_intf = match_intf
        self._probe_timeout = probe_timeout
        self._attempts = attempts
        self._addresses = self._probe(addresses)
        if not self._addresses:
            raise NoValidAddressesFound(
                f"Unable to connect to the {service_name} on any of "
                f"the addresses: {addresses}")

    def _probe(self, addresses):
        results = queue.Queue()
        threads = [in_thread(self._probe_one, (intf, addr, results))
                   for intf, addrs in addresses.items()
                   for addr in addrs]
        for t in threads:
            t.join()
        usable = {}
        while not results.empty():
            intf, addr = results.get()
            usable.setdefault(intf, []).append(addr)
        return usable

    def _probe_one(self, intf, addr, results):
        resp = self._try_request(addr, PingRequest(),
                                 probing=True)
        if resp is None or resp.service_name != self._service_name:
            return
        if self._match_intf:
            # reference network.py _probe_one: accept the address only
            # when the server saw our probe ARRIVE from an address of
            # the interface it was advertised under — i.e. the route
            # to ``addr`` actually leaves through ``intf``
            # (PingResponse.source_address is our address as the
            # server observed it).  Names that resolve to no NIC
            # (this framework's 'all' advertisement) carry no routing
            # claim and pass through unfiltered, and a source address
            # we cannot attribute to ANY interface (SIOCGIFADDR only
            # reports primaries, not aliases) is not evidence of a
            # wrong route — reject only a POSITIVE mismatch, a source
            # that is another NIC's address.
            local = _intf_ipv4_addresses(intf)
            if local is not None and resp.source_address not in local:
                others = set()
                try:
                    for _, name in socket.if_nameindex():
                        if name != intf:
                            others |= _intf_ipv4_addresses(name) or set()
                except OSError:
                    pass
                if resp.source_address in others:
                    return
        results.put((intf, addr))

    def _try_request(self, addr, req, probing=False, stream=None):
        attempts = 1 if probing else self._attempts
        for attempt in range(attempts):
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            # probe sockets are bounded; real RPCs block — several of
            # the protocol's requests legitimately wait minutes
            # (WaitForCommandExitCode, WaitForShutdown), and a timeout
            # retry would double-deliver or duplicate streamed output
            sock.settimeout(self._probe_timeout if probing else None)
            try:
                sock.connect(tuple(addr))
                rfile = sock.makefile("rb")
                wfile = sock.makefile("wb")
                try:
                    self._wire.write(req, wfile)
                    resp = self._wire.read(rfile)
                    if isinstance(resp, AckStreamResponse) and \
                            stream is not None:
                        shutil.copyfileobj(
                            _Utf8Reader(rfile), stream)
                    return resp
                finally:
                    rfile.close()
                    wfile.close()
            except (OSError, EOFError, struct.error):
                if attempt == attempts - 1:
                    if probing:
                        return None
                    # surface the raw connection error — callers (and
                    # the reference's tests) match on the errno text
                    raise
            finally:
                sock.close()
        return None

    def _send(self, req, stream=None):
        last_error = None
        for intf, addrs in self._addresses.items():
            for addr in addrs:
                try:
                    resp = self._try_request(addr, req, stream=stream)
                except (OSError, EOFError, struct.error) as exc:
                    # fail over to the next probed address; only the
                    # LAST address's failure surfaces (callers — and
                    # the reference's tests — match on the raw errno
                    # text)
                    last_error = exc
                    continue
                if resp is not None:
                    return resp
        if last_error is not None:
            raise last_error
        raise NoValidAddressesFound(
            f"{self._service_name} stopped responding on "
            f"{self._addresses}")

    def addresses(self):
        return {intf: list(addrs)
                for intf, addrs in self._addresses.items()}


class _Utf8Reader:
    """File-like over the socket's rfile decoding utf8 for stream
    responses."""

    def __init__(self, rfile):
        self._rfile = rfile

    def read(self, n=-1):
        data = self._rfile.read(n if n and n > 0 else 65536)
        return data.decode("utf-8", errors="replace") if data else ""

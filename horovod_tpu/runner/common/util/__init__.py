"""Reference package path ``horovod.runner.common.util``."""

"""Base64 pickle codec (reference
``horovod/runner/common/util/codec.py``) — used to pass functions and
settings through environment variables / command lines."""

import base64
import pickle


def _dumps(obj):
    try:
        import cloudpickle
        return cloudpickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    except ImportError:
        return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)


def dumps_base64(obj, to_ascii=True):
    serialized = base64.b64encode(_dumps(obj))
    return serialized.decode("ascii") if to_ascii else serialized


def loads_base64(encoded):
    return pickle.loads(base64.b64decode(encoded))

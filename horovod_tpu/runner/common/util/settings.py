"""Launcher settings objects (reference
``horovod/runner/common/util/settings.py``).  The TPU launcher passes
plain argparse namespaces internally (runner/launch.py); these classes
are the reference-shaped bundle used by the programmatic surfaces
(ray, spark) and by ported tooling.  MPI-only fields
(extra_mpi_args, binding_args, tcp_flag) are carried but unused."""


class BaseSettings:
    def __init__(self, num_proc=None, verbose=0, ssh_port=None,
                 ssh_identity_file=None, extra_mpi_args=None,
                 tcp_flag=None, binding_args=None, key=None,
                 start_timeout=None, output_filename=None,
                 run_func_mode=None, nics=None, elastic=False,
                 prefix_output_with_timestamp=False):
        self.num_proc = num_proc
        self.verbose = verbose
        self.ssh_port = ssh_port
        self.ssh_identity_file = ssh_identity_file
        self.extra_mpi_args = extra_mpi_args
        self.tcp_flag = tcp_flag
        self.binding_args = binding_args
        self.key = key
        self.start_timeout = start_timeout
        self.output_filename = output_filename
        self.run_func_mode = run_func_mode
        self.nics = nics
        self.elastic = elastic
        self.prefix_output_with_timestamp = prefix_output_with_timestamp


class Settings(BaseSettings):
    def __init__(self, hosts=None, **kwargs):
        super().__init__(**kwargs)
        self.hosts = hosts

"""Deadline helper (reference
``horovod/runner/common/util/timeout.py``)."""

import time


class TimeoutException(Exception):
    pass


class Timeout:
    def __init__(self, timeout, message="Timed out waiting for "
                                        "{activity}."):
        self._timeout = timeout
        self._message = message
        self._deadline = time.time() + timeout

    def remaining(self):
        return max(0.0, self._deadline - time.time())

    # alias kept for code written against earlier drafts
    remaining_time_s = remaining

    def timed_out(self):
        return time.time() > self._deadline

    def check_time_out_for(self, activity):
        if self.timed_out():
            raise TimeoutException(
                self._message.format(activity=activity,
                                     timeout=self._timeout))

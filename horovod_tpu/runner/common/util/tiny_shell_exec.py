"""Capture-only shell execution (reference
``horovod/runner/common/util/tiny_shell_exec.py``)."""

import subprocess


def execute(command):
    """Run ``command`` in a shell; returns ``(output, exit_code)`` or
    None on failure to spawn."""
    try:
        proc = subprocess.run(
            command, shell=True, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT)
    except OSError:
        return None
    return proc.stdout.decode("utf-8", errors="replace"), proc.returncode

"""Reference package path ``horovod.runner.common`` — shared runner
utilities and the pickled-message service framework."""

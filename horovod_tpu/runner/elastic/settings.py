"""Elastic launcher settings (reference
``horovod/runner/elastic/settings.py``)."""

from ..common.util.settings import BaseSettings


class ElasticSettings(BaseSettings):
    def __init__(self, discovery, min_num_proc, max_num_proc,
                 elastic_timeout, reset_limit, cooldown_range=None,
                 **kwargs):
        super().__init__(elastic=True, **kwargs)
        self.discovery = discovery
        self.min_num_proc = min_num_proc
        self.max_num_proc = max_num_proc
        self.elastic_timeout = elastic_timeout
        self.reset_limit = reset_limit
        self.cooldown_range = cooldown_range

"""Elastic constants (reference
``horovod/runner/elastic/constants.py``)."""

RESET_LIMIT_EXCEEDED_MESSAGE = (
    "Horovod detected that the maximum number of resets in the job "
    "has been exceeded (reset_limit={reset_limit}). Shutting down "
    "the job.")

"""Worker state registry (reference
``horovod/runner/elastic/registration.py``: READY/SUCCESS/FAILURE state
machine per slot, reset_limit enforcement :28-160)."""

import logging
import threading

logger = logging.getLogger("horovod_tpu.elastic")

READY = "READY"
SUCCESS = "SUCCESS"
FAILURE = "FAILURE"


class WorkerStateRegistry:
    """Collects per-slot terminal states for one rendezvous round; when
    every slot of the round has recorded, decides: stop (all success),
    fail (all failure / reset limit), or resume with a new rendezvous
    (mixed — blacklisting failed hosts)."""

    def __init__(self, driver, host_manager, reset_limit=None,
                 verbose=False):
        self._driver = driver
        self._host_manager = host_manager
        self._reset_limit = reset_limit
        self._reset_count = 0
        self._lock = threading.Lock()
        self._states = {}          # (host, slot) -> state
        self._workers = {}         # state -> set of keys
        self._rendezvous_id = 0
        self._verbose = verbose
        self._size = 0

    def get_recorded_slots(self):
        return list(self._states.keys())

    def get(self, state):
        return list(self._workers.get(state, set()))

    def count(self, state):
        return len(self._workers.get(state, set()))

    def reset(self, size):
        with self._lock:
            self._states.clear()
            self._workers.clear()
            self._rendezvous_id += 1
            self._size = size

    def size(self):
        return self._size

    def last_rendezvous(self):
        return self._rendezvous_id

    def record_ready(self, host, slot):
        return self._record_state(host, slot, READY)

    def record_success(self, host, slot):
        return self._record_state(host, slot, SUCCESS)

    def record_failure(self, host, slot):
        return self._record_state(host, slot, FAILURE)

    def _record_state(self, host, slot, state):
        if self._driver.finished():
            return self._rendezvous_id
        key = (host, slot)
        complete = False
        with self._lock:
            if self._states.get(key) == FAILURE and state == READY:
                return self._rendezvous_id
            prev = self._states.get(key)
            if prev is not None:
                self._workers.get(prev, set()).discard(key)
            self._states[key] = state
            self._workers.setdefault(state, set()).add(key)
            rendezvous_id = self._rendezvous_id
            if len(self._states) >= self._size and \
                    all(s in (SUCCESS, FAILURE)
                        for s in self._states.values()):
                complete = True
        if complete:
            self._on_workers_recorded()
        return rendezvous_id

    def _on_workers_recorded(self):
        logger.info("all %d workers recorded", self._size)
        if self.count(SUCCESS) == self._size:
            self._driver.stop()
            return
        if self.count(FAILURE) == self._size:
            logger.error("all workers failed")
            self._driver.stop(error=True)
            return
        for host, slot in self.get(FAILURE):
            self._host_manager.blacklist(host)
        if not self.note_reset():
            self._driver.stop(error=True)
            return
        self._driver.resume()

    def note_reset(self) -> bool:
        """Count one round restart toward the reset limit.  Returns
        False when the limit is exhausted — EVERY restart path must
        consult this (the reference enforces reset_limit on each
        re-rendezvous, driver-triggered or registry-triggered)."""
        with self._lock:
            if self._reset_limit is not None and \
                    self._reset_count >= self._reset_limit:
                logger.error("reset limit %d reached; aborting job",
                             self._reset_limit)
                return False
            self._reset_count += 1
            return True

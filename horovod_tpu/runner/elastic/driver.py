"""Elastic driver (reference ``horovod/runner/elastic/driver.py:69-320``
ElasticDriver): discovery thread, rank/host assignment with ordering
stability, worker lifecycle, blacklisting, round (re-)rendezvous.

TPU adaptation: a membership change means the global device mesh must
be re-formed, so each round publishes a fresh ``jax.distributed``
coordinator (new port) plus the rank assignments to the KV store;
workers tear down their runtime in-process
(jax.distributed.shutdown + clear_backends) and re-initialize against
the new round — state survives in memory exactly like the reference's
gloo re-rendezvous (SURVEY §7.7's hard part, made workable).
"""

import json
import logging
import os
import socket
import subprocess
import tempfile
import threading
import time
from typing import Dict, List, Optional

from ..hosts import get_host_assignments, parse_hosts, HostInfo
from ..http.http_server import local_ip
from ..proc_run import is_local, ssh_command
from .discovery import HostManager
from .registration import WorkerStateRegistry

logger = logging.getLogger("horovod_tpu.elastic")

DISCOVER_HOSTS_FREQUENCY_SECS = 1.0
ROUND_KEY = "/elastic/round"
NOTIFY_KEY = "/elastic/notify"


from ..http.http_server import free_port as _free_port


class ElasticDriver:
    def __init__(self, server, discovery, min_np, max_np, command,
                 env=None, reset_limit=None, cooldown_range=None,
                 platform=None, verbose=False, on_event=None,
                 elastic_timeout=600):
        self._server = server            # RendezvousServer (KV + coord)
        self._host_manager = HostManager(discovery, cooldown_range)
        self._min_np = min_np
        self._max_np = max_np
        self._command = command
        self._env = env or {}
        self._platform = platform
        self._verbose = verbose
        # lifecycle event hook (reference ray/elastic_v2.py:402-470
        # callback queue): called with dicts like
        # {"event": "round_start", ...}; exceptions are logged, never
        # fatal to the driver
        self._on_event = on_event
        # bound on each round's (re-)initialization — how long workers
        # may take to rendezvous after a reset before the round is
        # declared stuck and restarted (reference --elastic-timeout,
        # launch.py: "timeout for elastic initialisation after
        # re-scaling the cluster"); never bounds healthy training
        self._elastic_timeout = elastic_timeout

        self._registry = WorkerStateRegistry(self, self._host_manager,
                                             reset_limit=reset_limit)
        # autoscale lever (serving/autoscale.py): rounds are sized
        # min(available slots, _target_np); starts wide open
        self._target_np = max_np
        # multi-caller lever arbitration (docs/fleet.md): once an
        # owner claims the lever (the fleet controller), calls from
        # other writers are ignored, and a tagged write with a stale
        # epoch loses to the last accepted one — two racing callers
        # serialize into last-writer-wins instead of ping-ponging the
        # fleet through competing rounds
        self._lever_owner = None
        self._lever_epoch = -1
        # preemption-to-zero (docs/fleet.md "Suspension"): a suspended
        # job keeps its control plane (server, journal, spill) but
        # forms no rounds and drains its workers at a commit boundary
        self._suspended = False
        self._round = 0
        self._round_started_at = 0.0
        self._assignments: Dict[str, int] = {}
        self._slots_by_key: Dict[str, object] = {}  # "host:slot" -> SlotInfo
        self._worker_servers: Dict[str, tuple] = {}
        self._procs: Dict[str, subprocess.Popen] = {}  # "host:slot" -> p
        self._deassigned: Dict[str, float] = {}        # key -> deadline
        self._churn_respawns: Dict[str, int] = {}
        # procs the coordinator's liveness scan declared dead that the
        # monitor already acted on this round (missed-heartbeat feed —
        # catches HUNG workers that never exit; docs/fault_tolerance)
        self._dead_handled: set = set()
        self._notify_version = 0
        # committed worker state spills here so crash recovery can
        # restore it across process restarts
        self._spill_dir = tempfile.mkdtemp(prefix="hvd_elastic_state_")

        self._shutdown = threading.Event()
        self._error = False
        self._lock = threading.RLock()
        self._discovery_thread = threading.Thread(
            target=self._discover_hosts, daemon=True,
            name="elastic-discovery")
        self._monitor_thread = threading.Thread(
            target=self._monitor_workers, daemon=True,
            name="elastic-monitor")

    # -- lifecycle -----------------------------------------------------------

    def start(self, start_timeout=None):
        """``start_timeout`` bounds the wait for min_np slots (the
        reference's --start-timeout semantics); it does NOT bound job
        runtime."""
        self.wait_for_available_slots(
            self._min_np,
            timeout=120 if start_timeout is None else start_timeout)
        self._start_round()
        self._discovery_thread.start()
        self._monitor_thread.start()

    def wait_for_available_slots(self, min_np, timeout=120):
        deadline = time.monotonic() + timeout
        while True:
            # availability is checked at least once, so timeout=0 means
            # "fail fast unless slots are ALREADY available"
            self._host_manager.update_available_hosts()
            if self._host_manager.current_hosts.count_available_slots() \
                    >= min_np:
                return
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"timed out waiting for {min_np} slots to become "
                    f"available")
            time.sleep(DISCOVER_HOSTS_FREQUENCY_SECS)

    def join(self, timeout=None) -> bool:
        """Block until the job finishes; True on success.  ``timeout``
        (if given) bounds total runtime — normal jobs pass None; the
        startup wait is bounded separately in start()."""
        deadline = time.monotonic() + timeout if timeout else None
        while not self._shutdown.is_set():
            if deadline and time.monotonic() > deadline:
                self.stop(error=True)
                raise TimeoutError("elastic job timed out")
            time.sleep(0.1)
        self._terminate_all()
        return not self._error

    def finished(self):
        return self._shutdown.is_set()

    def stop(self, error=False):
        with self._lock:
            self._error = self._error or error
            self._shutdown.set()

    def resume(self):
        """Registry decided to start a new round (some workers failed
        or membership changed)."""
        with self._lock:
            if not self._shutdown.is_set():
                self._start_round()

    def _emit(self, event, **fields):
        if self._on_event is None:
            return
        try:
            self._on_event({"event": event, **fields})
        except Exception:  # noqa: BLE001 — user callback bug
            logger.exception("elastic event callback failed (%s)", event)

    # -- round management ----------------------------------------------------

    def _compute_assignments(self) -> List:
        hosts = self._host_manager.current_hosts
        host_infos = [HostInfo(h, hosts.host_slots[h])
                      for h in hosts.host_assignment_order]
        np = min(hosts.count_available_slots(), self._target_np)
        return get_host_assignments(host_infos, np)

    def current_world_size(self) -> int:
        """Workers in the current round (0 before the first forms)."""
        with self._lock:
            return len(self._assignments)

    def refresh_hosts(self) -> bool:
        """Synchronously re-poll discovery; True when membership
        changed.  The fleet controller calls this right after moving a
        job's placement view so the set_target_np that follows
        computes its effective size against the NEW hosts instead of
        the discovery thread's 1s-cadence cache (a shrink racing the
        cache would otherwise form a transient round on a host the
        controller just revoked).  Cheap for in-memory discoveries
        (FleetDiscovery); script-based discoveries pay one script run."""
        return self._host_manager.update_available_hosts()

    def acquire_target_lever(self, owner: str):
        """Claim exclusive ownership of the ``set_target_np`` lever
        (docs/fleet.md): after this, only calls tagged with ``owner``
        move the target — a per-job autoscaler racing the fleet
        controller is serialized out instead of re-forming rounds the
        fleet immediately undoes."""
        with self._lock:
            self._lever_owner = owner

    def release_target_lever(self):
        with self._lock:
            self._lever_owner = None
            self._lever_epoch = -1

    def set_target_np(self, target: int, owner: str = None,
                      epoch: int = None) -> int:
        """Autoscale lever (serving/autoscale.py): retarget the fleet
        to ``target`` workers, clamped to [min_np, max_np], and
        re-form the round exactly like a membership change — scale-up
        claims available slots, scale-down de-assigns workers (they
        get the usual drain grace before termination).  Returns the
        accepted target (the CURRENT target when the write was
        rejected).  A no-op target keeps the current round.

        Multi-caller arbitration: when an owner holds the lever
        (:meth:`acquire_target_lever`), writes from anyone else are
        ignored; ``epoch``-tagged writes are last-writer-wins — a
        write whose epoch is below the last accepted one is stale and
        dropped (two callers racing the lever resolve to the newest
        decision instead of interleaving rounds)."""
        with self._lock:
            if self._lever_owner is not None and \
                    owner != self._lever_owner:
                logger.info(
                    "set_target_np(%s) from %r ignored: lever owned "
                    "by %r", target, owner, self._lever_owner)
                return self._target_np
            if epoch is not None:
                if epoch < self._lever_epoch:
                    logger.info(
                        "set_target_np(%s) epoch %d is stale "
                        "(last accepted %d); dropped", target, epoch,
                        self._lever_epoch)
                    return self._target_np
                self._lever_epoch = epoch
            target = max(self._min_np, min(int(target), self._max_np))
            if target == self._target_np:
                return target
            prev, self._target_np = self._target_np, target
            # only re-form a live round (round 0 = driver not started:
            # start() will size its first round off the new target),
            # and only when the EFFECTIVE size actually moves — a
            # scale-up with no free slots must not bounce every
            # replica through a re-rendezvous for zero capacity gain
            # (the discovery thread starts the bigger round when new
            # hosts appear; _compute_assignments reads the target)
            effective = min(
                self._host_manager.current_hosts
                    .count_available_slots(), target)
            changed = self._round > 0 and \
                effective != len(self._assignments)
        logger.info("autoscale target: %d -> %d workers", prev, target)
        self._emit("autoscale_target", target=target, previous=prev)
        if changed and not self._shutdown.is_set():
            self._start_round()
        return target

    # -- suspension (docs/fleet.md "Suspension"): preemption to zero is
    #    a control-plane pause, not a restart ------------------------------

    @property
    def suspended(self) -> bool:
        return self._suspended

    def suspend(self, drain_grace: float = 30.0):
        """Preempt the job to ZERO workers while keeping its control
        plane: publish a ``suspended`` round so every worker drains at
        its next commit boundary (the committed state is already in
        the spill; the worker self-aborts cleanly — see
        ``basics._elastic_rendezvous``), journal the transition through
        the coordinator (a ``reset`` at size 0 — a later
        journal-restarted coordinator restores into the suspended
        shape), and stop forming rounds until :meth:`unsuspend`.
        Workers that miss the drain grace are terminated; their state
        survives in the spill either way."""
        with self._lock:
            if self._suspended:
                return
            self._suspended = True
            self._round += 1
            self._assignments = {}
            self._slots_by_key = {}
            round_info = {"round": self._round, "size": 0,
                          "suspended": True, "assignments": {}}
            self._server.store.put(ROUND_KEY,
                                   json.dumps(round_info).encode())
            self._notify_version += 1
            self._server.store.put(
                NOTIFY_KEY,
                json.dumps({"version": self._notify_version,
                            "round": self._round,
                            "suspended": True}).encode())
            # flush the suspension into the coordinator journal: the
            # round reset is a journaled transition, so a coordinator
            # (or fleet-controller) restart while suspended rebuilds
            # the paused control plane, not a live round
            self._server.coordinator.reset(world_size=0,
                                           round_id=self._round)
            now = time.monotonic()
            for key, p in list(self._procs.items()):
                if p.poll() is None:
                    self._deassigned.setdefault(key, now + drain_grace)
        logger.warning("job suspended at round %d (workers draining "
                       "at their next commit)", self._round)
        self._emit("suspend", round=self._round)

    def unsuspend(self):
        """Resume a suspended job: re-form a round from the current
        target + discovery.  Fresh workers restore the last elastic
        commit from the spill, and the coordinator's journal/epoch
        machinery fences any restart that happened while paused — the
        resumed job continues from the committed step."""
        with self._lock:
            if not self._suspended:
                return
            self._suspended = False
        logger.warning("job resuming from suspension")
        self._emit("resume", round=self._round)
        self._host_manager.update_available_hosts()
        self._start_round()

    def _start_round(self):
        with self._lock:
            if self._suspended:
                return
            slots = self._compute_assignments()
            if len(slots) < self._min_np:
                logger.warning(
                    "only %d slots available (< min_np %d); waiting",
                    len(slots), self._min_np)
                return
            self._round += 1
            self._assignments = {
                f"{s.hostname}:{s.local_rank}": s.rank for s in slots}
            self._slots_by_key = {
                f"{s.hostname}:{s.local_rank}": s for s in slots}
            size = len(slots)
            # routable addresses when the round spans hosts: rendezvous
            # lives here; the jax.distributed coordinator on rank 0's
            # host (same rule as proc_run.launch_procs)
            any_remote = any(not is_local(s.hostname) for s in slots)
            self._rdv_addr = local_ip() if any_remote else "127.0.0.1"
            rank0_host = slots[0].hostname
            coord_host = self._rdv_addr if is_local(rank0_host) \
                else rank0_host
            coordinator = f"{coord_host}:{_free_port()}"
            self._registry.reset(size)
            self._server.coordinator.reset(world_size=size,
                                           round_id=self._round)
            round_info = {
                "round": self._round,
                "size": size,
                "coordinator": coordinator,
                "assignments": self._assignments,
            }
            self._server.store.put(ROUND_KEY,
                                   json.dumps(round_info).encode())
            self._notify_version += 1
            self._server.store.put(
                NOTIFY_KEY,
                json.dumps({"version": self._notify_version,
                            "round": self._round}).encode())
            logger.info("round %d: %d workers %s", self._round, size,
                        self._assignments)
            self._emit("round_start", round=self._round, size=size,
                       assignments=dict(self._assignments))
            self._round_started_at = time.monotonic()
            self._churn_respawns.clear()
            self._dead_handled.clear()
            # spawn processes for slots without a live worker
            for key in self._assignments:
                p = self._procs.get(key)
                self._deassigned.pop(key, None)
                if p is None or p.poll() is not None:
                    self._spawn_worker(key)
            # de-assigned workers get a grace period to exit cleanly
            # (they participate in the old round's shutdown barrier,
            # then park in rendezvous wait) before being terminated
            for key, p in list(self._procs.items()):
                if key not in self._assignments and p.poll() is None:
                    self._deassigned.setdefault(
                        key, time.monotonic() + 30.0)

    def _spawn_worker(self, key):
        host, slot = key.rsplit(":", 1)
        env = dict(os.environ)
        env.update(self._env)
        # elastic workers derive topology from the rendezvous, not a
        # static host map — a stale inherited value would mislead them
        env.pop("HOROVOD_TPU_HOST_OF_RANK", None)
        env.update({
            "HOROVOD_ELASTIC": "1",
            "HOROVOD_CONTROLLER": "http",
            "HOROVOD_HOSTNAME": host,
            "HOROVOD_LOCAL_RANK": slot,
            "HOROVOD_GLOO_RENDEZVOUS_ADDR": getattr(
                self, "_rdv_addr", "127.0.0.1"),
            "HOROVOD_GLOO_RENDEZVOUS_PORT": str(self._server.port),
            "HOROVOD_SECRET_KEY": self._server.secret.hex()
            if self._server.secret else "",
            "HOROVOD_TPU_RANKS_PER_PROC": "1",
            # fail fast out of a stale round's rendezvous so the
            # respawn picks up the current one
            "HOROVOD_TPU_INIT_TIMEOUT": "20",
            # crash-durable commit spill (common/elastic.py)
            "HOROVOD_STATE_SPILL": self._spill_dir,
        })
        if self._platform == "cpu":
            env["JAX_PLATFORMS"] = "cpu"
            env["JAX_NUM_CPU_DEVICES"] = "1"
        if self._verbose:
            logger.info("spawning worker %s", key)
        self._emit("worker_start", host=host, slot=int(slot),
                   round=self._round)
        if is_local(host):
            self._procs[key] = subprocess.Popen(self._command, env=env)
        else:
            # remote slot: same ssh + stdin env handoff as the static
            # launcher (proc_run.ssh_command).  The Popen handle tracks
            # the ssh client; terminating it drops the connection and
            # sshd delivers SIGHUP to the remote worker.
            cmd, payload = ssh_command(host, self._command, env,
                                       cwd=os.getcwd(),
                                       extra_keys=set(self._env))
            p = subprocess.Popen(cmd, env=dict(os.environ),
                                 stdin=subprocess.PIPE)
            try:
                p.stdin.write(payload)
                p.stdin.close()
            except (BrokenPipeError, OSError):
                # ssh died instantly (unreachable host, auth failure):
                # leave the dead Popen in _procs so the monitor thread
                # reaps it and blacklists the host like any worker exit
                logger.warning("ssh to %s closed before env handoff",
                               host)
            self._procs[key] = p

    # -- background threads --------------------------------------------------

    def _discover_hosts(self):
        while not self._shutdown.is_set():
            try:
                changed = self._host_manager.update_available_hosts()
            except Exception:  # noqa: BLE001 — discovery script hiccup
                logger.exception("host discovery failed")
                changed = False
            if changed:
                logger.info("host membership changed: %s",
                            self._host_manager.current_hosts.host_slots)
                self._emit(
                    "hosts_updated",
                    hosts=dict(
                        self._host_manager.current_hosts.host_slots))
                self._start_round()
            self._shutdown.wait(DISCOVER_HOSTS_FREQUENCY_SECS)

    # -- reference per-worker rendezvous verbs (driver.py:200-260;
    #    consumed by elastic/rendezvous.py's handler adapter) ----------------

    def record_ready(self, host, local_rank):
        """A worker at ``host:local_rank`` reached rendezvous
        (reference driver.py record_ready).  The KV path records this
        via /elastic/joined markers; this direct form feeds the same
        registry.  Rank AND round are resolved under one lock so a
        concurrent ``_start_round`` cannot stamp the marker into the
        wrong round."""
        with self._lock:
            rank = self._assignments.get(f"{host}:{local_rank}")
            round_id = self._round
        if rank is not None:
            self._server.store.put(
                f"/elastic/joined/{round_id}/{rank}", b"1")

    def get_slot_info(self, host, local_rank):
        """SlotInfo for a worker slot in the current round (reference
        driver.py get_slot_info); INVALID for unassigned slots.
        Served from the allocator's own slot table (``_slots_by_key``,
        recorded at round start) so cross/local ranks always match the
        published round."""
        from ..common.util.hosts import INVALID_SLOT_INFO

        with self._lock:
            return self._slots_by_key.get(f"{host}:{local_rank}",
                                          INVALID_SLOT_INFO)

    def register_worker_server(self, host, local_rank, addresses,
                               secret_key):
        """Store a worker's notification-service address (reference
        driver.py register_worker_server) so the driver can push
        HostsUpdatedRequests over TCP in addition to the KV bump."""
        with self._lock:
            self._worker_servers[f"{host}:{local_rank}"] = \
                (addresses, secret_key)

    def get_worker_client(self, slot_info):
        """WorkerNotificationClient for a registered worker, or None
        (reference driver.py get_worker_client)."""
        from .worker import WorkerNotificationClient

        with self._lock:
            entry = self._worker_servers.get(
                f"{slot_info.hostname}:{slot_info.local_rank}")
        if entry is None:
            return None
        addresses, key = entry
        return WorkerNotificationClient(addresses, key,
                                        verbose=self._verbose)

    def _round_joined(self):
        """How many of this round's workers picked up the rendezvous
        (the /elastic/joined markers workers write on re-init)."""
        store = self._server.store
        return sum(
            1 for rank in range(len(self._assignments))
            if store.get(f"/elastic/joined/{self._round}/{rank}",
                         timeout=0.01) is not None)

    def _check_round_formation(self, now):
        """A round whose workers never all rendezvous within
        elastic_timeout is stuck (hung worker, stale state): terminate
        its processes and start a fresh round, burning one reset
        (reference --elastic-timeout role, launch.py)."""
        if not self._elastic_timeout or not self._assignments:
            return
        if (now - self._round_started_at) <= self._elastic_timeout:
            return
        joined = self._round_joined()
        size = len(self._assignments)
        if joined >= size:
            return
        logger.warning(
            "round %d never formed within %.0fs (%d/%d workers "
            "rendezvoused); restarting the round", self._round,
            self._elastic_timeout, joined, size)
        with self._lock:
            for key in list(self._assignments):
                p = self._procs.pop(key, None)
                if p is not None and p.poll() is None:
                    p.terminate()
                    try:
                        p.wait(timeout=5)
                    except subprocess.TimeoutExpired:
                        p.kill()
            if not self._registry.note_reset():
                self.stop(error=True)
                return
            self._host_manager.update_available_hosts()
            self._start_round()

    def _monitor_workers(self):
        while not self._shutdown.is_set():
            failed_hosts = []
            now = time.monotonic()
            self._check_round_formation(now)
            rid_before = self._registry.last_rendezvous()
            with self._lock:
                # reap grace-expired de-assigned workers
                for key, deadline in list(self._deassigned.items()):
                    p = self._procs.get(key)
                    if p is None or p.poll() is not None:
                        self._procs.pop(key, None)
                        self._deassigned.pop(key, None)
                    elif now > deadline:
                        p.terminate()
                for key, p in list(self._procs.items()):
                    code = p.poll()
                    if code is None:
                        continue
                    del self._procs[key]
                    if key in self._deassigned:
                        self._deassigned.pop(key, None)
                        continue       # expected exit of a removed slot
                    host, slot = key.rsplit(":", 1)
                    self._emit("worker_exit", host=host,
                               slot=int(slot), code=code,
                               round=self._round)
                    in_churn = (now - self._round_started_at) < 25.0
                    churns = self._churn_respawns.get(key, 0)
                    is_churn_exit = code in (-6, 134) or \
                        (code == 1 and in_churn)
                    if code == 0:
                        self._registry.record_success(host, int(slot))
                    elif is_churn_exit and churns < 10:
                        # SIGABRT / early-round deaths are jax
                        # coordination-client fatalities from peer loss
                        # or a stale rendezvous — churn, not a bad
                        # host: respawn against the current round
                        # (committed state restores from the spill)
                        logger.info("worker %s exited (%d) during "
                                    "re-rendezvous churn; respawning",
                                    key, code)
                        self._churn_respawns[key] = churns + 1
                        if key in self._assignments and \
                                not self._shutdown.is_set():
                            self._spawn_worker(key)
                    else:
                        logger.warning("worker %s exited with %d",
                                       key, code)
                        # distinct from worker_exit (which ALSO fires
                        # for churn/clean exits): this is the event
                        # consumers like the fleet controller treat as
                        # a real slot failure (docs/fleet.md)
                        self._emit("worker_failed", host=host,
                                   slot=int(slot), code=code,
                                   round=self._round)
                        self._registry.record_failure(host, int(slot))
                        failed_hosts.append(host)
                # coordinator liveness feed: a proc whose heartbeats
                # stopped but whose PROCESS never exited (hung worker,
                # network partition) would otherwise survive until the
                # stall timeout — reap it, fail its slot, blacklist
                # its host, exactly like an observed exit
                for proc, info in \
                        self._server.coordinator.dead_procs().items():
                    if proc in self._dead_handled:
                        continue
                    self._dead_handled.add(proc)
                    key = next((k for k, r in self._assignments.items()
                                if r == proc), None)
                    if key is None:
                        continue
                    p = self._procs.get(key)
                    if p is None or p.poll() is not None:
                        # the process also EXITED: the exit-code path
                        # above owns that failure — recording it here
                        # too would double-count one death
                        continue
                    host, slot = key.rsplit(":", 1)
                    logger.warning(
                        "worker %s (proc %d, global ranks %s) missed "
                        "heartbeats; treating as failed", key, proc,
                        info.get("ranks") or "unknown")
                    self._procs.pop(key, None)
                    p.kill()            # a hung process never exits
                    self._emit("worker_dead", host=host,
                               slot=int(slot), round=self._round,
                               ranks=info.get("ranks") or [])
                    self._registry.record_failure(host, int(slot))
                    failed_hosts.append(host)
            if failed_hosts and not self._shutdown.is_set() and \
                    self._registry.last_rendezvous() == rid_before:
                # a failure mid-run must not wait for survivors to
                # reach a terminal state — they are likely blocked in a
                # collective with the dead peer.  Blacklist and start a
                # new round now; survivors get a stale-round error and
                # re-rendezvous (reference driver.py:304-320
                # _handle_worker_exit -> blacklist -> new assignments).
                # (When record_failure completed the round, the registry
                # already blacklisted / consumed one reset / resumed —
                # last_rendezvous moved on, and burning a second reset
                # here would double-count one failure event.)
                for host in failed_hosts:
                    self._host_manager.blacklist(host)
                if not self._registry.note_reset():
                    self.stop(error=True)
                else:
                    self._host_manager.update_available_hosts()
                    self._start_round()
            self._shutdown.wait(0.2)

    def _terminate_all(self):
        with self._lock:
            for p in self._procs.values():
                if p.poll() is None:
                    p.terminate()
            t0 = time.monotonic()
            while time.monotonic() - t0 < 5:
                if all(p.poll() is not None for p in self._procs.values()):
                    break
                time.sleep(0.05)
            for p in self._procs.values():
                if p.poll() is None:
                    p.kill()


ELASTIC_TIMEOUT_SECS = 600


class Results:
    """Collected worker results for a run-function job (reference
    driver.py:39)."""

    def __init__(self, error_message, worker_results):
        self.error_message = error_message
        self.worker_results = worker_results


class ResultsRecorder:
    """Reference driver.py:45 — threads publishing per-worker results
    are registered with ``expect`` and joined at ``get_results``."""

    def __init__(self):
        import queue
        self._error_message = None
        self._worker_results = {}
        self._worker_threads = queue.Queue()

    def expect(self, worker_thread):
        self._worker_threads.put(worker_thread)

    def set_error_message(self, error_message):
        self._error_message = error_message

    def add_result(self, key, value):
        if key not in self._worker_results:
            self._worker_results[key] = value

    def get_results(self):
        while not self._worker_threads.empty():
            self._worker_threads.get().join()
        return Results(self._error_message, self._worker_results)

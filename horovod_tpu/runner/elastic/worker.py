"""Worker-side host-update notifications (reference
``horovod/runner/elastic/worker.py:32-119``
WorkerNotificationService/Manager — driver -> worker push).

Here the channel is the launcher's KV store: the driver bumps a
version under ``/elastic/notify``; a daemon thread long-polls it and
feeds registered ``State`` listeners, which raise
``HostsUpdatedInterrupt`` at the next ``state.commit()``.
"""

import json
import logging
import threading
import time

from ...common import env as env_mod

logger = logging.getLogger("horovod_tpu.elastic")

NOTIFY_KEY = "/elastic/notify"


class WorkerNotificationManager:
    def __init__(self):
        self._lock = threading.Lock()
        self._listeners = set()
        self._thread = None
        self._stop = threading.Event()
        self._seen_version = 0

    def init(self):
        with self._lock:
            if self._thread is not None:
                return
            if env_mod.get_str("HOROVOD_ELASTIC") is None and \
                    not env_mod.get_bool("HOROVOD_ELASTIC"):
                return
            addr = env_mod.get_str(env_mod.HOROVOD_RENDEZVOUS_ADDR)
            port = env_mod.get_int(env_mod.HOROVOD_RENDEZVOUS_PORT, 0)
            if not addr or not port:
                return
            secret = env_mod.get_str("HOROVOD_SECRET_KEY")
            from ..http.http_client import StoreClient
            self._client = StoreClient(
                addr, port, bytes.fromhex(secret) if secret else None)
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._poll_loop, daemon=True,
                name="hvd-notification")
            self._thread.start()

    def register_listener(self, listener):
        with self._lock:
            self._listeners.add(listener)

    def remove_listener(self, listener):
        with self._lock:
            self._listeners.discard(listener)

    def _poll_loop(self):
        while not self._stop.is_set():
            try:
                raw = self._client.get(NOTIFY_KEY, wait=5.0)
            except Exception:  # noqa: BLE001 — launcher went away
                time.sleep(1.0)
                continue
            if raw is None:
                continue
            try:
                info = json.loads(raw)
            except ValueError:
                continue
            version = info.get("version", 0)
            if version > self._seen_version:
                if self._seen_version != 0:
                    # version 0->first is the initial round, not a change
                    with self._lock:
                        listeners = list(self._listeners)
                    ts = time.time()
                    for listener in listeners:
                        try:
                            listener.on_hosts_updated(
                                ts, info.get("round"))
                        except Exception:  # noqa: BLE001
                            logger.exception("listener failed")
                self._seen_version = version
            else:
                time.sleep(0.5)


    def handle_hosts_updated(self, timestamp, update_res):
        """Direct dispatch (reference worker.py:85) — the path the
        TCP WorkerNotificationService below uses."""
        with self._lock:
            listeners = list(self._listeners)
        for listener in listeners:
            try:
                listener.on_hosts_updated(timestamp, update_res)
            except Exception:  # noqa: BLE001
                logger.exception("listener failed")


notification_manager = WorkerNotificationManager()


# -- reference-shaped surface (horovod/runner/elastic/worker.py) -------------
#
# The live notification channel above is KV-store push (driver bumps
# /elastic/notify, workers long-poll).  The reference's TCP
# notification service is also provided, fully functional, for tooling
# that drives workers through it directly.

from enum import IntFlag

from ..common.util import network as _network

HOROVOD_GLOO_RENDEZVOUS_ADDR = "HOROVOD_GLOO_RENDEZVOUS_ADDR"
HOROVOD_GLOO_RENDEZVOUS_PORT = "HOROVOD_GLOO_RENDEZVOUS_PORT"
HOROVOD_GLOO_IFACE = "HOROVOD_GLOO_IFACE"
HOROVOD_HOSTNAME = "HOROVOD_HOSTNAME"
HOROVOD_LOCAL_RANK = "HOROVOD_LOCAL_RANK"


class HostUpdateResult(IntFlag):
    no_update = 0
    removed = 1
    added = 2
    mixed = removed | added


class HostsUpdatedRequest:
    """Driver -> worker: available hosts/slots changed (reference
    worker.py:38)."""

    def __init__(self, timestamp, res=HostUpdateResult.no_update):
        self.timestamp = timestamp
        self.res = res


class WorkerNotificationService(_network.BasicService):
    NAME = "worker notification service"

    def __init__(self, key, nic, manager):
        super().__init__(WorkerNotificationService.NAME, key,
                         [nic] if nic else None)
        self._manager = manager

    def _handle(self, req, client_address):
        if isinstance(req, HostsUpdatedRequest):
            self._manager.handle_hosts_updated(req.timestamp, req.res)
            return _network.AckResponse()
        return super()._handle(req, client_address)


class WorkerNotificationClient(_network.BasicClient):
    def __init__(self, addresses, key, verbose=0, match_intf=False):
        super().__init__(WorkerNotificationService.NAME, addresses,
                         key, verbose, match_intf=match_intf)

    def notify_hosts_updated(self, timestamp, update_res):
        self._send(HostsUpdatedRequest(timestamp, update_res))

"""Host discovery for elastic jobs (reference
``horovod/runner/elastic/discovery.py``: HostManager :152,
HostDiscoveryScript :240, blacklist with exponential-cooldown
resurrection :33-111)."""

import logging
import random
import subprocess
import threading
import time
from collections import defaultdict

logger = logging.getLogger("horovod_tpu.elastic")

# reference discovery.py cooldown constants
DEFAULT_COOLDOWN_RANGE = (1.0, 600.0)


class HostState:
    """Blacklist state for one host (reference discovery.py:33-111):
    exponential backoff between blacklist and resurrection."""

    def __init__(self, cooldown_range=None):
        self._event = threading.Event()
        self._blacklisted = False
        self._blacklist_count = 0
        self._cooldown_range = cooldown_range or DEFAULT_COOLDOWN_RANGE
        self._cooldown_ends = None

    def get_event(self):
        if self._event.is_set():
            event = threading.Event()
            self._event = event
        return self._event

    def set_event(self):
        self._event.set()

    def _in_cooldown_period(self, current_time):
        return self._cooldown_ends is not None and \
            current_time < self._cooldown_ends

    def _set_cooldown_period(self, current_time):
        self._blacklist_count += 1
        lo, hi = self._cooldown_range
        # exponential backoff with jitter, capped at the range max
        delay = min(lo * (2 ** (self._blacklist_count - 1)), hi)
        delay *= 1.0 + 0.25 * random.random()
        self._cooldown_ends = current_time + min(delay, hi)

    def blacklist(self):
        """Blacklist the host with a cooldown period."""
        self._blacklisted = True
        self._set_cooldown_period(time.monotonic())
        self.set_event()

    def whitelist(self):
        """Whitelist the host immediately (cooldown expiry)."""
        self._cooldown_ends = None
        self._blacklisted = False

    def is_blacklisted(self):
        """Cooldown expiry resurrects the host (reference
        discovery.py:97-111)."""
        if self._blacklisted and not self._in_cooldown_period(
                time.monotonic()):
            self.whitelist()
        return self._blacklisted


class HostManager:
    """Tracks current available hosts + blacklist (reference
    discovery.py:152-239)."""

    def __init__(self, discovery, cooldown_range=None):
        self._current_hosts = DiscoveredHosts(host_slots={},
                                              host_assignment_order=[])
        self._hosts_state = defaultdict(
            lambda: HostState(cooldown_range))
        self._discovery = discovery

    def update_available_hosts(self):
        """Poll discovery; returns True when membership changed."""
        def active(host):
            return not self._hosts_state[host].is_blacklisted()

        prev_hosts = self._current_hosts
        slots = self._discovery.find_available_hosts_and_slots()
        if prev_hosts.host_slots != slots:
            available = {h for h in slots if active(h)}
            prev_avail = set(prev_hosts.host_assignment_order)
            if available != prev_avail or prev_hosts.host_slots != slots:
                # preserve order of existing hosts for rank stability
                # (reference HostManager.order_available_hosts)
                order = [h for h in prev_hosts.host_assignment_order
                         if h in available]
                order += sorted(available - set(order))
                self._current_hosts = DiscoveredHosts(
                    host_slots=slots, host_assignment_order=order)
                return True
        else:
            # blacklist state may have changed without slot changes
            available = {h for h in slots if active(h)}
            if set(self._current_hosts.host_assignment_order) != available:
                order = [h for h in self._current_hosts.host_assignment_order
                         if h in available]
                order += sorted(available - set(order))
                self._current_hosts = DiscoveredHosts(
                    host_slots=slots, host_assignment_order=order)
                return True
        return False

    @property
    def current_hosts(self):
        return self._current_hosts

    def blacklist(self, host):
        if not self._hosts_state[host].is_blacklisted():
            logger.warning("blacklisting host %s", host)
        self._hosts_state[host].blacklist()

    def is_blacklisted(self, host):
        return self._hosts_state[host].is_blacklisted()

    def get_host_event(self, host):
        return self._hosts_state[host].get_event()


class DiscoveredHosts:
    """Immutable snapshot (reference discovery.py:114-149)."""

    def __init__(self, host_slots, host_assignment_order):
        self.host_slots = dict(host_slots)
        self.host_assignment_order = list(host_assignment_order)

    @property
    def available_hosts(self):
        return set(self.host_assignment_order)

    def count_available_slots(self):
        return sum(self.host_slots.get(h, 0)
                   for h in self.host_assignment_order)

    def update(self, hosts_state):
        self.host_assignment_order = [
            h for h in self.host_assignment_order
            if not hosts_state[h].is_blacklisted()]
        return self


class HostDiscovery:
    def find_available_hosts_and_slots(self) -> dict:
        """Returns {hostname: slots}."""
        raise NotImplementedError


class HostDiscoveryScript(HostDiscovery):
    """User script printing ``host:slots`` lines (reference
    discovery.py:240-273)."""

    def __init__(self, discovery_script, slots=None):
        self._discovery_script = discovery_script
        self._default_slots = slots

    def _execute_discovery_script(self):
        """Run the user's script, return its stdout (separate method
        so tests can substitute results — reference discovery.py
        contract)."""
        return subprocess.check_output(
            self._discovery_script, shell=True, timeout=60).decode()

    def find_available_hosts_and_slots(self):
        stdout = self._execute_discovery_script()
        host_slots = {}
        for line in stdout.strip().splitlines():
            line = line.strip()
            if not line:
                continue
            if ":" in line:
                host, slots = line.split(":", 1)
                host_slots[host] = int(slots)
            else:
                if self._default_slots is None:
                    raise RuntimeError(
                        f"no slots for host {line}; pass --slots-per-host "
                        f"or print host:slots lines")
                host_slots[line] = self._default_slots
        return host_slots


class FixedHosts(HostDiscovery):
    def __init__(self, available_hosts):
        self._available_hosts = dict(available_hosts)

    def find_available_hosts_and_slots(self):
        return dict(self._available_hosts)


# reference discovery.py cooldown constant names (the tuple above is
# the live configuration; these are the reference's split form)
DEFAULT_COOLDOWN_LOWER_LIMIT_SECONDS = DEFAULT_COOLDOWN_RANGE[0]
DEFAULT_COOLDOWN_UPPER_LIMIT_SECONDS = DEFAULT_COOLDOWN_RANGE[1]

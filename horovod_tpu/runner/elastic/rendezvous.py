"""Elastic rendezvous verbs (reference
``horovod/runner/elastic/rendezvous.py``).

The live elastic rendezvous in this build is KV-published rounds: the
driver writes ``/elastic/round`` with the full assignment table and
workers long-poll it (driver.py ROUND_KEY / common/basics.py
``_elastic_rendezvous``) — one write per round instead of one GET per
worker.  The reference's per-worker verbs are provided here as a
functional adapter over the same driver state for tooling that speaks
them.
"""

from ..common.util import codec

# GET methods
GET_RANK_AND_SIZE = "rank_and_size"

# PUT methods
PUT_WORKER_ADDRESSES = "worker_addresses"


def create_rendezvous_handler(driver):
    """Returns a handler whose ``get``/``put`` implement the
    reference's scope verbs against ``driver`` (reference
    rendezvous.py:27-54)."""

    class ElasticRendezvousHandler:
        def get(self, scope, key):
            if scope == GET_RANK_AND_SIZE:
                host, local_rank = key.rsplit(":", 1)
                driver.record_ready(host, int(local_rank))
                slot_info = driver.get_slot_info(host, int(local_rank))
                return slot_info

            raise KeyError(f"unknown GET scope: {scope}")

        def put(self, scope, key, value):
            if scope == PUT_WORKER_ADDRESSES:
                host, local_rank = key.rsplit(":", 1)
                addresses, secret_key = codec.loads_base64(value)
                driver.register_worker_server(
                    host, int(local_rank), addresses, secret_key)
                return

            raise KeyError(f"unknown PUT scope: {scope}")

    return ElasticRendezvousHandler()

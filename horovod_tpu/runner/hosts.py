"""Host list parsing + slot allocation (reference
``horovod/runner/common/util/hosts.py`` and ``launch.py`` host flags).
"""

from dataclasses import dataclass
from typing import List


@dataclass
class HostInfo:
    hostname: str
    slots: int

    @staticmethod
    def from_string(s: str) -> "HostInfo":
        if ":" in s:
            host, slots = s.rsplit(":", 1)
            return HostInfo(host.strip(), int(slots))
        return HostInfo(s.strip(), 1)


@dataclass
class SlotInfo:
    """One rank's placement (reference hosts.py SlotInfo: rank,
    local/cross rank+size)."""
    hostname: str
    rank: int
    local_rank: int
    local_size: int
    cross_rank: int
    cross_size: int
    size: int


def parse_hosts(hosts_str: str) -> List[HostInfo]:
    """Parse ``h1:2,h2:4`` (reference hosts.py parse_hosts)."""
    return [HostInfo.from_string(x) for x in hosts_str.split(",") if x]


def parse_host_files(filename: str) -> str:
    """Hostfile with ``hostname slots=N`` lines (reference
    launch.py parse_host_files)."""
    hosts = []
    with open(filename) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            parts = line.split()
            name = parts[0]
            slots = 1
            for p in parts[1:]:
                if p.startswith("slots="):
                    slots = int(p[len("slots="):])
            hosts.append(f"{name}:{slots}")
    return ",".join(hosts)


def get_host_assignments(hosts: List[HostInfo], np: int) -> List[SlotInfo]:
    """Round-robin-free block allocation: fill each host's slots in
    order (reference hosts.py get_host_assignments)."""
    total = sum(h.slots for h in hosts)
    if total < np:
        raise ValueError(
            f"requested np={np} exceeds available slots {total} "
            f"across hosts {[f'{h.hostname}:{h.slots}' for h in hosts]}")
    assignments = []
    rank = 0
    cross_sizes = {}
    # first pass: (host, local_rank) placement
    placements = []
    for hi, h in enumerate(hosts):
        for lr in range(h.slots):
            if rank >= np:
                break
            placements.append((hi, h.hostname, lr))
            cross_sizes[lr] = cross_sizes.get(lr, 0) + 1
            rank += 1
    local_sizes = {}
    for hi, name, lr in placements:
        local_sizes[hi] = local_sizes.get(hi, 0) + 1
    cross_ranks = {}
    for rank, (hi, name, lr) in enumerate(placements):
        cr = cross_ranks.get(lr, 0)
        cross_ranks[lr] = cr + 1
        assignments.append(SlotInfo(
            hostname=name, rank=rank, local_rank=lr,
            local_size=local_sizes[hi], cross_rank=cr,
            cross_size=cross_sizes[lr], size=np))
    return assignments

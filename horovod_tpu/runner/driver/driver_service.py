"""horovodrun's driver service + interface discovery (reference
``horovod/runner/driver/driver_service.py``).

The reference launches task services on every host and has them probe
each other to find the common routable NICs.  TPU pods share one
fabric, so ``get_common_interfaces`` resolves trivially when every
host is local, and performs the driver-side registration wait when a
real multi-host probe is requested (tasks must be started out-of-band
with ``runner.run_task``)."""

from ..common.service import driver_service
from ..common.util.hosts import parse_hosts
from ..util.network import filter_local_addresses, get_local_intfs


class HorovodRunDriverService(driver_service.BasicDriverService):
    NAME = "horovod driver service"

    def __init__(self, num_hosts, key, nics=None):
        super().__init__(num_hosts, HorovodRunDriverService.NAME, key,
                         nics)


class HorovodRunDriverClient(driver_service.BasicDriverClient):
    def __init__(self, driver_addresses, key, verbose=0,
                 match_intf=False):
        super().__init__(HorovodRunDriverService.NAME,
                         driver_addresses, key, verbose,
                         match_intf=match_intf)


def get_local_interfaces(settings):
    """Reference driver_service.py get_local_interfaces — the
    single-host NIC set."""
    if settings.verbose >= 2:
        print("All hosts are local, finding the interfaces "
              "with the address 127.0.0.1")
    return get_local_intfs(nic=settings.nics)


def get_common_interfaces(settings, all_host_names,
                          remote_host_names=None, fn_cache=None):
    """Reference driver_service.py:49/246 — resolve the NIC set shared
    by all hosts.  On a TPU pod every host rides the same fabric; when
    all hosts are local this returns the loopback set, otherwise the
    hosts' common interface is delegated to the KV-store launcher
    (proc_run ssh env handoff), which needs no NIC list — so the probe
    reduces to a reachability check of nothing and returns the
    configured NICs."""
    if remote_host_names is None:
        remote_host_names = filter_local_addresses(all_host_names)
    if len(remote_host_names) == 0:
        return get_local_interfaces(settings)
    # multi-host: the TPU launcher's control plane is address-based
    # (HMAC-HTTP), not interface-based; honor an explicit --nics and
    # otherwise signal "no constraint"
    if settings.nics:
        return set(settings.nics) if not isinstance(settings.nics, set) \
            else settings.nics
    return set()


def _all_host_names(settings):
    if not getattr(settings, "hosts", None):
        return []
    return [h.hostname for h in parse_hosts(settings.hosts)]

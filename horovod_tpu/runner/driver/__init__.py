"""Reference package path ``horovod.runner.driver``."""

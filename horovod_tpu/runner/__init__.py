"""Launcher package.  ``horovod_tpu.runner.run`` mirrors the
reference's programmatic entry (``horovod/runner/__init__.py:95``
``horovod.run``); the CLI lives in :mod:`.launch`."""

from .thread_launcher import run  # noqa: F401

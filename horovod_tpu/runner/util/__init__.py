"""Reference package path ``horovod.runner.util``."""

"""Remote command synthesis (reference
``horovod/runner/util/remote.py``).  The worker-spawn path
(proc_run.ssh_command) builds argv lists; these string-form helpers
are the reference surface used by spark/ray drivers."""

from ..common.util import env as env_util

SSH_COMMAND_PREFIX = ("ssh -o PasswordAuthentication=no "
                      "-o StrictHostKeyChecking=no")


def get_ssh_command(local_command, host, port=None, identity_file=None,
                    timeout_s=None):
    port_arg = f"-p {port}" if port is not None else ""
    identity_arg = f"-i {identity_file}" if identity_file else ""
    timeout_arg = (f"-o ConnectTimeout={timeout_s}"
                   if timeout_s is not None else "")
    return (f"{SSH_COMMAND_PREFIX} {host} {port_arg} {identity_arg} "
            f"{timeout_arg} {local_command}")


def get_remote_command(local_command, host, port=None,
                       identity_file=None, timeout_s=None):
    if env_util.is_kubeflow_mpi():
        return f"{env_util.KUBEFLOW_MPI_EXEC} {host} {local_command}"
    return get_ssh_command(local_command, host, port, identity_file,
                           timeout_s)

"""Thread helpers (reference ``horovod/runner/util/threads.py``)."""

import queue
import threading


def in_thread(target, args=(), kwargs=None, name=None, daemon=True,
              silent=False):
    """Start ``target`` in a thread and return the thread (reference
    threads.py in_thread).  ``silent`` swallows exceptions."""
    if silent:
        inner = target

        def target(*a, **kw):  # noqa: F811
            try:
                inner(*a, **kw)
            except Exception:  # noqa: BLE001 — caller opted out
                pass

    t = threading.Thread(target=target, args=args, kwargs=kwargs or {},
                         name=name, daemon=daemon)
    t.start()
    return t


def execute_function_multithreaded(fn, args_list,
                                   block_until_all_done=True,
                                   max_concurrent_executions=1000):
    """Run ``fn`` over ``args_list`` on a bounded thread pool
    (reference threads.py:20).  Returns ``{index: result}`` when
    blocking, else None."""
    result_queue = queue.Queue()
    worker_queue = queue.Queue()
    for i, arg in enumerate(args_list):
        worker_queue.put((i, list(arg)))

    def worker():
        while True:
            try:
                index, arg = worker_queue.get(block=False)
            except queue.Empty:
                return
            try:
                result_queue.put((index, False, fn(*arg)))
            except BaseException as exc:  # noqa: BLE001 — re-raised
                # at collection; a silently missing index would
                # surface as a KeyError far from the real failure
                result_queue.put((index, True, exc))

    threads = [in_thread(worker, daemon=not block_until_all_done)
               for _ in range(min(max_concurrent_executions,
                                  len(args_list)))]
    if not block_until_all_done:
        return None
    # join with timeout so signals can interrupt
    while any(t.is_alive() for t in threads):
        for t in threads:
            t.join(0.1)
    results = {}
    first_error = None
    while not result_queue.empty():
        index, is_error, res = result_queue.get()
        if is_error:
            first_error = first_error or res
        else:
            results[index] = res
    if first_error is not None:
        raise first_error
    return results


def on_event(event, target, args=(), kwargs=None, daemon=True,
             stop=None):
    """Run ``target`` when ``event`` fires; ``stop`` (a second event)
    cancels the wait (reference threads.py on_event)."""
    def waiter():
        while True:
            if event.wait(0.1):
                target(*args, **(kwargs or {}))
                return
            if stop is not None and stop.is_set():
                return

    return in_thread(waiter, daemon=daemon)

"""Launcher parameter cache (reference
``horovod/runner/util/cache.py``): ``horovodrun`` caches the results
of expensive launch-time checks keyed by a hash of the run parameters,
invalidated by staleness or parameter change."""

import datetime
import os
import pickle
import threading


class Cache:
    def __init__(self, cache_folder,
                 cache_staleness_threshold_in_minutes, parameters_hash):
        self._cache_file = os.path.join(cache_folder, "cache.bin")
        os.makedirs(cache_folder, exist_ok=True)
        content = None
        if os.path.isfile(self._cache_file):
            try:
                with open(self._cache_file, "rb") as f:
                    content = pickle.load(f)
            except Exception:  # noqa: BLE001 — corrupt cache: rebuild
                try:
                    os.remove(self._cache_file)
                except OSError:
                    pass
        if not isinstance(content, dict) or \
                content.get("parameters_hash") != parameters_hash:
            content = {"parameters_hash": parameters_hash}
            self._dump(content)
        self._content = content
        self._staleness = datetime.timedelta(
            minutes=cache_staleness_threshold_in_minutes)
        self._lock = threading.Lock()

    def get(self, key):
        with self._lock:
            timestamp, val = self._content.get(key, (None, None))
        if timestamp and timestamp >= \
                datetime.datetime.now() - self._staleness:
            return val
        return None

    def put(self, key, val):
        with self._lock:
            self._content[key] = (datetime.datetime.now(), val)
            self._dump(self._content)

    def _dump(self, content):
        tmp = self._cache_file + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(content, f)
        os.replace(tmp, self._cache_file)


def use_cache():
    """Decorator factory: route a function through the active Cache
    when one is bound (reference cache.py use_cache — the launcher
    sets ``fn.cache``)."""
    def decorator(fn):
        def wrapper(*args, **kwargs):
            cache = getattr(wrapper, "cache", None)
            if cache is not None:
                key = pickle.dumps((fn.__name__, args,
                                    sorted(kwargs.items())))
                hit = cache.get(key)
                if hit is not None:
                    return hit
            result = fn(*args, **kwargs)
            if cache is not None and result is not None:
                cache.put(key, result)
            return result

        wrapper.cache = None
        wrapper.__name__ = fn.__name__
        return wrapper

    return decorator

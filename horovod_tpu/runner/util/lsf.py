"""LSF scheduler detection (reference
``horovod/runner/util/lsf.py``).  TPU pods are not scheduled by LSF
(SURVEY §7.4 sanctions the MPI/jsrun/LSF launch legs as N/A); the
detection predicate is real so ``horovodrun`` behaves correctly when
a ported script runs inside an LSF allocation anyway, and the query
helpers fail with an explicit message instead of silently returning
wrong topology."""

import os


class LSFUtils:
    """LSF utilities (reference lsf.py:26)."""

    @staticmethod
    def using_lsf():
        """True when the current process was started by LSF."""
        return "LSB_JOBID" in os.environ

    @staticmethod
    def get_compute_hosts():
        """Hosts of this LSF allocation from LSB_HOSTS/LSB_MCPU_HOSTS
        (batch host excluded, duplicates collapsed in order)."""
        mcpu = os.environ.get("LSB_MCPU_HOSTS")
        if mcpu:
            toks = mcpu.split()
            return [h for h in toks[0::2]]
        hosts = os.environ.get("LSB_HOSTS", "").split()
        seen, out = set(), []
        for h in hosts:
            if h not in seen:
                seen.add(h)
                out.append(h)
        return out

    @staticmethod
    def get_num_processes():
        """Total slots in the allocation."""
        mcpu = os.environ.get("LSB_MCPU_HOSTS")
        if mcpu:
            toks = mcpu.split()
            return sum(int(n) for n in toks[1::2])
        return len(os.environ.get("LSB_HOSTS", "").split())

    @staticmethod
    def get_num_gpus():
        raise RuntimeError(
            "LSFUtils.get_num_gpus queries the IBM CSM stack, which "
            "does not exist on TPU hosts; device count on a TPU host "
            "is len(jax.devices()).")

    @staticmethod
    def get_num_cores():
        return os.cpu_count() or 1

    @staticmethod
    def get_num_threads():
        return 1

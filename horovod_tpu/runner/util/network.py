"""Host/interface discovery (reference
``horovod/runner/util/network.py``).  Implemented on the stdlib — no
psutil in this image: interface addresses come from
``socket.getaddrinfo`` plus a best-effort read of the routing trick
(UDP connect) the KV server already uses (http_server.local_ip)."""

import random
import socket

from . import threads

_local_addresses_cache = None


def _interface_addresses():
    """IPv4 addresses assigned to this host."""
    addresses = {"127.0.0.1"}
    hostname = socket.gethostname()
    for name in (hostname, "localhost"):
        try:
            for info in socket.getaddrinfo(name, None,
                                           socket.AF_INET):
                addresses.add(info[4][0])
        except socket.gaierror:
            continue
    try:
        # the address a default route would use (no packets sent)
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("10.255.255.255", 1))
        addresses.add(s.getsockname()[0])
        s.close()
    except OSError:
        pass
    return addresses


def get_local_host_addresses():
    global _local_addresses_cache
    if _local_addresses_cache is None:
        _local_addresses_cache = _interface_addresses()
    return _local_addresses_cache


def get_local_intfs(nic=None):
    """Interfaces carrying 127.0.0.1 (reference network.py:36 — used
    only as the single-host fallback NIC set).  ``nic`` may be a
    single name or a set of names (launch.py's --nics action builds a
    set)."""
    wanted = None
    if nic is not None:
        wanted = {nic} if isinstance(nic, str) else set(nic)
    intfs = set()
    try:
        names = {name for _, name in socket.if_nameindex()}
    except OSError:
        names = {"lo"}
    if "lo" in names and (wanted is None or "lo" in wanted):
        intfs.add("lo")
    elif wanted:
        intfs |= wanted & names
    return intfs


def resolve_host_address(host_name):
    try:
        return socket.gethostbyname(host_name)
    except socket.gaierror:
        return None


def filter_local_addresses(all_host_names):
    """Hosts from the list that do NOT resolve to a local address
    (reference network.py:54) — the set the launcher must ssh to."""
    local = get_local_host_addresses()
    resolved = threads.execute_function_multithreaded(
        resolve_host_address, [[h] for h in all_host_names])
    remote = []
    for i, name in enumerate(all_host_names):
        addr = resolved[i]
        if not addr or addr not in local:
            remote.append(name)
    return remote


def get_driver_ip(nics=None):
    """The address workers should dial back to (reference
    network.py get_driver_ip)."""
    from ..http.http_server import local_ip
    return local_ip()


def find_port(server_factory):
    """Bind ``server_factory(addr)`` to a random free port (reference
    network.py:74)."""
    min_port, max_port = 1024, 65536
    num_ports = max_port - min_port
    start = random.randrange(0, num_ports)
    for offset in range(num_ports):
        port = min_port + (start + offset) % num_ports
        try:
            server = server_factory(("", port))
            return server, port
        except OSError:
            continue
    raise RuntimeError("Unable to find a port to bind to.")

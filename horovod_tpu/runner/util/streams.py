"""In-memory rendezvous pipe (reference
``horovod/runner/util/streams.py``): single-slot, blocking on both
sides, usable with strings or bytes."""

import threading


class Pipe:
    def __init__(self):
        self._buf = None
        self._offs = 0
        self._cond = threading.Condition()
        self._closed = False

    def write(self, buf):
        with self._cond:
            while self._buf is not None and not self._closed:
                self._cond.wait()
            if self._closed:
                raise RuntimeError("Pipe is closed")
            self._buf = buf
            self._offs = 0
            self._cond.notify_all()

    def read(self, length=-1):
        with self._cond:
            while self._buf is None and not self._closed:
                self._cond.wait()
            if self._buf is None:
                return None
            if 0 < length < len(self._buf) - self._offs:
                end = self._offs + length
                out = self._buf[self._offs:end]
                self._offs = end
            else:
                out = self._buf[self._offs:]
                self._buf = None
            self._cond.notify_all()
            return out

    def flush(self):
        pass

    def close(self):
        with self._cond:
            self._closed = True
            self._cond.notify_all()

"""In-process rank launcher.

The TPU-native replacement for per-rank process spawning on a single
host: one thread per rank, every rank bound to a device of the local
mesh.  This is the analogue of the reference's programmatic launcher
``horovod.run(func, np=...)`` (horovod/runner/__init__.py:95) for the
local case — multi-host jobs wrap this per host (runner/launch.py).

Threads are the right isolation level on TPU: a single process must own
the TPU client, and rank threads release the GIL while compiled
programs run, so per-rank Python overhead overlaps device execution.
"""

import threading

from ..common import basics


class _RankThread(threading.Thread):
    def __init__(self, fn, rank, args, kwargs):
        super().__init__(name=f"hvd-rank-{rank}", daemon=True)
        self.fn = fn
        self.rank = rank
        self.args = args
        self.kwargs = kwargs
        self.result = None
        self.error = None

    def run(self):
        basics.bind_rank(self.rank)
        try:
            self.result = self.fn(*self.args, **self.kwargs)
        except BaseException as exc:  # noqa: BLE001 — reported to caller
            self.error = exc
        finally:
            basics.unbind_rank()


def run(fn, np=None, args=(), kwargs=None, devices=None,
        keep_alive=False):
    """Run ``fn`` once per rank and return the list of per-rank results
    (reference horovod.run returns per-rank results,
    runner/__init__.py:95).

    ``np`` defaults to the number of local devices — one rank per TPU
    chip.  ``keep_alive`` leaves the runtime initialized after the
    function returns (for REPL / successive phases)."""
    kwargs = kwargs or {}
    already = basics.is_initialized()
    if np is None:
        if already:
            np = basics.engine().num_local
        elif devices is not None:
            np = len(devices)        # explicit devices win
        else:
            from ..common import env as env_mod
            # under the multi-process launcher the rank count comes
            # from the env contract — touching jax.devices() here
            # would initialize the XLA backend before init() can call
            # jax.distributed.initialize()
            np = env_mod.get_int(env_mod.HOROVOD_TPU_RANKS_PER_PROC, 0)
            if not np:
                import jax
                platform = env_mod.get_str(env_mod.HOROVOD_TPU_PLATFORM)
                devices = jax.devices(platform) if platform \
                    else jax.devices()
                np = len(devices)
    if not already:
        basics.init(num_ranks=np, devices=devices)
    elif basics.engine().num_local != np:
        raise ValueError(
            f"horovod_tpu already initialized with "
            f"{basics.engine().num_local} local ranks; cannot run with "
            f"np={np}")
    threads = [_RankThread(fn, r, args, kwargs) for r in range(np)]
    first_error = None
    try:
        for t in threads:
            t.start()
        # Monitor: the first rank failure aborts the engine so peers
        # blocked in collectives fail fast instead of deadlocking (the
        # reference ends all ranks with SHUT_DOWN_ERROR when one dies).
        pending = list(threads)
        while pending:
            still = []
            for t in pending:
                t.join(timeout=0.05)
                if t.is_alive():
                    still.append(t)
                elif t.error is not None and first_error is None:
                    first_error = (t.rank, t.error)
                    basics.engine().abort(t.error)
            pending = still
    finally:
        if not keep_alive and not already:
            basics.shutdown()
    if first_error is not None:
        rank, err = first_error
        nfail = sum(1 for t in threads if t.error is not None)
        raise RuntimeError(
            f"{nfail}/{np} ranks failed; first failure on rank "
            f"{rank}: {err!r}") from err
    return [t.result for t in threads]

"""Multi-process engine self-check: the coordinator/store-controller
protocol exercised end-to-end at N OS processes.

The reference validates its controller with multi-worker integration
runs (``test/integration/``, ``controller.h:78-110`` negotiation
contract); this module is the equivalent harness, reused by the CI
suite (``tests/test_runner.py``) and the driver's multi-chip dry run
(``__graft_entry__.dryrun_multichip``) so the part that must survive a
pod — negotiation, aux merging, join, dynamic process sets, and the
parallel package's dp/tp SPMD train step over a process-spanning mesh
— runs at real process boundaries, not rank threads.
"""

import os
import sys
import tempfile
import textwrap


def spmd_lm_check(steps: int = 3, expect_devices: int = None):
    """The pod-shape SPMD scenario, shared by the engine self-check
    worker and the CI test worker (tests/test_runner.py) so the two
    cannot drift: build a dp·tp mesh over ALL global devices
    (spanning the processes under multi-controller jax.distributed),
    train ``steps`` fused-CE LM steps, assert the loss decreases, and
    return the final loss (replication checks — engine allreduce —
    stay with the caller, whose rank-binding context differs).

    ``expect_devices`` asserts the GLOBAL device count — callers in
    multi-process mode must pass their world size so a
    jax.distributed regression (each process seeing only its local
    devices) fails loudly instead of silently degrading to a local
    mesh.  Returns None when the global device count is odd or < 2
    (no tp=2 mesh to build)."""
    import jax
    import jax.numpy as jnp
    import optax

    from .models import TransformerConfig
    from .parallel import MeshSpec, build_mesh, make_lm_train_step

    devs = jax.devices()
    n = len(devs)
    if expect_devices is not None and n != expect_devices:
        raise AssertionError(
            f"expected a {expect_devices}-device global mesh, got "
            f"{n} — jax.distributed is not spanning the processes")
    if n < 2 or n % 2:
        return None
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_layers=2,
                            n_heads=2, d_ff=64, max_seq_len=16,
                            dtype=jnp.float32)
    mesh = build_mesh(MeshSpec(dp=n // 2, tp=2), devs)
    toks = jax.random.randint(jax.random.PRNGKey(0), (n, 16), 0, 64)
    init, _, jit_step, tok_shd = make_lm_train_step(
        mesh, cfg, optimizer=optax.sgd(0.1), fused_ce=True,
        ce_chunks=4)
    # same seed everywhere -> identical initial state on every process
    state = init(jax.random.PRNGKey(1), toks)
    compiled, state = jit_step(state)
    td = jax.device_put(toks, tok_shd)
    losses = []
    for _ in range(steps):
        state, loss = compiled(state, td)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    return losses[-1]

#: Worker: one rank per process; every negotiated surface the
#: coordinator owns.  Asserts are exact (no float tolerance games).
ENGINE_CHECK_WORKER = textwrap.dedent("""
    import numpy as np
    import horovod_tpu as hvd

    hvd.init()
    r, n = hvd.rank(), hvd.size()

    # negotiated allreduce
    out = hvd.allreduce(np.ones(8, np.float32) * (r + 1),
                        op=hvd.Average)
    assert np.allclose(out, np.mean([i + 1.0 for i in range(n)])), \\
        (r, out)

    # grouped mixed-dtype allreduce (per-dtype fused submissions)
    a, b = hvd.grouped_allreduce(
        [np.full(4, r + 1, np.float32), np.full(3, r + 1, np.int64)],
        op=hvd.Sum, name="gmix")
    tot = sum(i + 1 for i in range(n))
    assert np.array_equal(a, np.full(4, float(tot), np.float32)), a
    assert np.array_equal(b, np.full(3, tot, np.int64)), b

    # allgather with uneven first dims: the coordinator merges the
    # per-process aux dim0 tables in rank order
    g = hvd.allgather(np.full((r % 3 + 1, 2), float(r), np.float32),
                      name="ag")
    assert g.shape == (sum(i % 3 + 1 for i in range(n)), 2), g.shape
    off = 0
    for j in range(n):
        rows = j % 3 + 1
        assert np.allclose(g[off:off + rows], float(j)), (r, j)
        off += rows

    # alltoall with non-uniform splits (rank j sends k+1 rows to
    # rank k); exact delivery across every process boundary
    splits = [k + 1 for k in range(n)]
    x = np.arange(sum(splits), dtype=np.float32) + 1000.0 * r
    out, recv = hvd.alltoall(x, splits=splits, name="a2a")
    assert list(recv) == [r + 1] * n, (r, recv)
    off = 0
    for j in range(n):
        src_off = sum(splits[:r])
        want = np.arange(r + 1, dtype=np.float32) + src_off + 1000.0 * j
        assert np.allclose(out[off:off + r + 1], want), (r, j)
        off += r + 1

    # dynamic process sets: add (evens), reduce inside, remove —
    # registration and the draining removal barrier are collective
    evens = [i for i in range(n) if i % 2 == 0]
    ps = hvd.add_process_set(evens)
    if r in evens:
        sub = hvd.allreduce(np.ones(2, np.float32), op=hvd.Sum,
                            name="psred", process_set=ps)
        assert np.allclose(sub, float(len(evens))), sub
    hvd.remove_process_set(ps)

    # join: every rank but the last submits one extra allreduce; the
    # joined ranks' zero contributions must merge (reference Join op)
    if r != n - 1:
        tail = hvd.allreduce(np.ones(2, np.float32), op=hvd.Sum,
                             name="tail")
        assert np.allclose(tail, float(n - 1)), tail
    last = hvd.join()
    assert last >= 0, last

    # the SPMD pod shape: the parallel package's dp/tp train step over
    # a global mesh SPANNING the processes (multi-controller jax) —
    # every process holds one device, XLA inserts the cross-process
    # collectives, the fused-CE loss trains and stays replicated
    # (scenario shared with tests/test_runner.py via spmd_lm_check)
    from horovod_tpu.selfcheck import spmd_lm_check
    l1 = spmd_lm_check(steps=2, expect_devices=n)
    if l1 is not None:
        same = hvd.allreduce(np.array([l1], np.float32), op=hvd.Average)
        assert abs(float(same[0]) - l1) < 1e-6, (same, l1)

    print(f"ENGINE-CHECK OK {r}/{n}")
    hvd.shutdown()
""")


def run_engine_selfcheck(np_procs: int = 8, start_timeout: float = 420):
    """Launch ``np_procs`` one-rank worker PROCESSES (jax.distributed
    over virtual CPU devices + the HTTP store controller) through the
    real launcher and run the negotiated-op scenario.  Raises on any
    nonzero worker exit."""
    from .runner.proc_run import launch_procs

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with tempfile.TemporaryDirectory() as td:
        script = os.path.join(td, "engine_check_worker.py")
        with open(script, "w") as f:
            f.write(ENGINE_CHECK_WORKER)
        codes = launch_procs(
            [sys.executable, script], np=np_procs, platform="cpu",
            env={"PYTHONPATH": repo}, start_timeout=start_timeout)
    if codes != [0] * np_procs:
        raise RuntimeError(
            f"engine self-check failed at np={np_procs}: exit codes "
            f"{codes}")
    return True

"""Steady-state negotiation bypass (ROADMAP item 2; the reference's
``response_cache.cc`` CoordinateCacheAndState idea, Horovod paper
arXiv:1802.05799 §4, rebuilt as the DEGRADED MODE of a crash-tolerant
control plane).

Training loops are periodic: after warm-up the coordinator schedules
the identical response list every cycle, yet every cycle still pays a
ready-POST + long-poll round-trip per process against one
launcher-hosted box.  The bypass removes the coordinator from the
steady state entirely:

1. **Detect** — each worker fingerprints the batch-response list of
   every completed negotiation cycle (the ``_fingerprint`` seam of
   core/store_controller.py extended from per-entry to per-cycle).
   Once the list is identical for K consecutive cycles
   (``HOROVOD_BYPASS_AFTER_CYCLES``), the worker votes its
   fingerprint to the coordinator (``bypass_ready`` verb).
2. **Arm** — when EVERY proc votes the same fingerprint, the
   coordinator appends one ``bypass_arm`` record to the response log.
   Consumed in log order, that record is the coordinated instant all
   workers switch modes — no two-phase commit needed.
3. **Run** — each armed cycle, once the cached keys are locally
   ready, the ranks agree via a cheap all-to-all bitvector exchange
   over the EXISTING collective path (a 1-element MIN allreduce on
   the global mesh: my-state-matches = 1).  Unanimity executes the
   cached response list with no coordinator traffic; any dissent — a
   new tensor, a changed wire dtype, a resize, a stall past the wait
   bound, a desynced rank — makes the fallback UNANIMOUS too (the
   vote result is identical on every rank), so all procs re-enter
   full negotiation together and the coordinator re-validates
   everything cross-process.

Because armed workers never touch the coordinator, training steps
keep flowing while the rendezvous service is down or restarting from
its journal — "fast path" and "survives coordinator death" are one
mechanism (docs/fault_tolerance.md "Coordinator crash survival").

Safety argument: vote 1 means "my locally-ready entries match MY
cached response list exactly"; the arm handshake proved every proc
cached the SAME list (same fingerprint), so unanimity implies
cross-process meta consistency — the same invariant the
coordinator's ``_validate`` enforces on the slow path.
"""

import hashlib
import json
import time

#: Ops whose metas are identical across steps — only all-cacheable,
#: global-process-set cycles are bypass-eligible.  ONE definition
#: shared with the coordinator's response-cache eligibility and the
#: worker controller's hit path (runner/http/contract.py).
from ..runner.http.contract import CACHEABLE_TYPES  # noqa: F401


# hvdlint: seam[determinism]
def sanitize_response(resp):
    """Strip the per-step volatile fields (trace ids, cache ids) from
    a batch response, keeping exactly what re-execution needs."""
    return {"kind": "batch", "keys": list(resp.get("keys", [])),
            "metas": resp.get("metas", {}),
            "aux": resp.get("aux", {})}


# hvdlint: seam[determinism]
def cycle_fingerprint(responses):
    """Canonical identity of one negotiation cycle's response list."""
    return hashlib.sha1(
        json.dumps(responses, sort_keys=True).encode()).hexdigest()


# hvdlint: seam[determinism]
def meta_fingerprint(meta):
    """Canonical identity of one negotiation meta (aux/error excluded
    — the per-entry ``_fingerprint`` contract of
    core/store_controller.py, shared so the two seams cannot
    drift)."""
    return json.dumps(
        {k: v for k, v in meta.items() if k not in ("aux", "error")},
        sort_keys=True)


def _eligible(resp):
    metas = resp.get("metas", {})
    if not metas or len(metas) != len(resp.get("keys", [])):
        return False
    return all(m.get("type") in CACHEABLE_TYPES and m.get("ps", 0) == 0
               for m in metas.values())


class BypassState:
    """Per-engine bypass tracker + armed-mode state machine.

    Driven from the engine background thread (plus ``poison`` from
    rank threads); no internal locking — every mutating call happens
    on the engine loop, and ``poison`` is a benign one-shot flag."""

    def __init__(self, after_cycles=5, wait_secs=10.0):
        self.K = int(after_cycles)
        self.wait_secs = float(wait_secs)
        #: armed-mode state
        self.active = False
        self.broken = False     # armed without the list: vote 0 once
        self.fp = None
        self.responses = []     # sanitized batch responses, in order
        self.keys = set()
        self.key_fps = {}       # key -> meta fingerprint
        self.cycles = 0         # executed bypass cycles
        #: cumulative per-key trace-id sequence: every proc executes
        #: the same responses in the same order, so the sequence is
        #: identical everywhere (never reset — ids must not reuse)
        self.trace_seq = 0
        #: detection state
        self._cycle = []        # sanitized responses of the open cycle
        self._cycle_ok = True
        self._last_fp = None
        self._stable = 0
        self._candidate = None  # (fp, responses) of the last stable list
        #: armed-cycle wait state
        self._wait_t0 = None
        self._poison = None

    # -- detection (un-armed) ------------------------------------------------

    def observe_response(self, resp):
        """One coordinator response applied by the store cycle."""
        kind = resp.get("kind")
        if kind == "batch":
            s = sanitize_response(resp)
            if not _eligible(s):
                self._cycle_ok = False
            self._cycle.append(s)
        elif kind in ("error", "join_done", "dead", "stall"):
            # not a steady cycle: reset stability
            self._cycle_ok = False

    def cycle_complete(self):
        """Close the open cycle (the awaiting table drained).  Returns
        the fingerprint to VOTE to the coordinator once the list has
        been identical for K consecutive cycles, else None."""
        if not self._cycle:
            return None
        cycle, self._cycle = self._cycle, []
        ok, self._cycle_ok = self._cycle_ok, True
        if not ok:
            self._last_fp, self._stable = None, 0
            return None
        fp = cycle_fingerprint(cycle)
        if fp == self._last_fp:
            self._stable += 1
        else:
            self._last_fp, self._stable = fp, 1
        self._candidate = (fp, cycle)
        if self.K > 0 and self._stable >= self.K:
            return fp
        return None

    # -- arming --------------------------------------------------------------

    def on_arm(self, fp):
        """The coordinator's ``bypass_arm`` record arrived (in log
        order, so every proc arms at the same point in its response
        stream).  Arming is UNCONDITIONAL — a proc whose cycle moved
        on since it voted arms ``broken`` and votes 0 in the first
        agreement round, which makes the fallback unanimous instead
        of deadlocking the peers' vote collective."""
        if self.active:
            return
        self.active = True
        self._wait_t0 = None
        self._poison = None
        if self._candidate is not None and self._candidate[0] == fp:
            self.fp, self.responses = fp, list(self._candidate[1])
            self.keys = {k for r in self.responses for k in r["keys"]}
            self.key_fps = {
                k: meta_fingerprint(m)
                for r in self.responses
                for k, m in r["metas"].items()}
            self.broken = False
        else:
            self.broken = True

    def disarm(self):
        """Back to cold detection (fallback taken, or elastic reset)."""
        self.active = False
        self.broken = False
        self.fp = None
        self.responses = []
        self.keys = set()
        self.key_fps = {}
        self._cycle = []
        self._cycle_ok = True
        self._last_fp, self._stable = None, 0
        self._candidate = None
        self._wait_t0 = None
        self._poison = None

    def poison(self, reason):
        """Force the next agreement round to vote 0 (join requested,
        process-set churn — anything the cached list cannot cover)."""
        self._poison = reason

    # -- armed-cycle decisions -----------------------------------------------

    # hvdlint: seam[determinism]
    def decide(self, awaiting_fps, foreign, now=None):
        """One armed-cycle decision from the engine loop.

        ``awaiting_fps``: {key: meta_fingerprint} of the global set's
        awaiting entries; ``foreign``: entries awaiting on any other
        process set.  Returns None (keep waiting), or
        ``(vote, reason)`` — vote 1 to execute the cached list, vote 0
        to force the unanimous fallback."""
        now = time.monotonic() if now is None else now
        if self.broken:
            return 0, "unarmed"
        if self._poison:
            return 0, self._poison
        if foreign:
            return 0, "mismatch"
        keys = set(awaiting_fps)
        if not keys:
            return None
        if keys - self.keys:
            # a tensor outside the cached list can never match
            return 0, "mismatch"
        if keys == self.keys:
            for k, fp in awaiting_fps.items():
                if fp != self.key_fps[k]:
                    # same name, different params (wire-dtype flip,
                    # reshape): renegotiate
                    return 0, "mismatch"
            self._wait_t0 = None
            return 1, None
        # partial: some cached keys not locally ready yet — wait, but
        # bounded, so a genuinely stalled/desynced rank degrades into
        # full negotiation (where stall attribution lives) instead of
        # wedging the job
        if self._wait_t0 is None:
            self._wait_t0 = now
        if now - self._wait_t0 > self.wait_secs:
            self._wait_t0 = None
            return 0, "timeout"
        return None

"""ZeRO-grade weight-update sharding — the engine-path core.

The pod-scale playbook (arXiv:1909.09756) pairs distributed gradient
summation with *weight-update sharding*: each rank REDUCESCATTERs the
gradients, updates only its 1/dp shard of the parameters + optimizer
state, and ALLGATHERs the updated parameters back.  The optimizer
state shrinks by dp and the full allreduce becomes reducescatter +
allgather — the same bytes, but the update compute and its state are
distributed.

This module is the framework-agnostic half shared by the torch and
TF/Keras ``DistributedOptimizer(sharded=True)`` frontends (the
jax/compiled path builds the same decomposition *inside* one XLA
program — ops/compiled.py ``make_compiled_train_step(sharded=True)``):

* :class:`ShardPlan` — the deterministic shard layout.  Parameters
  pack into contiguous flat buckets derived from the SAME rule the
  engine's fusion uses (matching (dtype, param-group) runs under the
  fusion threshold), and each bucket splits across ranks with the
  engine executor's exact ``chunk_sizes`` rule — so bucket boundaries
  and shard boundaries coincide by construction and the reducescatter
  output IS the shard (no gather-regather churn).  The layout
  fingerprint rides every collective as ``Request.shard_fp`` and is
  cross-rank validated like the wire pair and algorithm: ranks
  disagreeing on the layout would update different slices against
  each other, so a mismatch fails LOUDLY, not silently skewed.
* :class:`ShardedUpdater` — the wire: gradients go out as grouped
  reducescatter on the existing per-hop quantized wire (with EF21
  error feedback host-side, exactly like the dense optimizer's
  residuals), and the updated-param allgather rides the same wire
  with its OWN error-feedback state — the master shard stays full
  width on its owning rank, the transmitted params are
  ``deq(q(master + residual))`` and every rank (owner included)
  installs the decoded value, so ranks stay bit-identical and the
  quantization error dithers instead of accumulating into the weights.

Elastic contract: a resize (or an autotune shard-layout flip) re-shards
DETERMINISTICALLY — :meth:`ShardedUpdater.gather_full` reconstructs
the full flat state from the shards (an exact allgather), and the new
plan re-slices it; error-feedback residuals are dropped at every
re-shard (``reset_wire_state``), never re-injected at stale shapes.
"""

import hashlib
import json
import threading

import numpy as np

SHARD_LAYOUT_CHOICES = ("bucket", "flat")


def normalize_shard_layout(layout):
    """'bucket' (default: shard boundaries from fusion buckets) |
    'flat' (one bucket per (dtype, group): fewest, largest
    collectives).  The autotuner sweeps this as its eighth
    dimension."""
    if layout is None or layout == "":
        return "bucket"
    layout = str(layout).strip().lower()
    if layout not in SHARD_LAYOUT_CHOICES:
        raise ValueError(
            f"shard layout must be one of {SHARD_LAYOUT_CHOICES}, "
            f"got {layout!r}")
    return layout


def compression_wire(compression):
    """Wire format a Compression marker/compressor asks for: the
    quantized markers carry ``wire`` ('int8'/'int4'); the fp16/bf16
    CAST compressors carry ``wire_dtype`` (a framework dtype).  Under
    sharded mode the cast happens on the collective wire itself, so
    both spellings resolve to the updater's wire string instead of
    the 16-bit request being silently dropped (works on torch and tf
    dtypes alike via their string forms)."""
    w = getattr(compression, "wire", None)
    if w:
        return w
    wd = getattr(compression, "wire_dtype", None)
    if wd is None:
        return None
    name = str(wd)
    if "bfloat16" in name:
        return "bf16"
    if "float16" in name:
        return "fp16"
    return None


def chunk_sizes(n, dp):
    """THE uneven split rule: as even as possible, larger chunks on
    lower ranks (reference collective_operations.cc
    ReducescatterOp::ComputeOutputShapeForRank).  The engine
    executor's reducescatter (xla_ops.MeshExecutor.chunk_sizes)
    delegates here, so the shard plan can never drift from what the
    scatter actually returns."""
    base = n // dp
    extra = n % dp
    return [base + (1 if r < extra else 0) for r in range(dp)]


def overlap_bucket_splits(sizes, itemsize, bucket_bytes, align=1):
    """THE bucketization rule for bucket-granular comm/compute
    overlap (ops/compiled.py and this module's sharded step both
    delegate here, so their bucket boundaries can never drift).

    Splits ``sizes`` (per-member element counts, plan order) into
    contiguous ``(start, stop)`` member-index runs.  A run closes at
    the first member where the cumulative payload reaches
    ``bucket_bytes`` AND the cumulative element count from member 0
    is a multiple of ``align`` — with ``align`` = the quantization
    BLOCK, every bucket boundary then falls on a block-grid boundary
    of the grouped flat buffer, which is what keeps the quantized
    wire bitwise identical to the single grouped program.
    ``bucket_bytes`` <= 0 (or None) means no split: one bucket, the
    grouped pre-overlap behavior."""
    n = len(sizes)
    if not n:
        return []
    if bucket_bytes is None or bucket_bytes <= 0:
        return [(0, n)]
    splits = []
    start, run_elems, total_elems = 0, 0, 0
    for i, sz in enumerate(sizes):
        run_elems += int(sz)
        total_elems += int(sz)
        full = run_elems * itemsize >= bucket_bytes
        aligned = align <= 1 or total_elems % align == 0
        if i == n - 1 or (full and aligned):
            splits.append((start, i + 1))
            start, run_elems = i + 1, 0
    return splits


def overlap_segment_bounds(n, itemsize, bucket_bytes, unit=1):
    """Within-buffer companion to :func:`overlap_bucket_splits`: split
    one flat buffer of ``n`` elements into contiguous ``(start,
    stop)`` segments of at most ~``bucket_bytes`` each, every segment
    length a multiple of ``unit`` (the compiled sharded step passes
    R, or BLOCK*R under a quantized wire, so each segment scatters
    evenly into whole-block shards).  ``n`` itself must be a multiple
    of ``unit`` (the sharded step's pad rule guarantees it).
    ``bucket_bytes`` <= 0 means no split."""
    if n <= 0:
        return []
    if bucket_bytes is None or bucket_bytes <= 0:
        return [(0, n)]
    seglen = max(unit, (bucket_bytes // itemsize) // unit * unit)
    return [(s, min(s + seglen, n)) for s in range(0, n, seglen)]


class ShardBucket:
    """One contiguous flat buffer: members laid out back to back, the
    dp split at ``chunks`` boundaries."""

    __slots__ = ("index", "dtype", "group", "members", "n", "chunks",
                 "rank_offsets")

    def __init__(self, index, dtype, group, members, dp):
        self.index = index
        self.dtype = dtype          # numpy dtype string
        self.group = group          # frontend param-group index
        #: [(key, size, shape)] in pack order
        self.members = members
        self.n = sum(m[1] for m in members)
        self.chunks = chunk_sizes(self.n, dp)
        offs = np.cumsum([0] + self.chunks[:-1])
        self.rank_offsets = [int(o) for o in offs]

    def shard_slice(self, pos):
        """[start, end) of rank-position ``pos``'s shard in the flat
        bucket."""
        start = self.rank_offsets[pos]
        return start, start + self.chunks[pos]


class ShardPlan:
    """Deterministic shard layout over an ordered parameter list.

    ``specs`` is ``[(key, shape, dtype_str, group_index)]`` in the
    frontend's canonical order (param_groups order for torch, the
    variable list for TF).  Buckets close when the (dtype, group)
    signature changes or the running size crosses ``threshold_bytes``
    ('bucket' layout); the 'flat' layout ignores the threshold and
    packs each (dtype, group) run into one bucket.
    """

    def __init__(self, specs, dp, threshold_bytes, layout="bucket"):
        if dp < 1:
            raise ValueError(f"dp must be >= 1, got {dp}")
        self.dp = int(dp)
        self.layout = normalize_shard_layout(layout)
        self.threshold_bytes = int(threshold_bytes)
        self.buckets = []
        cur, cur_sig, cur_bytes = [], None, 0
        for key, shape, dtype, group in specs:
            size = int(np.prod(shape, dtype=np.int64)) if shape else 1
            itemsize = 2 if dtype == "bfloat16" else \
                np.dtype(dtype).itemsize
            nbytes = size * itemsize
            sig = (dtype, group)
            closes = cur and (
                sig != cur_sig
                or (self.layout == "bucket"
                    and cur_bytes + nbytes > self.threshold_bytes))
            if closes:
                self.buckets.append(ShardBucket(
                    len(self.buckets), cur_sig[0], cur_sig[1], cur,
                    self.dp))
                cur, cur_bytes = [], 0
            cur.append((key, size, tuple(shape)))
            cur_bytes += nbytes
            cur_sig = sig
        if cur:
            self.buckets.append(ShardBucket(
                len(self.buckets), cur_sig[0], cur_sig[1], cur,
                self.dp))
        self.total_elems = sum(b.n for b in self.buckets)

    def fingerprint(self):
        """Stable layout identity: every rank derives this from its
        own spec list; it rides each collective as ``shard_fp`` and
        the engine/coordinator reject a cross-rank mismatch before
        anything executes."""
        doc = [self.layout, self.dp,
               [[b.dtype, b.group,
                 [[k, s, list(shp)] for k, s, shp in b.members]]
                for b in self.buckets]]
        return hashlib.md5(
            json.dumps(doc, sort_keys=True).encode()).hexdigest()[:16]

    def local_elems(self, pos):
        return sum(b.chunks[pos] for b in self.buckets)

    # -- flat pack/unpack ----------------------------------------------------

    def pack(self, bucket, arrays_by_key, dtype=None):
        """Member arrays → one flat bucket buffer (missing members
        contribute zeros — the unused-parameter case)."""
        dt = np.dtype(dtype or np.float32)
        buf = np.zeros(bucket.n, dtype=dt)
        off = 0
        for key, size, shape in bucket.members:
            a = arrays_by_key.get(key)
            if a is not None:
                buf[off:off + size] = np.asarray(a, dtype=dt).ravel()
            off += size
        return buf

    def unpack(self, bucket, buf):
        """Flat bucket buffer → {key: array} views (reshaped)."""
        out = {}
        off = 0
        for key, size, shape in bucket.members:
            out[key] = buf[off:off + size].reshape(shape)
            off += size
        return out


class ShardedUpdater:
    """The sharded weight-update wire for host-side (engine path)
    frontends.  Owns: the grouped reducescatter of gradient buckets,
    the grouped allgather of updated param shards (both over the
    configured wire, each with its own EF residual state), the layout
    fingerprint threading, and the telemetry that proves the ÷dp
    claim from a scrape."""

    def __init__(self, plan, process_set=None, op=None,
                 grad_wire=None, param_wire=None, name="shard"):
        from ..ops.api import Average

        self.plan = plan
        self.process_set = process_set
        self.op = Average if op is None else op
        #: wire for the gradient reducescatter — None defers to the
        #: engine's process-wide default (the per-entry latch applies)
        self.grad_wire = grad_wire
        #: wire for the updated-param allgather; quantized formats
        #: keep a per-bucket EF residual here
        self.param_wire = param_wire
        self.name = name
        self.shard_fp = plan.fingerprint()
        self._grad_residuals = {}
        self._param_residuals = {}
        self._lock = threading.Lock()
        # a step quarantine (core/integrity.py) must reset the dual
        # wires' residuals too: the in-place rollback never reaches
        # the elastic reset that would
        from .integrity import register_wire_state
        register_wire_state(self)

    # -- position ------------------------------------------------------------

    def my_pos(self):
        """This rank's position in the process set (the shard index)."""
        from ..common import basics
        from ..common.process_sets import ProcessSet

        eng = basics.engine()
        ps_id = 0
        if isinstance(self.process_set, ProcessSet):
            ps_id = self.process_set.process_set_id or 0
        elif self.process_set is not None:
            ps_id = int(self.process_set)
        ps = eng.process_sets[ps_id]
        rank = basics.context().rank
        return ps.index[rank]

    # -- gradient reducescatter ---------------------------------------------

    def _ef_inject_grad(self, i, buf, wire):
        """EF21 on the gradient wire: inject last step's quantization
        residual, measure this one (ops/quantize.py is a pure function
        of x, so the host-side re-encode matches the engine's wire)."""
        from ..ops import quantize as qz

        x = buf.astype(np.float32, copy=True)
        r = self._grad_residuals.get(i)
        if r is not None and r.shape == x.shape:
            x = x + r
        self._grad_residuals[i] = x - qz.np_fake_quantize_wire(x, wire)
        return x.astype(buf.dtype, copy=False)

    def reduce_grads(self, bucket_buffers):
        """Grouped reducescatter of the flat gradient buckets (one
        jointly-negotiated group per dtype — the shard layout IS the
        fusion layout).  Returns this rank's shard per bucket."""
        from ..ops import api
        from .. import telemetry

        wire = self.grad_wire
        bufs = list(bucket_buffers)
        if wire in ("int8", "int4"):
            bufs = [self._ef_inject_grad(i, b, wire)
                    for i, b in enumerate(bufs)]
        by_dtype = {}
        for i, b in enumerate(bufs):
            by_dtype.setdefault(str(b.dtype), []).append(i)
        handles = []
        for dt in sorted(by_dtype):
            idxs = by_dtype[dt]
            handles.append((idxs, api.grouped_reducescatter_async(
                [bufs[i] for i in idxs], op=self.op,
                name=f"{self.name}.rs.{dt}",
                process_set=self.process_set
                if self.process_set is not None else 0,
                wire_dtype=wire, shard_fp=self.shard_fp)))
        out = [None] * len(bufs)
        for idxs, h in handles:
            res = api.synchronize(h)
            if not isinstance(res, (list, tuple)):
                res = [res]
            for i, r in zip(idxs, res):
                out[i] = np.asarray(r)
        telemetry.count_sharded_update()
        return out

    # -- updated-param allgather ---------------------------------------------

    def gather_params(self, shard_buffers, async_=False):
        """Allgather the updated param shards back into full flat
        buckets, over ``param_wire``.  Quantized wires ship the codec
        (codes + bf16 scales) with an EF residual per bucket: the
        master shard never leaves full width on its owner, the decoded
        value is what EVERY rank (owner included) installs, and the
        caller must therefore overwrite its own params from the
        returned buffers too.  ``async_=True`` returns a zero-arg
        completion callable instead of blocking — the pp runtime
        overlaps it into the next microbatch's forward."""
        wire = self.param_wire
        if wire in ("int8", "int4"):
            waiter = self._gather_quantized(shard_buffers, wire)
        elif wire in ("fp16", "bf16"):
            waiter = self._gather_cast16(shard_buffers, wire)
        else:
            waiter = self._gather_plain(shard_buffers)
        return waiter if async_ else waiter()

    def _gather_plain(self, shards):
        from ..ops import api

        by_dtype = {}
        for i, s in enumerate(shards):
            by_dtype.setdefault(str(s.dtype), []).append(i)
        handles = []
        for dt in sorted(by_dtype):
            idxs = by_dtype[dt]
            handles.append((idxs, api.grouped_allgather_async(
                [shards[i] for i in idxs],
                name=f"{self.name}.ag.{dt}",
                process_set=self.process_set
                if self.process_set is not None else 0,
                shard_fp=self.shard_fp)))

        def wait():
            from ..ops import api as _api
            out = [None] * len(shards)
            for idxs, h in handles:
                res = _api.synchronize(h)
                if not isinstance(res, (list, tuple)):
                    res = [res]
                for i, r in zip(idxs, res):
                    out[i] = np.asarray(r)
            return out
        return wait

    def _gather_cast16(self, shards, wire):
        from ..ops import api

        wdt = np.dtype(np.float16) if wire == "fp16" else _bf16()
        sent, dtypes = [], []
        for i, s in enumerate(shards):
            x = s.astype(np.float32, copy=True)
            r = self._param_residuals.get(i)
            if r is not None and r.shape == x.shape:
                x = x + r
            tx = x.astype(wdt)
            self._param_residuals[i] = x - tx.astype(np.float32)
            sent.append(tx)
            dtypes.append(s.dtype)
        h = api.grouped_allgather_async(
            sent, name=f"{self.name}.ag16",
            process_set=self.process_set
            if self.process_set is not None else 0,
            shard_fp=self.shard_fp)

        def wait():
            from ..ops import api as _api
            res = _api.synchronize(h)
            if not isinstance(res, (list, tuple)):
                res = [res]
            return [np.asarray(r).astype(dt)
                    for r, dt in zip(res, dtypes)]
        return wait

    def _gather_quantized(self, shards, wire):
        """Codec allgather: encode my shard once (with EF), gather
        codes + scales for all ranks, decode every rank's segment —
        the actual 1 B/elem (int8) / 0.5 B/elem (int4) wire, not a
        full-width gather."""
        from ..ops import api
        from ..ops import quantize as qz

        int4 = wire == "int4"
        encode = qz.np_quantize_blockwise_int4 if int4 \
            else qz.np_quantize_blockwise
        codes, scales, dtypes = [], [], []
        for i, s in enumerate(shards):
            x = s.astype(np.float32, copy=True).ravel()
            r = self._param_residuals.get(i)
            if r is not None and r.shape == x.shape:
                x = x + r
            q, sc, n = encode(x)
            deq = (qz.np_dequantize_blockwise_int4(q, sc, n)
                   if int4 else qz.np_dequantize_blockwise(q, sc, n))
            self._param_residuals[i] = x - deq[:x.size]
            codes.append(q)
            scales.append(np.asarray(sc))
            dtypes.append(s.dtype)
        hq = api.grouped_allgather_async(
            codes, name=f"{self.name}.agq",
            process_set=self.process_set
            if self.process_set is not None else 0,
            shard_fp=self.shard_fp)
        hs = api.grouped_allgather_async(
            scales, name=f"{self.name}.ags",
            process_set=self.process_set
            if self.process_set is not None else 0,
            shard_fp=self.shard_fp)
        plan = self.plan

        def wait():
            from ..ops import api as _api
            gq = _api.synchronize(hq)
            gs = _api.synchronize(hs)
            if not isinstance(gq, (list, tuple)):
                gq, gs = [gq], [gs]
            out = []
            for b, q_all, s_all, dt in zip(plan.buckets, gq, gs,
                                           dtypes):
                full = np.empty(b.n, np.float32)
                qo = so = 0
                for pos in range(plan.dp):
                    m = b.chunks[pos]
                    nb = -(-m // qz.BLOCK) if m else 0
                    qlen = nb * (qz.BLOCK // 2 if int4 else qz.BLOCK)
                    seg_q = np.asarray(q_all)[qo:qo + qlen]
                    seg_s = np.asarray(s_all)[so:so + nb]
                    if m:
                        deq = (qz.np_dequantize_blockwise_int4(
                            seg_q, seg_s, nb * qz.BLOCK) if int4 else
                            qz.np_dequantize_blockwise(
                                seg_q, seg_s, nb * qz.BLOCK))
                        start = b.rank_offsets[pos]
                        full[start:start + m] = deq[:m]
                    qo += qlen
                    so += nb
                out.append(full.astype(dt, copy=False))
            return out
        return wait

    # -- re-shard ------------------------------------------------------------

    def gather_full(self, shard_buffers):
        """EXACT (full-width) allgather of per-bucket shard state —
        the deterministic re-shard primitive: state_dict saves gather
        here, and a resize/layout flip reconstructs full flat buffers
        before re-slicing under the new plan.  Never rides a lossy
        wire: optimizer state must survive a re-shard bit-exactly."""
        return self._gather_plain(
            [np.ascontiguousarray(s) for s in shard_buffers])()

    def reset_wire_state(self):
        """Drop every EF residual (gradient AND param wires) plus the
        compiled path's device residuals — the elastic/resize hook
        (docs/concepts.md residual lifecycle): stale residual shapes
        from the old layout must never be injected into the new."""
        with self._lock:
            self._grad_residuals.clear()
            self._param_residuals.clear()
        from ..ops.compiled import reset_ef_state
        reset_ef_state()

    # -- telemetry -----------------------------------------------------------

    def record_state_bytes(self, shard_state_bytes):
        """Export the ÷dp evidence: ``scope="shard"`` is what this
        rank actually holds, ``scope="full"`` what the dense optimizer
        would hold (shard bytes scaled by total/local elements) — a
        scrape divides them and reads dp."""
        from .. import telemetry

        pos = self.my_pos()
        local = max(self.plan.local_elems(pos), 1)
        full = int(round(shard_state_bytes
                         * self.plan.total_elems / local))
        telemetry.set_optimizer_state_bytes("shard",
                                            int(shard_state_bytes))
        telemetry.set_optimizer_state_bytes("full", full)


def _bf16():
    import ml_dtypes
    return np.dtype(ml_dtypes.bfloat16)

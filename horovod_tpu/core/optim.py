"""Gaussian-process regression + Bayesian optimization for autotuning.

Reference: ``horovod/common/optim/gaussian_process.{h,cc}`` and
``optim/bayesian_optimization.{h,cc}`` (Eigen + LBFGS).  Numpy is the
right tool here — the GP fits tens of points over a 2-4 dim space, so
closed-form Cholesky solves beat a native reimplementation.
"""

import numpy as np


class GaussianProcess:
    """RBF-kernel GP regression (reference gaussian_process.h Matern
    is close enough to RBF at this sample scale)."""

    def __init__(self, length_scale=1.0, signal_variance=1.0,
                 noise=1e-4):
        self.length_scale = length_scale
        self.signal_variance = signal_variance
        self.noise = noise
        self._X = None
        self._y = None
        self._L = None
        self._alpha = None

    def _kernel(self, A, B):
        d2 = ((A[:, None, :] - B[None, :, :]) ** 2).sum(-1)
        return self.signal_variance * np.exp(
            -0.5 * d2 / self.length_scale ** 2)

    def fit(self, X, y):
        X = np.atleast_2d(np.asarray(X, dtype=np.float64))
        y = np.asarray(y, dtype=np.float64)
        self._ymean = y.mean() if y.size else 0.0
        yc = y - self._ymean
        K = self._kernel(X, X) + self.noise * np.eye(len(X))
        self._L = np.linalg.cholesky(K)
        self._alpha = np.linalg.solve(
            self._L.T, np.linalg.solve(self._L, yc))
        self._X = X
        self._y = y

    def predict(self, Xs):
        Xs = np.atleast_2d(np.asarray(Xs, dtype=np.float64))
        Ks = self._kernel(Xs, self._X)
        mu = Ks @ self._alpha + self._ymean
        v = np.linalg.solve(self._L, Ks.T)
        var = np.clip(
            self.signal_variance - (v ** 2).sum(axis=0), 1e-12, None)
        return mu, np.sqrt(var)


def expected_improvement(mu, sigma, best, xi=0.01):
    """EI acquisition (reference bayesian_optimization.cc)."""
    from math import erf, sqrt

    imp = mu - best - xi
    z = imp / sigma
    cdf = 0.5 * (1.0 + np.vectorize(erf)(z / sqrt(2.0)))
    pdf = np.exp(-0.5 * z ** 2) / np.sqrt(2 * np.pi)
    return imp * cdf + sigma * pdf


class BayesianOptimizer:
    """Maximize a black-box score over a box of normalized [0,1]^d
    parameters (reference BayesianOptimization: EI over GP posterior,
    candidates sampled instead of LBFGS-polished)."""

    def __init__(self, dims, seed=0, noise=1e-3):
        self.dims = dims
        self._rng = np.random.RandomState(seed)
        self._X = []
        self._y = []
        self._gp = GaussianProcess(length_scale=0.3, noise=noise)

    def observe(self, x, score):
        self._X.append(np.asarray(x, dtype=np.float64))
        self._y.append(float(score))

    def suggest(self):
        if len(self._X) < 2:
            return self._rng.uniform(size=self.dims)
        self._gp.fit(np.stack(self._X), np.asarray(self._y))
        cands = self._rng.uniform(size=(256, self.dims))
        mu, sigma = self._gp.predict(cands)
        ei = expected_improvement(mu, sigma, max(self._y))
        return cands[int(np.argmax(ei))]

    def best(self):
        if not self._y:
            return None, None
        i = int(np.argmax(self._y))
        return self._X[i], self._y[i]

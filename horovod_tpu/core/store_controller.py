"""Worker-side distributed controller: negotiation over the launcher's
HTTP coordinator.

TPU-native replacement for the reference's controller transports
(``mpi/mpi_controller.cc`` MPI_Gatherv/Bcast, ``gloo/gloo_controller.cc``):
each worker *process* reports its locally-ready tensors to the
launcher-hosted coordinator (runner/http/http_server.py Coordinator)
and polls an ordered response log.  The log fixes the global execution
order, which is what lets every process issue identical compiled XLA
collectives — the SPMD invariant that replaces the reference's
explicit NCCL communicator synchronization.
"""

import json
import threading

from ..common import env as env_mod
from ..common.exceptions import HorovodInternalError
from ..runner.http.http_client import StoreClient, TieredStoreClient
from ..runner.http.contract import CACHEABLE_TYPES as _CACHEABLE_TYPES


# hvdlint: seam[determinism]
def _fingerprint(meta):
    """Canonical identity of a negotiation meta, aux/error excluded
    (reference response_cache.h:45-101 keys the LRU on tensor name +
    params the same way)."""
    return json.dumps(
        {k: v for k, v in meta.items() if k not in ("aux", "error")},
        sort_keys=True)


class StaleRoundError(HorovodInternalError):
    """The coordinator moved to a new elastic round: every in-flight
    collective of the old round must fail so workers fall into the
    restore/re-rendezvous path instead of hanging (reference: gloo
    context failure -> HorovodInternalError -> state.restore)."""


class StoreController:
    """One per worker process in multi-process jobs."""

    def __init__(self, addr, port, secret, proc_id, num_procs,
                 nlocal, poll_wait=5.0, round_id=0,
                 agg_addr=None, agg_port=None):
        if agg_addr is not None:
            # per-host aggregator tier: PRIMARY route is the host's
            # aggregator with a deliberately tight retry budget (a
            # silent aggregator must trigger the direct fallback in
            # seconds, not after the coordinator outage deadline);
            # the direct coordinator client keeps the
            # outage-spanning budget
            agg_client = StoreClient(agg_addr, agg_port, secret)
            fb = env_mod.get_float(
                env_mod.HOROVOD_AGG_FALLBACK_DEADLINE_SECONDS, 5.0)
            agg_client.retry_attempts = 3
            agg_client.retry_deadline = fb
            agg_client.outage_deadline = fb
            self.client = TieredStoreClient(
                agg_client, StoreClient(addr, port, secret))
            self.client.on_route_change = self._on_route_change
        else:
            self.client = StoreClient(addr, port, secret)
        self.proc_id = proc_id
        self.num_procs = num_procs
        self.nlocal = nlocal
        self.poll_wait = poll_wait
        self.round_id = round_id
        self._cursor = 0
        self._reported = set()
        self._cache = {}      # key -> (cache_id, fingerprint)
        self._suppressed = {} # key -> full meta withheld on a cache hit
        self._lock = threading.Lock()  # hvdlint: lock[ctrl:21]
        self._jid = 0         # join-request id (idempotent retries)
        self._rid = 0         # ready-report id (idempotent retries)
        # session id: a NEW controller against the SAME coordinator
        # (engine shutdown + re-init without an elastic round reset)
        # must not have its reports deduplicated against the previous
        # controller's counters
        import secrets as _secrets
        self._sid = _secrets.token_hex(8)
        #: Last coordinator-tuned parameters seen in a poll reply
        #: (reference SynchronizeParameters broadcast); the engine
        #: applies them to its config each cycle.
        self.tuned = None
        #: Coordinator generation (docs/fault_tolerance.md
        #: "Coordinator crash survival"): learned from poll replies,
        #: carried on every verb thereafter.  A mismatch reply means
        #: the rendezvous service restarted from its journal — one
        #: resync handshake re-registers the session instead of blind
        #: replay, then the engine drains the replayed response log
        #: and re-reports whatever is still awaiting.
        self.epoch = None
        #: aggregator generation (the second half of the
        #: (coord_epoch, agg_epoch) fence pair, docs/fault_tolerance
        #: "Per-host aggregator tier"): learned from the tier's
        #: replies, carried on every verb.  A restarted (stateless)
        #: aggregator registers a new session upstream, the
        #: coordinator bumps its agg_epoch, and this worker's first
        #: contact with the successor gets the SAME
        #: mismatch-then-resync recovery a coordinator restart does.
        #: The coordinator itself ignores the field, so a direct
        #: fallback needs no unstamping.
        self.agg_epoch = None
        self._drain_to = None
        self._rereport = False

    # -- epoch fencing -------------------------------------------------------

    def _on_route_change(self, reason):
        """TieredStoreClient switched routes (aggregator died ->
        direct, or a probe re-attached).  Either way the in-flight
        picture is unknown — the last batch may or may not have
        landed — so run the same resync + drain + re-report recovery
        an epoch bump triggers."""
        self.resync()

    def _stamp(self, payload):
        with self._lock:
            if self.epoch is not None:
                payload = {**payload, "epoch": self.epoch}
            if self.agg_epoch is not None:
                payload = {**payload, "agg_epoch": self.agg_epoch}
        return payload

    def _adopt_epochs(self, out):
        with self._lock:
            if out.get("epoch") is not None:
                self.epoch = out["epoch"]
            if out.get("agg_epoch") is not None:
                self.agg_epoch = out["agg_epoch"]

    def _coord(self, verb, payload, timeout=None, budget=None):
        """One coordinator verb with the (coord_epoch, agg_epoch)
        pair attached; handles the stale-round and epoch-mismatch
        replies in ONE place.  Either tier's fence may answer — the
        recovery is identical."""
        out = self.client.coord(verb, self._stamp(payload),
                                timeout=timeout, budget=budget)
        if out.get("stale"):
            raise StaleRoundError(
                f"coordinator moved to round {out.get('round')}")
        if out.get("epoch_mismatch"):
            self.resync()
            if verb == "ready":
                # never blind-replay a ready across an epoch bump: the
                # restarted coordinator may have scheduled these
                # entries pre-crash (the journaled log replays them).
                # Recovery is drain-then-rereport (take_rereport).
                return {}
            out = self.client.coord(verb, self._stamp(payload),
                                    timeout=timeout, budget=budget)
            if out.get("stale"):
                raise StaleRoundError(
                    f"coordinator moved to round {out.get('round')}")
            if out.get("epoch_mismatch"):
                raise HorovodInternalError(
                    "coordinator epoch moved twice within one request")
        self._adopt_epochs(out)
        return out

    def resync(self):
        """Epoch resync handshake against a restarted coordinator:
        re-register this session, adopt the new epoch, and arm the
        drain-then-rereport recovery — entries the old coordinator
        scheduled before dying arrive via the replayed log, and only
        what is STILL awaiting after the drain gets re-reported (full
        metas; the restarted response cache starts cold)."""
        out = self.client.coord("resync", {
            "proc": self.proc_id, "sid": self._sid,
            "round": self.round_id})
        if out.get("stale"):
            raise StaleRoundError(
                f"coordinator moved to round {out.get('round')}")
        with self._lock:
            self.epoch = out.get("epoch")
            self.agg_epoch = out.get("agg_epoch")
            self._drain_to = out.get("cursor", 0)
            self._rereport = True
            self._reported.clear()
            self._suppressed.clear()
            self._cache.clear()
        try:
            from ..telemetry import count_coord_resync
            count_coord_resync()
        except Exception:  # noqa: BLE001 — accounting only
            pass

    def take_rereport(self):
        """True ONCE per resync, and only after the replayed response
        log has been drained (cursor past the resync point) — the
        engine then re-reports every entry still awaiting."""
        with self._lock:
            if not self._rereport:
                return False
            if self._drain_to is not None \
                    and self._cursor < self._drain_to:
                return False
            self._rereport = False
            self._drain_to = None
            return True

    def bypass_ready(self, fp):
        """Vote this worker's stable cycle fingerprint (core/bypass.py
        step 1 -> 2).  Idempotent server-side; advisory here."""
        self._coord("bypass_ready", {
            "proc": self.proc_id, "round": self.round_id,
            "sid": self._sid, "fp": fp}, timeout=5.0)

    # -- reporting -----------------------------------------------------------

    # hvdlint: seam[determinism]
    def report_ready(self, metas):
        """Announce locally-ready entries (idempotent per key).  Keys
        whose params match a cached response template go out as tiny
        ``{key, c}`` records — the steady-state fast path."""
        fresh = []
        with self._lock:
            for m in metas:
                if m.get("error"):
                    # error notifications are fire-and-forget: the local
                    # handle already failed, and peers may never submit
                    # this tensor (so no response would ever clear a
                    # reported mark) — don't track, don't dedup
                    fresh.append(m)
                elif m["key"] not in self._reported:
                    self._reported.add(m["key"])
                    cached = self._cache.get(m["key"])
                    if cached is not None and \
                            m.get("type") in _CACHEABLE_TYPES and \
                            cached[1] == _fingerprint(m):
                        self._suppressed[m["key"]] = m
                        hit = {"key": m["key"], "c": cached[0]}
                        if m.get("aux"):
                            hit["aux"] = m["aux"]
                        fresh.append(hit)
                    else:
                        fresh.append(m)
        if fresh:
            self._post_ready(fresh)

    def _post_ready(self, entries):
        with self._lock:
            self._rid += 1
            rid = self._rid
        out = self._coord("ready", {
            "proc": self.proc_id, "nlocal": self.nlocal,
            "round": self.round_id, "entries": entries, "rid": rid,
            "sid": self._sid})
        uncached = out.get("uncached")
        if uncached:
            # the coordinator evicted (or never had) those cache ids:
            # resend the withheld full metas and drop the stale entries
            resend = []
            with self._lock:
                for key in uncached:
                    self._cache.pop(key, None)
                    full = self._suppressed.pop(key, None)
                    if full is not None:
                        resend.append(full)
            if resend:
                self._post_ready(resend)

    def clear_reported(self):
        """Drop ALL reported-key dedup marks.  Called by the engine
        when the bypass disengages: entries reported in the pre-arm
        race window were dropped server-side at arm time (and executed
        through the bypass), so their marks would otherwise silently
        swallow the re-report of any re-used tensor name — nothing is
        genuinely in flight at a bypass fallback."""
        with self._lock:
            self._reported.clear()
            self._suppressed.clear()

    def forget(self, key):
        """Drop a key from the reported set without a coordinator
        response.  Called by the engine whenever it removes an entry
        from ``awaiting`` through a path that will never yield a
        response for this process (stall shutdown, local validation
        failure, abort) — otherwise a later resubmission of the same
        tensor name would be silently skipped and hang the job."""
        with self._lock:
            self._reported.discard(key)
            self._suppressed.pop(key, None)

    def heartbeat(self, ranks=None, host=None, bye=False):
        """Liveness beat to the coordinator (docs/fault_tolerance.md):
        carries the global ranks this process hosts (so a later death
        is attributed to ranks, not just a proc index) and the
        hostname (so the elastic driver can blacklist the host).
        ``bye=True`` deregisters on clean shutdown.  Returns True if
        the coordinator has declared THIS process dead — the caller
        must abort and restart rather than keep computing against
        peers whose collectives were already failed."""
        payload = {"proc": self.proc_id, "round": self.round_id,
                   "sid": self._sid}
        if ranks is not None:
            payload["ranks"] = list(ranks)
        if host:
            payload["host"] = host
        if bye:
            payload["bye"] = True
        elif isinstance(self.client, TieredStoreClient):
            # the heartbeat loop is the probe clock: a fallen-back
            # worker re-pings its aggregator here and re-attaches
            # when an agg_restart brought it back
            self.client.maybe_probe()
        # the goodbye races teardown: a dead rendezvous service must
        # not wedge clean worker exit behind the outage-spanning
        # retry budget — one bounded retry, then give up
        out = self._coord("heartbeat", payload, timeout=5.0,
                          budget=(2, 3.0) if bye else None)
        return bool(out.get("dead"))

    def report_join(self, ps_id, rank, ps_size, proc_members=1):
        with self._lock:
            self._jid += 1
            jid = self._jid
        self._coord("join", {"ps": ps_id, "rank": rank,
                             "ps_size": ps_size,
                             "proc": self.proc_id,
                             "round": self.round_id,
                             "proc_members": proc_members,
                             "jid": jid, "sid": self._sid})

    # -- polling -------------------------------------------------------------

    def poll(self, wait=None):
        """Fetch responses past the cursor; returns list of response
        dicts ({kind: batch|error|join_done, ...})."""
        out = self._coord(
            "poll", {"cursor": self._cursor, "round": self.round_id,
                     "proc": self.proc_id,
                     "wait": self.poll_wait if wait is None else wait},
            timeout=(self.poll_wait if wait is None else wait) + 30)
        responses = out.get("responses", [])
        self._cursor = out.get("cursor", self._cursor)
        for j, r in enumerate(responses):
            if r.get("kind") == "bypass_arm":
                # the arm record is the coordinated mode switch: STOP
                # consuming the log exactly there, records before it
                # included.  A batch scheduled after the arm must not
                # be executed by fast pollers only (the slow ones
                # bypass those entries instead — a guaranteed
                # collective-order divergence); fencing the cursor to
                # the arm position makes every proc resume from the
                # same log point after a later fallback/resync.
                self._cursor -= len(responses) - (j + 1)
                responses = responses[:j + 1]
                break
        if "tuned" in out:
            self.tuned = out["tuned"]
        if responses:
            with self._lock:
                for r in responses:
                    cache_ids = r.get("cache_ids", {})
                    for k in r.get("keys", []):
                        self._reported.discard(k)
                        self._suppressed.pop(k, None)
                        cid = cache_ids.get(k)
                        meta = r.get("metas", {}).get(k)
                        if cid is not None and meta is not None and \
                                meta.get("type") in _CACHEABLE_TYPES:
                            self._cache[k] = (cid, _fingerprint(meta))
                    if "key" in r:          # error responses
                        self._reported.discard(r["key"])
                        self._suppressed.pop(r["key"], None)
                        self._cache.pop(r["key"], None)
        return responses

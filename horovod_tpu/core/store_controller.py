"""Worker-side distributed controller: negotiation over the launcher's
HTTP coordinator.

TPU-native replacement for the reference's controller transports
(``mpi/mpi_controller.cc`` MPI_Gatherv/Bcast, ``gloo/gloo_controller.cc``):
each worker *process* reports its locally-ready tensors to the
launcher-hosted coordinator (runner/http/http_server.py Coordinator)
and polls an ordered response log.  The log fixes the global execution
order, which is what lets every process issue identical compiled XLA
collectives — the SPMD invariant that replaces the reference's
explicit NCCL communicator synchronization.
"""

import threading

from ..common.exceptions import HorovodInternalError
from ..runner.http.http_client import StoreClient


class StaleRoundError(HorovodInternalError):
    """The coordinator moved to a new elastic round: every in-flight
    collective of the old round must fail so workers fall into the
    restore/re-rendezvous path instead of hanging (reference: gloo
    context failure -> HorovodInternalError -> state.restore)."""


class StoreController:
    """One per worker process in multi-process jobs."""

    def __init__(self, addr, port, secret, proc_id, num_procs,
                 nlocal, poll_wait=5.0, round_id=0):
        self.client = StoreClient(addr, port, secret)
        self.proc_id = proc_id
        self.num_procs = num_procs
        self.nlocal = nlocal
        self.poll_wait = poll_wait
        self.round_id = round_id
        self._cursor = 0
        self._reported = set()
        self._lock = threading.Lock()

    # -- reporting -----------------------------------------------------------

    def report_ready(self, metas):
        """Announce locally-ready entries (idempotent per key)."""
        fresh = []
        with self._lock:
            for m in metas:
                if m.get("error"):
                    # error notifications are fire-and-forget: the local
                    # handle already failed, and peers may never submit
                    # this tensor (so no response would ever clear a
                    # reported mark) — don't track, don't dedup
                    fresh.append(m)
                elif m["key"] not in self._reported:
                    self._reported.add(m["key"])
                    fresh.append(m)
        if fresh:
            out = self.client.coord("ready", {
                "proc": self.proc_id, "nlocal": self.nlocal,
                "round": self.round_id, "entries": fresh})
            if out.get("stale"):
                raise StaleRoundError(
                    f"coordinator moved to round {out.get('round')}")

    def forget(self, key):
        """Drop a key from the reported set without a coordinator
        response.  Called by the engine whenever it removes an entry
        from ``awaiting`` through a path that will never yield a
        response for this process (stall shutdown, local validation
        failure, abort) — otherwise a later resubmission of the same
        tensor name would be silently skipped and hang the job."""
        with self._lock:
            self._reported.discard(key)

    def report_join(self, ps_id, rank, ps_size, proc_members=1):
        out = self.client.coord("join", {"ps": ps_id, "rank": rank,
                                         "ps_size": ps_size,
                                         "proc": self.proc_id,
                                         "round": self.round_id,
                                         "proc_members": proc_members})
        if out.get("stale"):
            raise StaleRoundError(
                f"coordinator moved to round {out.get('round')}")

    # -- polling -------------------------------------------------------------

    def poll(self, wait=None):
        """Fetch responses past the cursor; returns list of response
        dicts ({kind: batch|error|join_done, ...})."""
        out = self.client.coord(
            "poll", {"cursor": self._cursor, "round": self.round_id,
                     "proc": self.proc_id,
                     "wait": self.poll_wait if wait is None else wait},
            timeout=(self.poll_wait if wait is None else wait) + 30)
        if out.get("stale"):
            raise StaleRoundError(
                f"coordinator moved to round {out.get('round')}")
        responses = out.get("responses", [])
        self._cursor = out.get("cursor", self._cursor)
        if responses:
            with self._lock:
                for r in responses:
                    for k in r.get("keys", []):
                        self._reported.discard(k)
                    if "key" in r:          # error responses
                        self._reported.discard(r["key"])
        return responses

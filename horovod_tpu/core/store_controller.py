"""Worker-side distributed controller: negotiation over the launcher's
HTTP coordinator.

TPU-native replacement for the reference's controller transports
(``mpi/mpi_controller.cc`` MPI_Gatherv/Bcast, ``gloo/gloo_controller.cc``):
each worker *process* reports its locally-ready tensors to the
launcher-hosted coordinator (runner/http/http_server.py Coordinator)
and polls an ordered response log.  The log fixes the global execution
order, which is what lets every process issue identical compiled XLA
collectives — the SPMD invariant that replaces the reference's
explicit NCCL communicator synchronization.
"""

import json
import threading

from ..common.exceptions import HorovodInternalError
from ..runner.http.http_client import StoreClient
from ..runner.http.http_server import CACHEABLE_TYPES as _CACHEABLE_TYPES


def _fingerprint(meta):
    """Canonical identity of a negotiation meta, aux/error excluded
    (reference response_cache.h:45-101 keys the LRU on tensor name +
    params the same way)."""
    return json.dumps(
        {k: v for k, v in meta.items() if k not in ("aux", "error")},
        sort_keys=True)


class StaleRoundError(HorovodInternalError):
    """The coordinator moved to a new elastic round: every in-flight
    collective of the old round must fail so workers fall into the
    restore/re-rendezvous path instead of hanging (reference: gloo
    context failure -> HorovodInternalError -> state.restore)."""


class StoreController:
    """One per worker process in multi-process jobs."""

    def __init__(self, addr, port, secret, proc_id, num_procs,
                 nlocal, poll_wait=5.0, round_id=0):
        self.client = StoreClient(addr, port, secret)
        self.proc_id = proc_id
        self.num_procs = num_procs
        self.nlocal = nlocal
        self.poll_wait = poll_wait
        self.round_id = round_id
        self._cursor = 0
        self._reported = set()
        self._cache = {}      # key -> (cache_id, fingerprint)
        self._suppressed = {} # key -> full meta withheld on a cache hit
        self._lock = threading.Lock()
        self._jid = 0         # join-request id (idempotent retries)
        self._rid = 0         # ready-report id (idempotent retries)
        # session id: a NEW controller against the SAME coordinator
        # (engine shutdown + re-init without an elastic round reset)
        # must not have its reports deduplicated against the previous
        # controller's counters
        import secrets as _secrets
        self._sid = _secrets.token_hex(8)
        #: Last coordinator-tuned parameters seen in a poll reply
        #: (reference SynchronizeParameters broadcast); the engine
        #: applies them to its config each cycle.
        self.tuned = None

    # -- reporting -----------------------------------------------------------

    def report_ready(self, metas):
        """Announce locally-ready entries (idempotent per key).  Keys
        whose params match a cached response template go out as tiny
        ``{key, c}`` records — the steady-state fast path."""
        fresh = []
        with self._lock:
            for m in metas:
                if m.get("error"):
                    # error notifications are fire-and-forget: the local
                    # handle already failed, and peers may never submit
                    # this tensor (so no response would ever clear a
                    # reported mark) — don't track, don't dedup
                    fresh.append(m)
                elif m["key"] not in self._reported:
                    self._reported.add(m["key"])
                    cached = self._cache.get(m["key"])
                    if cached is not None and \
                            m.get("type") in _CACHEABLE_TYPES and \
                            cached[1] == _fingerprint(m):
                        self._suppressed[m["key"]] = m
                        hit = {"key": m["key"], "c": cached[0]}
                        if m.get("aux"):
                            hit["aux"] = m["aux"]
                        fresh.append(hit)
                    else:
                        fresh.append(m)
        if fresh:
            self._post_ready(fresh)

    def _post_ready(self, entries):
        with self._lock:
            self._rid += 1
            rid = self._rid
        out = self.client.coord("ready", {
            "proc": self.proc_id, "nlocal": self.nlocal,
            "round": self.round_id, "entries": entries, "rid": rid,
            "sid": self._sid})
        if out.get("stale"):
            raise StaleRoundError(
                f"coordinator moved to round {out.get('round')}")
        uncached = out.get("uncached")
        if uncached:
            # the coordinator evicted (or never had) those cache ids:
            # resend the withheld full metas and drop the stale entries
            resend = []
            with self._lock:
                for key in uncached:
                    self._cache.pop(key, None)
                    full = self._suppressed.pop(key, None)
                    if full is not None:
                        resend.append(full)
            if resend:
                self._post_ready(resend)

    def forget(self, key):
        """Drop a key from the reported set without a coordinator
        response.  Called by the engine whenever it removes an entry
        from ``awaiting`` through a path that will never yield a
        response for this process (stall shutdown, local validation
        failure, abort) — otherwise a later resubmission of the same
        tensor name would be silently skipped and hang the job."""
        with self._lock:
            self._reported.discard(key)
            self._suppressed.pop(key, None)

    def heartbeat(self, ranks=None, host=None, bye=False):
        """Liveness beat to the coordinator (docs/fault_tolerance.md):
        carries the global ranks this process hosts (so a later death
        is attributed to ranks, not just a proc index) and the
        hostname (so the elastic driver can blacklist the host).
        ``bye=True`` deregisters on clean shutdown.  Returns True if
        the coordinator has declared THIS process dead — the caller
        must abort and restart rather than keep computing against
        peers whose collectives were already failed."""
        payload = {"proc": self.proc_id, "round": self.round_id,
                   "sid": self._sid}
        if ranks is not None:
            payload["ranks"] = list(ranks)
        if host:
            payload["host"] = host
        if bye:
            payload["bye"] = True
        out = self.client.coord("heartbeat", payload, timeout=5.0)
        if out.get("stale"):
            raise StaleRoundError(
                f"coordinator moved to round {out.get('round')}")
        return bool(out.get("dead"))

    def report_join(self, ps_id, rank, ps_size, proc_members=1):
        with self._lock:
            self._jid += 1
            jid = self._jid
        out = self.client.coord("join", {"ps": ps_id, "rank": rank,
                                         "ps_size": ps_size,
                                         "proc": self.proc_id,
                                         "round": self.round_id,
                                         "proc_members": proc_members,
                                         "jid": jid, "sid": self._sid})
        if out.get("stale"):
            raise StaleRoundError(
                f"coordinator moved to round {out.get('round')}")

    # -- polling -------------------------------------------------------------

    def poll(self, wait=None):
        """Fetch responses past the cursor; returns list of response
        dicts ({kind: batch|error|join_done, ...})."""
        out = self.client.coord(
            "poll", {"cursor": self._cursor, "round": self.round_id,
                     "proc": self.proc_id,
                     "wait": self.poll_wait if wait is None else wait},
            timeout=(self.poll_wait if wait is None else wait) + 30)
        if out.get("stale"):
            raise StaleRoundError(
                f"coordinator moved to round {out.get('round')}")
        responses = out.get("responses", [])
        self._cursor = out.get("cursor", self._cursor)
        if "tuned" in out:
            self.tuned = out["tuned"]
        if responses:
            with self._lock:
                for r in responses:
                    cache_ids = r.get("cache_ids", {})
                    for k in r.get("keys", []):
                        self._reported.discard(k)
                        self._suppressed.pop(k, None)
                        cid = cache_ids.get(k)
                        meta = r.get("metas", {}).get(k)
                        if cid is not None and meta is not None and \
                                meta.get("type") in _CACHEABLE_TYPES:
                            self._cache[k] = (cid, _fingerprint(meta))
                    if "key" in r:          # error responses
                        self._reported.discard(r["key"])
                        self._suppressed.pop(r["key"], None)
                        self._cache.pop(r["key"], None)
        return responses

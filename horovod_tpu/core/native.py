"""ctypes binding for the native host-path library (csrc/fusion.cpp).

The reference binds its native core with ctypes the same way
(``horovod/common/basics.py:29`` loads the shared lib).  If the
library is missing it is built once with g++ (the toolchain is part of
the image); failing that, a numpy fallback keeps everything working.
"""

import ctypes
import logging
import os
import subprocess
import threading

import numpy as np

logger = logging.getLogger("horovod_tpu")

_lock = threading.Lock()
_lib = None
_tried = False

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_LIB_PATH = os.path.join(_PKG_DIR, "_native", "libhvdnative.so")
_SRC_DIR = os.path.join(os.path.dirname(_PKG_DIR), "csrc")
_SRC_NAMES = ("fusion.cpp", "arena.cpp", "timeline.cpp")


def _srcs():
    return [os.path.join(_SRC_DIR, s) for s in _SRC_NAMES
            if os.path.exists(os.path.join(_SRC_DIR, s))]


def _build():
    os.makedirs(os.path.dirname(_LIB_PATH), exist_ok=True)
    # compile to a per-process temp file and rename atomically so
    # concurrently launched workers never dlopen a half-written .so
    tmp = f"{_LIB_PATH}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-fPIC", "-std=c++17", "-shared",
           "-o", tmp] + _srcs() + ["-lpthread"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _LIB_PATH)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _stale():
    """Rebuild when any source is newer than the shared lib."""
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    return any(os.path.getmtime(s) > lib_mtime for s in _srcs())


def get_lib():
    """Load (building if needed) the native lib; None on failure."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            if _srcs() and _stale():
                _build()
            lib = ctypes.CDLL(_LIB_PATH)
            lib.hvd_pack.argtypes = [
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64, ctypes.c_char_p]
            lib.hvd_unpack.argtypes = [
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_void_p)]
            if hasattr(lib, "hvd_pack_mt"):
                lib.hvd_pack_mt.argtypes = \
                    lib.hvd_pack.argtypes + [ctypes.c_int64]
            if hasattr(lib, "hvd_arena_new"):
                lib.hvd_arena_new.restype = ctypes.c_void_p
                lib.hvd_arena_acquire.restype = ctypes.c_void_p
                lib.hvd_arena_acquire.argtypes = [ctypes.c_void_p,
                                                  ctypes.c_int64]
                lib.hvd_arena_release.argtypes = [ctypes.c_void_p,
                                                  ctypes.c_void_p]
                lib.hvd_arena_bytes.restype = ctypes.c_int64
                lib.hvd_arena_bytes.argtypes = [ctypes.c_void_p]
                lib.hvd_arena_destroy.argtypes = [ctypes.c_void_p]
            if hasattr(lib, "hvd_tl_open"):
                lib.hvd_tl_open.restype = ctypes.c_void_p
                lib.hvd_tl_open.argtypes = [ctypes.c_char_p]
                lib.hvd_tl_event.argtypes = [
                    ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                    ctypes.c_int64, ctypes.c_double]
                if hasattr(lib, "hvd_tl_counter"):
                    lib.hvd_tl_counter.argtypes = [
                        ctypes.c_void_p, ctypes.c_char_p,
                        ctypes.c_char_p, ctypes.c_double]
                if hasattr(lib, "hvd_tl_set_pid"):
                    lib.hvd_tl_set_pid.argtypes = [
                        ctypes.c_void_p, ctypes.c_int64]
                if hasattr(lib, "hvd_tl_meta"):
                    lib.hvd_tl_meta.argtypes = [
                        ctypes.c_void_p, ctypes.c_char_p,
                        ctypes.c_char_p, ctypes.c_int64]
                if hasattr(lib, "hvd_tl_flow"):
                    lib.hvd_tl_flow.argtypes = [
                        ctypes.c_void_p, ctypes.c_char_p,
                        ctypes.c_int64, ctypes.c_int64,
                        ctypes.c_double]
                lib.hvd_tl_close.argtypes = [ctypes.c_void_p]
            _lib = lib
        except Exception as exc:  # noqa: BLE001 — fall back to numpy
            logger.info("native lib unavailable (%s); using numpy "
                        "fallback", exc)
            _lib = None
        return _lib


def available() -> bool:
    return get_lib() is not None


def pack(arrays, dst: np.ndarray, offsets_bytes) -> None:
    """Pack flat arrays into the contiguous dst buffer at byte offsets
    (one native call per fusion bucket; reference batched-D2D)."""
    lib = get_lib()
    n = len(arrays)
    if lib is None or n == 0:
        for a, off in zip(arrays, offsets_bytes):
            nb = a.nbytes
            dst.view(np.uint8)[off:off + nb] = \
                np.ascontiguousarray(a).view(np.uint8).ravel()
        return
    arrays = [np.ascontiguousarray(a) for a in arrays]
    srcs = (ctypes.c_void_p * n)(
        *[a.ctypes.data for a in arrays])
    sizes = (ctypes.c_int64 * n)(*[a.nbytes for a in arrays])
    offs = (ctypes.c_int64 * n)(*offsets_bytes)
    lib.hvd_pack(srcs, sizes, offs, n,
                 dst.ctypes.data_as(ctypes.c_char_p))


def unpack(src: np.ndarray, arrays, offsets_bytes) -> None:
    """Scatter the contiguous src buffer back into writable arrays."""
    lib = get_lib()
    n = len(arrays)
    if lib is None or n == 0:
        for a, off in zip(arrays, offsets_bytes):
            nb = a.nbytes
            a.view(np.uint8).ravel()[:] = \
                src.view(np.uint8)[off:off + nb]
        return
    for a in arrays:
        assert a.flags["C_CONTIGUOUS"] and a.flags["WRITEABLE"]
    dsts = (ctypes.c_void_p * n)(*[a.ctypes.data for a in arrays])
    sizes = (ctypes.c_int64 * n)(*[a.nbytes for a in arrays])
    offs = (ctypes.c_int64 * n)(*offsets_bytes)
    lib.hvd_unpack(src.ctypes.data_as(ctypes.c_char_p),
                   sizes, offs, n, dsts)


def pack_mt(arrays, dst: np.ndarray, offsets_bytes,
            nthreads: int = 4) -> None:
    """Multithreaded pack for large buckets (csrc hvd_pack_mt); falls
    back to the single-threaded path."""
    lib = get_lib()
    n = len(arrays)
    if lib is None or n == 0 or not hasattr(lib, "hvd_pack_mt"):
        return pack(arrays, dst, offsets_bytes)
    arrays = [np.ascontiguousarray(a) for a in arrays]
    srcs = (ctypes.c_void_p * n)(*[a.ctypes.data for a in arrays])
    sizes = (ctypes.c_int64 * n)(*[a.nbytes for a in arrays])
    offs = (ctypes.c_int64 * n)(*offsets_bytes)
    lib.hvd_pack_mt(srcs, sizes, offs, n,
                    dst.ctypes.data_as(ctypes.c_char_p), nthreads)


class Arena:
    """Size-class staging-buffer arena (csrc/arena.cpp — the
    reference FusionBufferManager's persistent-buffer role).  Buffers
    come back as numpy views over 64-byte-aligned native slabs; a
    numpy freelist stands in when the native lib is unavailable."""

    def __init__(self):
        self._lib = get_lib()
        self._native = self._lib is not None and \
            hasattr(self._lib, "hvd_arena_new")
        self._handle = self._lib.hvd_arena_new() if self._native else None
        self._py_free = {}      # size-class -> [ndarray]
        self._live = {}         # data address -> release token
        self._lock = threading.Lock()

    @staticmethod
    def _cls(nbytes):
        c = 4096
        while c < nbytes:
            c <<= 1
        return c

    def acquire(self, nbytes: int, dtype=np.uint8) -> np.ndarray:
        """A reusable buffer of >= nbytes, viewed as `dtype`
        (element count = nbytes // itemsize).  Release by passing the
        SAME array (tracked by data address — ndarrays don't accept
        attributes)."""
        itemsize = np.dtype(dtype).itemsize
        if self._native:
            ptr = self._lib.hvd_arena_acquire(self._handle, nbytes)
            if ptr:
                raw = (ctypes.c_char * nbytes).from_address(ptr)
                arr = np.frombuffer(raw, dtype=np.uint8, count=nbytes) \
                    .view(dtype)[: nbytes // itemsize]
                with self._lock:
                    self._live[int(ptr)] = ("native", int(ptr))
                return arr
        cls = self._cls(nbytes)
        with self._lock:
            slabs = self._py_free.setdefault(cls, [])
            base = slabs.pop() if slabs else np.empty(cls, np.uint8)
        arr = base[:nbytes].view(dtype)[: nbytes // itemsize]
        with self._lock:
            self._live[int(base.ctypes.data)] = ("py", base)
        return arr

    def release(self, arr: np.ndarray):
        addr = int(arr.ctypes.data)
        with self._lock:
            token = self._live.pop(addr, None)
        if token is None:
            return
        kind, val = token
        if kind == "native":
            self._lib.hvd_arena_release(self._handle, val)
        else:
            with self._lock:
                self._py_free.setdefault(len(val), []).append(val)

    def total_bytes(self) -> int:
        if self._native:
            return int(self._lib.hvd_arena_bytes(self._handle))
        with self._lock:
            return sum(len(b) for slabs in self._py_free.values()
                       for b in slabs)

    def __del__(self):  # pragma: no cover — interpreter teardown
        try:
            if self._native and self._handle:
                self._lib.hvd_arena_destroy(self._handle)
                self._handle = None
        except Exception:  # noqa: BLE001
            pass


def timeline_writer(path: str):
    """Native async chrome-trace writer handle, or None when the lib
    lacks it (utils/timeline.py then uses its python writer thread)."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "hvd_tl_open"):
        return None
    handle = lib.hvd_tl_open(path.encode())
    return (lib, handle) if handle else None

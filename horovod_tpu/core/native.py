"""ctypes binding for the native host-path library (csrc/fusion.cpp).

The reference binds its native core with ctypes the same way
(``horovod/common/basics.py:29`` loads the shared lib).  If the
library is missing it is built once with g++ (the toolchain is part of
the image); failing that, a numpy fallback keeps everything working.
"""

import ctypes
import logging
import os
import subprocess
import threading

import numpy as np

logger = logging.getLogger("horovod_tpu")

_lock = threading.Lock()
_lib = None
_tried = False

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_LIB_PATH = os.path.join(_PKG_DIR, "_native", "libhvdnative.so")
_SRC_PATH = os.path.join(os.path.dirname(_PKG_DIR), "csrc", "fusion.cpp")


def _build():
    os.makedirs(os.path.dirname(_LIB_PATH), exist_ok=True)
    # compile to a per-process temp file and rename atomically so
    # concurrently launched workers never dlopen a half-written .so
    tmp = f"{_LIB_PATH}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-fPIC", "-std=c++17", "-shared",
           "-o", tmp, _SRC_PATH]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _LIB_PATH)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def get_lib():
    """Load (building if needed) the native lib; None on failure."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            if not os.path.exists(_LIB_PATH) and os.path.exists(_SRC_PATH):
                _build()
            lib = ctypes.CDLL(_LIB_PATH)
            lib.hvd_pack.argtypes = [
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64, ctypes.c_char_p]
            lib.hvd_unpack.argtypes = [
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_void_p)]
            _lib = lib
        except Exception as exc:  # noqa: BLE001 — fall back to numpy
            logger.info("native lib unavailable (%s); using numpy "
                        "fallback", exc)
            _lib = None
        return _lib


def available() -> bool:
    return get_lib() is not None


def pack(arrays, dst: np.ndarray, offsets_bytes) -> None:
    """Pack flat arrays into the contiguous dst buffer at byte offsets
    (one native call per fusion bucket; reference batched-D2D)."""
    lib = get_lib()
    n = len(arrays)
    if lib is None or n == 0:
        for a, off in zip(arrays, offsets_bytes):
            nb = a.nbytes
            dst.view(np.uint8)[off:off + nb] = \
                np.ascontiguousarray(a).view(np.uint8).ravel()
        return
    arrays = [np.ascontiguousarray(a) for a in arrays]
    srcs = (ctypes.c_void_p * n)(
        *[a.ctypes.data for a in arrays])
    sizes = (ctypes.c_int64 * n)(*[a.nbytes for a in arrays])
    offs = (ctypes.c_int64 * n)(*offsets_bytes)
    lib.hvd_pack(srcs, sizes, offs, n,
                 dst.ctypes.data_as(ctypes.c_char_p))


def unpack(src: np.ndarray, arrays, offsets_bytes) -> None:
    """Scatter the contiguous src buffer back into writable arrays."""
    lib = get_lib()
    n = len(arrays)
    if lib is None or n == 0:
        for a, off in zip(arrays, offsets_bytes):
            nb = a.nbytes
            a.view(np.uint8).ravel()[:] = \
                src.view(np.uint8)[off:off + nb]
        return
    for a in arrays:
        assert a.flags["C_CONTIGUOUS"] and a.flags["WRITEABLE"]
    dsts = (ctypes.c_void_p * n)(*[a.ctypes.data for a in arrays])
    sizes = (ctypes.c_int64 * n)(*[a.nbytes for a in arrays])
    offs = (ctypes.c_int64 * n)(*offsets_bytes)
    lib.hvd_unpack(src.ctypes.data_as(ctypes.c_char_p),
                   sizes, offs, n, dsts)

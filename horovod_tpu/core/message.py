"""Control-plane message types.

TPU-native analogue of the reference's ``horovod/common/message.h``:
``DataType`` (message.h:30-41), ``ReduceOp`` (message.h:43-50),
``Request`` (message.h:59-143) and ``Response`` (message.h:175-265).
Serialization is a compact JSON-able dict (the reference uses
FlatBuffers, wire/message.fbs) — the wire only carries shapes and
names, never tensor data, so the format is not performance-critical.
"""

import enum
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np


class ReduceOp(enum.IntEnum):
    # Values mirror reference message.h:43-50.
    AVERAGE = 0
    SUM = 1
    ADASUM = 2
    MIN = 3
    MAX = 4
    PRODUCT = 5


# Public aliases matching the hvd.* API surface.
Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM
Adasum = ReduceOp.ADASUM
Min = ReduceOp.MIN
Max = ReduceOp.MAX
Product = ReduceOp.PRODUCT


class RequestType(enum.IntEnum):
    # Mirrors reference message.h:66-75.
    ALLREDUCE = 0
    ALLGATHER = 1
    BROADCAST = 2
    JOIN = 3
    ADASUM = 4
    ALLTOALL = 5
    BARRIER = 6
    REDUCESCATTER = 7


class ResponseType(enum.IntEnum):
    ALLREDUCE = 0
    ALLGATHER = 1
    BROADCAST = 2
    JOIN = 3
    ADASUM = 4
    ALLTOALL = 5
    BARRIER = 6
    REDUCESCATTER = 7
    ERROR = 8


@dataclass
class Request:
    """One rank's declaration that a tensor is ready for a collective.

    Field-parity with reference message.h:59-143 (rank, type, name,
    root_rank, device, group_id, shape, prescale/postscale, reduce op);
    ``splits`` covers the alltoall send-split vector which the reference
    passes out-of-band through the entry.
    """
    request_type: RequestType
    tensor_name: str
    rank: int = 0
    dtype: Optional[str] = None          # numpy dtype string, e.g. "float32"
    shape: Tuple[int, ...] = ()
    root_rank: int = -1                  # broadcast only
    reduce_op: ReduceOp = ReduceOp.SUM
    prescale_factor: float = 1.0
    postscale_factor: float = 1.0
    group_id: int = -1                   # grouped-op negotiation unit
    process_set_id: int = 0
    splits: Optional[Tuple[int, ...]] = None  # alltoall send splits
    # wire compression for the payload of THIS collective:
    # None (= tensor dtype) | 'fp16' | 'bf16' | 'int8' | 'int4'
    # (block-scaled, ops/quantize.py).  Cross-rank validated like
    # dtype — ranks disagreeing on the wire format would mis-decode
    # each other.  Under a 2-D decomposition this is the OUTER
    # (cross-host / DCN) hop's format; flat collectives have one hop
    # and this is it.
    wire_dtype: Optional[str] = None
    # INNER (intra-host / ICI) hop wire for decomposed allreduces:
    # None (= uniform-shorthand expansion of wire_dtype, or full
    # width) | 'f32' (explicit full width) | 'fp16' | 'bf16'.  The
    # quantized formats are never legal here (ops/quantize.py
    # INNER_WIRE_CHOICES).  Cross-rank validated like wire_dtype.
    wire_inner: Optional[str] = None
    # error feedback for a quantized alltoall wire: the sender folds
    # each peer slot's quantization residual into that slot's NEXT
    # exchange.  Default on (it converges the dispatch wire), but the
    # residual is engine-local state that a step quarantine clears,
    # so bit-exact-replay consumers (the integrity drills) turn it
    # off per request.  Only decodes the SENDER's own payload, so no
    # cross-rank validation is needed — but it does segregate fusion
    # buckets (one fused exchange has one EF policy).
    error_feedback: bool = True
    # reduction algorithm for THIS collective: None (= process-wide
    # default) | 'flat' | 'hierarchical' | 'torus'
    # (common/topology.py).  Cross-rank validated like wire_dtype —
    # ranks disagreeing would issue different SPMD programs.
    algorithm: Optional[str] = None
    # pipeline-schedule tag ("<schedule>@<n_micro>",
    # schedule.pp_label) on gradient reduces submitted from inside an
    # MPMD pipeline step: None outside pipelines.  Cross-rank
    # validated like wire_dtype — ranks running different schedules
    # (or microbatch counts) would overlap different collectives into
    # different bubbles and accumulate different gradient sums, so a
    # divergence must fail loudly, not train silently skewed.  The
    # engine latches the process-wide default per negotiation entry
    # (autotune's seventh dimension flips it between steps only).
    pp_sched: Optional[str] = None
    # shard-layout fingerprint (core/sharded.ShardPlan.fingerprint)
    # on collectives issued by a sharded weight update: None outside
    # sharded mode.  Cross-rank validated like wire_dtype — ranks
    # disagreeing on the shard layout would reducescatter/allgather
    # different slices against each other and corrupt the update, so
    # a divergence must fail loudly.
    shard_fp: Optional[str] = None
    # grouped submissions: shape of EVERY member tensor, so cross-rank
    # validation covers members beyond the first (the reference issues
    # one Request per member inside the group instead)
    group_shapes: Optional[Tuple[Tuple[int, ...], ...]] = None

    def to_dict(self):
        return {
            "t": int(self.request_type),
            "n": self.tensor_name,
            "r": self.rank,
            "d": self.dtype,
            "s": list(self.shape),
            "rr": self.root_rank,
            "op": int(self.reduce_op),
            "pre": self.prescale_factor,
            "post": self.postscale_factor,
            "g": self.group_id,
            "ps": self.process_set_id,
            "sp": list(self.splits) if self.splits is not None else None,
            "gs": [list(s) for s in self.group_shapes]
            if self.group_shapes is not None else None,
            "w": self.wire_dtype,
            "wi": self.wire_inner,
            "ef": self.error_feedback,
            "alg": self.algorithm,
            "pp": self.pp_sched,
            "sfp": self.shard_fp,
        }

    @classmethod
    def from_dict(cls, d):
        return cls(
            request_type=RequestType(d["t"]),
            tensor_name=d["n"],
            rank=d["r"],
            dtype=d["d"],
            shape=tuple(d["s"]),
            root_rank=d["rr"],
            reduce_op=ReduceOp(d["op"]),
            prescale_factor=d["pre"],
            postscale_factor=d["post"],
            group_id=d["g"],
            process_set_id=d["ps"],
            splits=tuple(d["sp"]) if d["sp"] is not None else None,
            group_shapes=tuple(tuple(s) for s in d["gs"])
            if d.get("gs") is not None else None,
            wire_dtype=d.get("w"),
            wire_inner=d.get("wi"),
            error_feedback=d.get("ef", True),
            algorithm=d.get("alg"),
            pp_sched=d.get("pp"),
            shard_fp=d.get("sfp"),
        )


@dataclass
class Response:
    """The coordinator's instruction to execute one (possibly fused)
    collective, or to deliver an error (reference message.h:175-265)."""
    response_type: ResponseType
    tensor_names: List[str] = field(default_factory=list)
    error_message: str = ""
    prescale_factor: float = 1.0
    postscale_factor: float = 1.0
    reduce_op: ReduceOp = ReduceOp.SUM
    last_joined_rank: int = -1
    process_set_id: int = 0

    def to_dict(self):
        return {
            "t": int(self.response_type),
            "n": self.tensor_names,
            "e": self.error_message,
            "pre": self.prescale_factor,
            "post": self.postscale_factor,
            "op": int(self.reduce_op),
            "lj": self.last_joined_rank,
            "ps": self.process_set_id,
        }

    @classmethod
    def from_dict(cls, d):
        return cls(
            response_type=ResponseType(d["t"]),
            tensor_names=list(d["n"]),
            error_message=d["e"],
            prescale_factor=d["pre"],
            postscale_factor=d["post"],
            reduce_op=ReduceOp(d["op"]),
            last_joined_rank=d["lj"],
            process_set_id=d["ps"],
        )


_REQUEST_TYPE_TO_RESPONSE = {
    RequestType.ALLREDUCE: ResponseType.ALLREDUCE,
    RequestType.ALLGATHER: ResponseType.ALLGATHER,
    RequestType.BROADCAST: ResponseType.BROADCAST,
    RequestType.JOIN: ResponseType.JOIN,
    RequestType.ADASUM: ResponseType.ADASUM,
    RequestType.ALLTOALL: ResponseType.ALLTOALL,
    RequestType.BARRIER: ResponseType.BARRIER,
    RequestType.REDUCESCATTER: ResponseType.REDUCESCATTER,
}


def response_type_for(request_type: RequestType) -> ResponseType:
    return _REQUEST_TYPE_TO_RESPONSE[request_type]


def normalize_dtype(dtype) -> str:
    """Canonical dtype string used in negotiation (cross-rank dtype
    checks compare these, like reference DataType message.h:30-41)."""
    return np.dtype(dtype).name if not str(dtype).startswith("bfloat16") else "bfloat16"

"""Runtime parameter autotuning (reference
``horovod/common/parameter_manager.{h,cc}``: score = bytes/sec over
sample windows, warmup discard, Bayesian optimization over tunables,
CSV log via HOROVOD_AUTOTUNE_LOG, converge-to-best after max samples).

Tunables here are the six that exist on the TPU engine: the fusion
threshold (bucket size for packed allreduces), the cycle time (how
long the background thread batches submissions), the
multithreaded-pack threshold (bucket size above which the native pack
fans out across threads), the coordinator response-cache capacity
(the reference tunes cache on/off, parameter_manager.h:65; here the
LRU size tunes smoothly with 0 = disabled), the per-hop WIRE PAIR
((inner, outer) — full width / 16-bit on the intra-host/ICI hop,
anything up to block-scaled int4 on the cross-host/DCN hop,
ops/quantize.py WIRE_PAIR_CHOICES: a LEGAL-PAIR ENUMERATION swept as
ONE categorical, not a cross product — intra-hop int4 is never
legal, so the grid never proposes it), and the reduction ALGORITHM
(flat / hierarchical / torus, common/topology.py — the reference's
HOROVOD_HIERARCHICAL_ALLREDUCE / HOROVOD_TORUS_ALLREDUCE toggles as
one swept categorical).  The score is LOGICAL bytes/sec —
gradient goodput — so shrinking the wire payload (or keeping it off
the cross-host hop) raises the score exactly when the interconnect,
not the chip, is the bottleneck: that is how the parameter manager
learns to turn quantization or hierarchical routing on for
network-bound jobs and leave them off when the extra hops outweigh
the saved slow-hop bytes.  Algorithms that do not factor the running
topology silently execute flat (engine._algo_plan), so a sweep never
breaks a job — it just scores the fallback.
"""

import time

import numpy as np

from .optim import BayesianOptimizer
from ..common.topology import ALGORITHMS
from ..ops.quantize import WIRE_PAIR_CHOICES, wire_pair_label

# log2 bounds: fusion threshold 1 MiB .. 256 MiB, cycle 0.5 .. 32 ms,
# MT-pack threshold 1 MiB .. 64 MiB, cache capacity 0 .. 4096 entries
_FUSION_LO, _FUSION_HI = 20.0, 28.0
_CYCLE_LO, _CYCLE_HI = -1.0, 5.0
_PACKMT_LO, _PACKMT_HI = 20.0, 26.0
_CACHE_BITS = 12.0


class ParameterManager:
    def __init__(self, config, warmup_samples=3, steps_per_sample=10,
                 max_samples=20, log_path=None, seed=0, tune_wire=True,
                 tune_algorithm=True):
        self.config = config
        self.warmup_samples = warmup_samples
        self.steps_per_sample = steps_per_sample
        self.max_samples = max_samples
        self.active = True
        # tune_wire=False / tune_algorithm=False drop those categorical
        # dimensions entirely: the coordinator-side autotuner
        # (runner/http/http_server) has no distribution channel for a
        # tuned wire format or algorithm (workers applying a new
        # default at different cycles would fail the cross-process
        # consistency check), and sweeping a dimension nothing applies
        # would waste samples and write never-applied values into the
        # CSV
        self.tune_wire = bool(tune_wire)
        self.tune_algorithm = bool(tune_algorithm)
        dims = 4 + int(self.tune_wire) + int(self.tune_algorithm)
        self._bo = BayesianOptimizer(dims=dims, seed=seed)
        self._samples = 0
        self._steps = 0
        self._bytes = 0
        self._t0 = None
        self._current = self._encode(
            config.fusion_threshold_bytes, config.cycle_time_ms,
            getattr(config, "pack_mt_threshold_bytes", 8 << 20),
            getattr(config, "cache_capacity", 1024),
            (getattr(config, "wire_inner", None),
             getattr(config, "wire_dtype", None)),
            getattr(config, "algorithm", None))
        self._best_score = -np.inf
        self._best = self._current
        self._log = open(log_path, "w") if log_path else None
        if self._log:
            wire_col = "wire_pair," if self.tune_wire else ""
            algo_col = "algorithm," if self.tune_algorithm else ""
            self._log.write(
                "sample,fusion_threshold_bytes,cycle_time_ms,"
                f"pack_mt_threshold_bytes,cache_capacity,{wire_col}"
                f"{algo_col}score_bytes_per_sec\n")

    # -- encoding ------------------------------------------------------------

    def _encode(self, fusion_bytes, cycle_ms, pack_mt_bytes,
                cache_capacity, wire_pair=None, algorithm=None):
        x0 = (np.log2(max(fusion_bytes, 1)) - _FUSION_LO) / \
            (_FUSION_HI - _FUSION_LO)
        x1 = (np.log2(max(cycle_ms, 2 ** _CYCLE_LO)) - _CYCLE_LO) / \
            (_CYCLE_HI - _CYCLE_LO)
        x2 = (np.log2(max(pack_mt_bytes, 1)) - _PACKMT_LO) / \
            (_PACKMT_HI - _PACKMT_LO)
        x3 = np.log2(cache_capacity + 1) / _CACHE_BITS
        xs = [x0, x1, x2, x3]
        if self.tune_wire:
            # fifth dimension: the per-hop (inner, outer) wire pair as
            # a categorical grid over [0, 1] (WIRE_PAIR_CHOICES at bin
            # centers — the BO's continuous suggestion snaps to the
            # nearest legal pair in _decode; quantized inner hops are
            # not in the enumeration, so the tuner can never propose
            # one).  Seeds canonicalize to the enumeration's spelling:
            # an unset inner INHERITS a 16-bit outer (the uniform
            # shorthand lands on the uniform bin), while an explicit
            # 'f32' inner keeps the cross-hop-only bin; an 'f32'
            # outer encodes as full width.  'f32' is only a distinct
            # spelling AGAINST a 16-bit outer — against a quantized or
            # unset outer the inner hop runs full width either way
            # (effective_inner_wire), so those seeds land on the
            # matching (None, outer) bin, and an API-legal 16-bit
            # inner the grid does not enumerate (e.g. fp16 over a
            # quantized outer) seeds its byte-equivalent 16-bit bin.
            inner, outer = wire_pair or (None, None)
            outer = None if outer == "f32" else outer
            if inner is None and outer in ("fp16", "bf16"):
                inner = outer
            elif inner == "f32" and outer not in ("fp16", "bf16"):
                inner = None
            try:
                wi = WIRE_PAIR_CHOICES.index((inner, outer))
            except ValueError:
                if inner in ("fp16", "bf16") and outer in ("int8", "int4"):
                    wi = WIRE_PAIR_CHOICES.index(("bf16", outer))
                else:
                    wi = 0
            xs.append((wi + 0.5) / len(WIRE_PAIR_CHOICES))
        if self.tune_algorithm:
            # sixth dimension: reduction algorithm over the same kind
            # of categorical grid; an unset default encodes as flat
            try:
                ai = ALGORITHMS.index(algorithm or "flat")
            except ValueError:
                ai = 0
            xs.append((ai + 0.5) / len(ALGORITHMS))
        return np.clip(xs, 0.0, 1.0)

    def _decode(self, x):
        fusion = int(2 ** (_FUSION_LO + x[0] * (_FUSION_HI - _FUSION_LO)))
        cycle = float(2 ** (_CYCLE_LO + x[1] * (_CYCLE_HI - _CYCLE_LO)))
        pack_mt = int(2 ** (_PACKMT_LO + x[2] * (_PACKMT_HI - _PACKMT_LO)))
        # capacity 0 (cache off) is reachable at the low end — the
        # reference's cache-enabled toggle as the floor of a smooth dim
        cache = int(round(2 ** (x[3] * _CACHE_BITS))) - 1
        out = [fusion, cycle, pack_mt, cache]
        i = 4
        if self.tune_wire:
            wi = min(int(x[i] * len(WIRE_PAIR_CHOICES)),
                     len(WIRE_PAIR_CHOICES) - 1)
            out.append(WIRE_PAIR_CHOICES[wi])
            i += 1
        if self.tune_algorithm:
            ai = min(int(x[i] * len(ALGORITHMS)), len(ALGORITHMS) - 1)
            out.append(ALGORITHMS[ai])
        return tuple(out)

    # -- recording (engine hot path) ----------------------------------------

    def record_bytes(self, nbytes):
        """One fused collective completed (reference
        ParameterManager::Update counts tensor bytes per step)."""
        if not self.active:
            return
        if self._t0 is None:
            self._t0 = time.monotonic()
        self._bytes += nbytes
        self._steps += 1
        if self._steps >= self.steps_per_sample:
            self._finish_sample()

    def _metrics_record(self, score):
        """Export the sample count, best score and best config
        (telemetry/registry.py; docs/observability.md) — the CSV log's
        scrape-able twin."""
        from .. import telemetry

        reg = telemetry.registry()
        reg.counter(telemetry.AUTOTUNE_SAMPLES_FAMILY,
                    telemetry.AUTOTUNE_SAMPLES_HELP).inc()
        reg.gauge(telemetry.AUTOTUNE_BEST_SCORE_FAMILY,
                  telemetry.AUTOTUNE_BEST_SCORE_HELP
                  ).set(max(self._best_score, score)
                        if self._best_score != -np.inf else score)
        decoded = self._decode(self._best)
        fusion, cycle, _, _ = decoded[:4]
        i = 4
        wire = algo = ""
        if self.tune_wire:
            wire = wire_pair_label(*decoded[i])
            i += 1
        if self.tune_algorithm:
            algo = decoded[i]
        best = reg.gauge(
            telemetry.AUTOTUNE_BEST_CONFIG_FAMILY,
            telemetry.AUTOTUNE_BEST_CONFIG_HELP,
            labelnames=telemetry.AUTOTUNE_BEST_CONFIG_LABELS)
        # the gauge is an info-style marker: exactly ONE labeled child
        # (the current best) — a new best replaces, never accumulates
        best.clear()
        best.labels(fusion_threshold_bytes=fusion,
                    # hvdlint: ignore[telemetry-unbounded-label] info-gauge: best.clear() above caps it at ONE live child; the label IS the payload
                    cycle_time_ms=f"{cycle:.3f}", wire=wire,
                    algorithm=algo).set(1)

    def _finish_sample(self):
        elapsed = max(time.monotonic() - self._t0, 1e-6)
        score = self._bytes / elapsed
        self._samples += 1
        if self._log:
            decoded = self._decode(self._current)
            fusion, cycle, pack_mt, cache = decoded[:4]
            i = 4
            wire_col = ""
            if self.tune_wire:
                wire_col = f"{wire_pair_label(*decoded[i])},"
                i += 1
            algo_col = f"{decoded[i]}," if self.tune_algorithm else ""
            self._log.write(
                f"{self._samples},{fusion},{cycle:.3f},{pack_mt},"
                f"{cache},{wire_col}{algo_col}{score:.1f}\n")
            self._log.flush()
        if self._samples > self.warmup_samples:
            self._bo.observe(self._current, score)
            if score > self._best_score:
                self._best_score = score
                self._best = self._current
        try:
            self._metrics_record(score)
        except Exception:  # noqa: BLE001 — telemetry must never kill
            pass           # a tuning session
        if self._samples >= self.max_samples:
            # converge: pin best parameters, stop tuning (reference
            # parameter_manager.cc final tuning state)
            self._apply(self._best)
            self.active = False
        else:
            self._current = self._bo.suggest()
            self._apply(self._current)
        self._steps = 0
        self._bytes = 0
        self._t0 = None

    def _apply(self, x):
        decoded = self._decode(x)
        fusion, cycle, pack_mt, cache = decoded[:4]
        self.config.fusion_threshold_bytes = fusion
        self.config.cycle_time_ms = cycle
        self.config.pack_mt_threshold_bytes = pack_mt
        self.config.cache_capacity = cache
        i = 4
        if self.tune_wire:
            # one categorical, both halves applied at one instant —
            # the engine's per-entry latch (submit) then freezes the
            # pair per negotiation so a mid-submit flip can never
            # split one tensor across wire formats
            inner, outer = decoded[i]
            self.config.wire_inner = inner
            self.config.wire_dtype = outer
            i += 1
        if self.tune_algorithm:
            self.config.algorithm = decoded[i]

    def best_parameters(self):
        return self._decode(self._best)

    def close(self):
        if self._log:
            self._log.close()
            self._log = None

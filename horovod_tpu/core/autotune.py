"""Runtime parameter autotuning (reference
``horovod/common/parameter_manager.{h,cc}``: score = bytes/sec over
sample windows, warmup discard, Bayesian optimization over tunables,
CSV log via HOROVOD_AUTOTUNE_LOG, converge-to-best after max samples).

Tunables here are the six that exist on the TPU engine: the fusion
threshold (bucket size for packed allreduces), the cycle time (how
long the background thread batches submissions), the
multithreaded-pack threshold (bucket size above which the native pack
fans out across threads), the coordinator response-cache capacity
(the reference tunes cache on/off, parameter_manager.h:65; here the
LRU size tunes smoothly with 0 = disabled), the per-hop WIRE PAIR
((inner, outer) — full width / 16-bit on the intra-host/ICI hop,
anything up to block-scaled int4 on the cross-host/DCN hop,
ops/quantize.py WIRE_PAIR_CHOICES: a LEGAL-PAIR ENUMERATION swept as
ONE categorical, not a cross product — intra-hop int4 is never
legal, so the grid never proposes it), and the reduction ALGORITHM
(flat / hierarchical / torus, common/topology.py — the reference's
HOROVOD_HIERARCHICAL_ALLREDUCE / HOROVOD_TORUS_ALLREDUCE toggles as
one swept categorical).  The score is LOGICAL bytes/sec —
gradient goodput — so shrinking the wire payload (or keeping it off
the cross-host hop) raises the score exactly when the interconnect,
not the chip, is the bottleneck: that is how the parameter manager
learns to turn quantization or hierarchical routing on for
network-bound jobs and leave them off when the extra hops outweigh
the saved slow-hop bytes.  Algorithms that do not factor the running
topology silently execute flat (engine._algo_plan), so a sweep never
breaks a job — it just scores the fallback.
"""

import json
import os
import time

import numpy as np

from .optim import BayesianOptimizer
from .sharded import SHARD_LAYOUT_CHOICES
from ..common.env import OVERLAP_BUCKET_CHOICES
from ..common.topology import ALGORITHMS
from ..ops.quantize import WIRE_PAIR_CHOICES, wire_pair_label
# PP_CHOICES / pp_label load lazily in ParameterManager.__init__:
# importing parallel.schedule executes the whole parallel package
# (flax models, attention helpers), which only pipeline-tuning jobs
# should pay — the same deal common/env.py strikes for pp_schedule
PP_CHOICES = None
pp_label = None
# MOE_CHOICES / moe_label load lazily the same way (parallel.moe):
# only MoE jobs (config.moe_experts > 0) pay the parallel import
MOE_CHOICES = None
moe_label = None

# log2 bounds: fusion threshold 1 MiB .. 256 MiB, cycle 0.5 .. 32 ms,
# MT-pack threshold 1 MiB .. 64 MiB, cache capacity 0 .. 4096 entries
_FUSION_LO, _FUSION_HI = 20.0, 28.0
_CYCLE_LO, _CYCLE_HI = -1.0, 5.0
_PACKMT_LO, _PACKMT_HI = 20.0, 26.0
_CACHE_BITS = 12.0


class ParameterManager:
    def __init__(self, config, warmup_samples=3, steps_per_sample=10,
                 max_samples=20, log_path=None, seed=0, tune_wire=True,
                 tune_algorithm=True, tune_pipeline=False,
                 tune_sharded=False, tune_overlap=False,
                 tune_moe=False, cache_path=None, topo_fp="local",
                 world_size=1):
        self.config = config
        self.warmup_samples = warmup_samples
        self.steps_per_sample = steps_per_sample
        self.max_samples = max_samples
        self.active = True
        # tune_wire=False / tune_algorithm=False drop those categorical
        # dimensions entirely: the coordinator-side autotuner
        # (runner/http/http_server) has no distribution channel for a
        # tuned wire format or algorithm (workers applying a new
        # default at different cycles would fail the cross-process
        # consistency check), and sweeping a dimension nothing applies
        # would waste samples and write never-applied values into the
        # CSV
        self.tune_wire = bool(tune_wire)
        self.tune_algorithm = bool(tune_algorithm)
        # seventh dimension: the pipeline (schedule, n_micro) pair —
        # only swept when the job actually runs the MPMD pipeline
        # runtime (config.pp_stages > 1); the runtime re-latches the
        # pair at each step start and snaps an indivisible n_micro to
        # the nearest legal value, so a sweep can propose any bin
        # without breaking a step mid-flight
        self.tune_pipeline = bool(tune_pipeline)
        if self.tune_pipeline:
            global PP_CHOICES, pp_label
            from ..parallel.schedule import PP_CHOICES, pp_label
        # EIGHTH dimension: the shard-bucket layout of the sharded
        # weight update (core/sharded.SHARD_LAYOUT_CHOICES) — only
        # swept when the job runs DistributedOptimizer(sharded=True);
        # the updaters re-shard deterministically when a sweep flips
        # it (a coordinated vote, never mid-step)
        self.tune_sharded = bool(tune_sharded)
        # NINTH dimension: the compiled path's overlap bucket ceiling
        # (common/env.OVERLAP_BUCKET_CHOICES; 0 = one grouped
        # program) — only swept when HOROVOD_OVERLAP_AUTOTUNE opts
        # in: the dense reducer re-reads config.overlap_bucket_bytes
        # at each stream's start (never mid-stream), so a sweep can
        # flip the ceiling without splitting one step across bucket
        # layouts; the sharded train step latches it once at build
        self.tune_overlap = bool(tune_overlap)
        # TENTH dimension: the MoE routing geometry — the
        # (expert-parallel degree, capacity factor) pair
        # (parallel/moe.MOE_CHOICES) swept as ONE categorical, only
        # when the job actually hosts experts (config.moe_experts >
        # 0).  ep trades alltoall fan-out against per-rank expert
        # count; the capacity factor trades dropped tokens against
        # padded exchange bytes — both move the same wire, so they
        # sweep together.  The MoE layer re-latches the pair at step
        # start and snaps an ep that does not divide the set to the
        # nearest legal degree, so a sweep can propose any bin
        # without re-sharding mid-step
        self.tune_moe = bool(tune_moe)
        if self.tune_moe:
            global MOE_CHOICES, moe_label
            from ..parallel.moe import MOE_CHOICES, moe_label
        # warm start (docs/autotune.md "Warm start"): a local JSON
        # cache of converged best configs keyed by (bucket signature,
        # topology, world size) — production jobs start at
        # yesterday's optimum instead of re-learning from scratch.
        # The key completes when the engine notes the first fusion
        # bucket's signature (note_bucket_signature); convergence
        # persists under the same key.
        self.cache_path = cache_path
        self._key_suffix = f"{topo_fp}|np{int(world_size)}"
        if self.tune_sharded:
            # sharded jobs score a different wire/threshold landscape
            # (reducescatter+allgather vs allreduce): their optima
            # never warm-start a dense job, or vice versa
            self._key_suffix += "|sharded"
        if self.tune_overlap:
            # an overlap-swept optimum is only meaningful to jobs
            # that dispatch bucket-granular programs
            self._key_suffix += "|overlap"
        if self.tune_moe:
            # an expert job's optimum scores the alltoall wire on
            # top of the reduction wire — meaningless to dense jobs
            self._key_suffix += "|moe"
        self._cache_key = None
        self.warm_started = False
        dims = 4 + int(self.tune_wire) + int(self.tune_algorithm) \
            + int(self.tune_pipeline) + int(self.tune_sharded) \
            + int(self.tune_overlap) + int(self.tune_moe)
        self._bo = BayesianOptimizer(dims=dims, seed=seed)
        self._samples = 0
        self._steps = 0
        self._bytes = 0
        self._t0 = None
        self._current = self._encode(
            config.fusion_threshold_bytes, config.cycle_time_ms,
            getattr(config, "pack_mt_threshold_bytes", 8 << 20),
            getattr(config, "cache_capacity", 1024),
            (getattr(config, "wire_inner", None),
             getattr(config, "wire_dtype", None)),
            getattr(config, "algorithm", None),
            (getattr(config, "pp_schedule", None),
             getattr(config, "pp_n_micro", 0)),
            getattr(config, "shard_layout", None),
            getattr(config, "overlap_bucket_bytes", None),
            (getattr(config, "moe_ep", 0),
             getattr(config, "moe_capacity_factor", 0.0)))
        self._best_score = -np.inf
        self._best = self._current
        self._log = open(log_path, "w") if log_path else None
        if self._log:
            wire_col = "wire_pair," if self.tune_wire else ""
            algo_col = "algorithm," if self.tune_algorithm else ""
            pp_col = "pipeline," if self.tune_pipeline else ""
            shard_col = "shard_layout," if self.tune_sharded else ""
            ov_col = "overlap_bucket_bytes," if self.tune_overlap \
                else ""
            moe_col = "moe," if self.tune_moe else ""
            self._log.write(
                "sample,fusion_threshold_bytes,cycle_time_ms,"
                f"pack_mt_threshold_bytes,cache_capacity,{wire_col}"
                f"{algo_col}{pp_col}{shard_col}{ov_col}{moe_col}"
                "score_bytes_per_sec\n")

    # -- encoding ------------------------------------------------------------

    def _encode(self, fusion_bytes, cycle_ms, pack_mt_bytes,
                cache_capacity, wire_pair=None, algorithm=None,
                pp_pair=None, shard_layout=None, overlap_bucket=None,
                moe_pair=None):
        x0 = (np.log2(max(fusion_bytes, 1)) - _FUSION_LO) / \
            (_FUSION_HI - _FUSION_LO)
        x1 = (np.log2(max(cycle_ms, 2 ** _CYCLE_LO)) - _CYCLE_LO) / \
            (_CYCLE_HI - _CYCLE_LO)
        x2 = (np.log2(max(pack_mt_bytes, 1)) - _PACKMT_LO) / \
            (_PACKMT_HI - _PACKMT_LO)
        x3 = np.log2(cache_capacity + 1) / _CACHE_BITS
        xs = [x0, x1, x2, x3]
        if self.tune_wire:
            # fifth dimension: the per-hop (inner, outer) wire pair as
            # a categorical grid over [0, 1] (WIRE_PAIR_CHOICES at bin
            # centers — the BO's continuous suggestion snaps to the
            # nearest legal pair in _decode; quantized inner hops are
            # not in the enumeration, so the tuner can never propose
            # one).  Seeds canonicalize to the enumeration's spelling:
            # an unset inner INHERITS a 16-bit outer (the uniform
            # shorthand lands on the uniform bin), while an explicit
            # 'f32' inner keeps the cross-hop-only bin; an 'f32'
            # outer encodes as full width.  'f32' is only a distinct
            # spelling AGAINST a 16-bit outer — against a quantized or
            # unset outer the inner hop runs full width either way
            # (effective_inner_wire), so those seeds land on the
            # matching (None, outer) bin, and an API-legal 16-bit
            # inner the grid does not enumerate (e.g. fp16 over a
            # quantized outer) seeds its byte-equivalent 16-bit bin.
            inner, outer = wire_pair or (None, None)
            outer = None if outer == "f32" else outer
            if inner is None and outer in ("fp16", "bf16"):
                inner = outer
            elif inner == "f32" and outer not in ("fp16", "bf16"):
                inner = None
            try:
                wi = WIRE_PAIR_CHOICES.index((inner, outer))
            except ValueError:
                if inner in ("fp16", "bf16") and outer in ("int8", "int4"):
                    wi = WIRE_PAIR_CHOICES.index(("bf16", outer))
                else:
                    wi = 0
            xs.append((wi + 0.5) / len(WIRE_PAIR_CHOICES))
        if self.tune_algorithm:
            # sixth dimension: reduction algorithm over the same kind
            # of categorical grid; an unset default encodes as flat
            try:
                ai = ALGORITHMS.index(algorithm or "flat")
            except ValueError:
                ai = 0
            xs.append((ai + 0.5) / len(ALGORITHMS))
        if self.tune_pipeline:
            # seventh dimension: the pipeline (schedule, n_micro)
            # pair over the PP_CHOICES enumeration; an incumbent
            # n_micro outside the grid seeds the nearest bin of its
            # schedule so its score is attributed to its own
            # neighborhood, never to gpipe@2
            sched, m = pp_pair or (None, 0)
            sched = sched or "1f1b"
            try:
                pi = PP_CHOICES.index((sched, int(m or 0)))
            except ValueError:
                cands = [i for i, (s2, _) in enumerate(PP_CHOICES)
                         if s2 == sched] or [0]
                pi = min(cands, key=lambda i: abs(
                    PP_CHOICES[i][1] - int(m or PP_CHOICES[i][1])))
            xs.append((pi + 0.5) / len(PP_CHOICES))
        if self.tune_sharded:
            # eighth dimension: the shard-bucket layout categorical
            # (an unset default encodes as 'bucket')
            try:
                si = SHARD_LAYOUT_CHOICES.index(
                    shard_layout or "bucket")
            except ValueError:
                si = 0
            xs.append((si + 0.5) / len(SHARD_LAYOUT_CHOICES))
        if self.tune_overlap:
            # ninth dimension: the overlap bucket ceiling as a
            # categorical over OVERLAP_BUCKET_CHOICES; an incumbent
            # off the grid (hand-set env knob) seeds its nearest bin
            # so its score stays in its own neighborhood
            b = int(overlap_bucket or 0)
            oi = min(range(len(OVERLAP_BUCKET_CHOICES)),
                     key=lambda j: abs(OVERLAP_BUCKET_CHOICES[j] - b))
            xs.append((oi + 0.5) / len(OVERLAP_BUCKET_CHOICES))
        if self.tune_moe:
            # tenth dimension: the (ep, capacity factor) pair over
            # the MOE_CHOICES enumeration; an incumbent off the grid
            # (hand-set knobs) seeds the nearest bin of its ep degree
            # so its score stays in its own fan-out neighborhood
            ep, cf = moe_pair or (0, 0.0)
            ep = int(ep or 1)
            cf = float(cf or 1.25)
            try:
                mi = MOE_CHOICES.index((ep, cf))
            except ValueError:
                cands = [i for i, (e2, _) in enumerate(MOE_CHOICES)
                         if e2 == ep] or list(range(len(MOE_CHOICES)))
                mi = min(cands, key=lambda i: (
                    abs(MOE_CHOICES[i][0] - ep),
                    abs(MOE_CHOICES[i][1] - cf)))
            xs.append((mi + 0.5) / len(MOE_CHOICES))
        return np.clip(xs, 0.0, 1.0)

    def _decode(self, x):
        fusion = int(2 ** (_FUSION_LO + x[0] * (_FUSION_HI - _FUSION_LO)))
        cycle = float(2 ** (_CYCLE_LO + x[1] * (_CYCLE_HI - _CYCLE_LO)))
        pack_mt = int(2 ** (_PACKMT_LO + x[2] * (_PACKMT_HI - _PACKMT_LO)))
        # capacity 0 (cache off) is reachable at the low end — the
        # reference's cache-enabled toggle as the floor of a smooth dim
        cache = int(round(2 ** (x[3] * _CACHE_BITS))) - 1
        out = [fusion, cycle, pack_mt, cache]
        i = 4
        if self.tune_wire:
            wi = min(int(x[i] * len(WIRE_PAIR_CHOICES)),
                     len(WIRE_PAIR_CHOICES) - 1)
            out.append(WIRE_PAIR_CHOICES[wi])
            i += 1
        if self.tune_algorithm:
            ai = min(int(x[i] * len(ALGORITHMS)), len(ALGORITHMS) - 1)
            out.append(ALGORITHMS[ai])
            i += 1
        if self.tune_pipeline:
            pi = min(int(x[i] * len(PP_CHOICES)), len(PP_CHOICES) - 1)
            out.append(PP_CHOICES[pi])
            i += 1
        if self.tune_sharded:
            si = min(int(x[i] * len(SHARD_LAYOUT_CHOICES)),
                     len(SHARD_LAYOUT_CHOICES) - 1)
            out.append(SHARD_LAYOUT_CHOICES[si])
            i += 1
        if self.tune_overlap:
            oi = min(int(x[i] * len(OVERLAP_BUCKET_CHOICES)),
                     len(OVERLAP_BUCKET_CHOICES) - 1)
            out.append(OVERLAP_BUCKET_CHOICES[oi])
            i += 1
        if self.tune_moe:
            mi = min(int(x[i] * len(MOE_CHOICES)),
                     len(MOE_CHOICES) - 1)
            out.append(MOE_CHOICES[mi])
        return tuple(out)

    # -- recording (engine hot path) ----------------------------------------

    def record_bytes(self, nbytes):
        """One fused collective completed (reference
        ParameterManager::Update counts tensor bytes per step)."""
        if not self.active:
            return
        if self._t0 is None:
            self._t0 = time.monotonic()
        self._bytes += nbytes
        self._steps += 1
        if self._steps >= self.steps_per_sample:
            self._finish_sample()

    def abort_sample(self):
        """Discard the in-flight sample window (the engine's
        integrity quarantine): a quarantined step's window spans a
        rollback + replay, so its bytes/sec would score the current
        config against fictitious timing.  The next clean step starts
        a fresh window."""
        self._bytes = 0
        self._steps = 0
        self._t0 = None

    def _metrics_record(self, score):
        """Export the sample count, best score and best config
        (telemetry/registry.py; docs/observability.md) — the CSV log's
        scrape-able twin."""
        from .. import telemetry

        reg = telemetry.registry()
        reg.counter(telemetry.AUTOTUNE_SAMPLES_FAMILY,
                    telemetry.AUTOTUNE_SAMPLES_HELP).inc()
        reg.gauge(telemetry.AUTOTUNE_BEST_SCORE_FAMILY,
                  telemetry.AUTOTUNE_BEST_SCORE_HELP
                  ).set(max(self._best_score, score)
                        if self._best_score != -np.inf else score)
        decoded = self._decode(self._best)
        fusion, cycle, _, _ = decoded[:4]
        i = 4
        wire = algo = pipeline = shard = overlap = experts = ""
        if self.tune_wire:
            wire = wire_pair_label(*decoded[i])
            i += 1
        if self.tune_algorithm:
            algo = decoded[i]
            i += 1
        if self.tune_pipeline:
            pipeline = pp_label(*decoded[i])
            i += 1
        if self.tune_sharded:
            shard = decoded[i]
            i += 1
        if self.tune_overlap:
            overlap = str(decoded[i])
            i += 1
        if self.tune_moe:
            experts = moe_label(*decoded[i])
        best = reg.gauge(
            telemetry.AUTOTUNE_BEST_CONFIG_FAMILY,
            telemetry.AUTOTUNE_BEST_CONFIG_HELP,
            labelnames=telemetry.AUTOTUNE_BEST_CONFIG_LABELS)
        # the gauge is an info-style marker: exactly ONE labeled child
        # (the current best) — a new best replaces, never accumulates
        best.clear()
        best.labels(fusion_threshold_bytes=fusion,
                    # hvdlint: ignore[telemetry-unbounded-label] info-gauge: best.clear() above caps it at ONE live child; the label IS the payload
                    cycle_time_ms=f"{cycle:.3f}", wire=wire,
                    algorithm=algo, pipeline=pipeline,
                    shard_layout=shard,
                    overlap_bucket=overlap,
                    experts=experts).set(1)

    def _finish_sample(self):
        elapsed = max(time.monotonic() - self._t0, 1e-6)
        score = self._bytes / elapsed
        self._samples += 1
        if self._log:
            decoded = self._decode(self._current)
            fusion, cycle, pack_mt, cache = decoded[:4]
            i = 4
            wire_col = algo_col = pp_col = shard_col = ov_col = ""
            moe_col = ""
            if self.tune_wire:
                wire_col = f"{wire_pair_label(*decoded[i])},"
                i += 1
            if self.tune_algorithm:
                algo_col = f"{decoded[i]},"
                i += 1
            if self.tune_pipeline:
                pp_col = f"{pp_label(*decoded[i])},"
                i += 1
            if self.tune_sharded:
                shard_col = f"{decoded[i]},"
                i += 1
            if self.tune_overlap:
                ov_col = f"{decoded[i]},"
                i += 1
            if self.tune_moe:
                moe_col = f"{moe_label(*decoded[i])},"
            self._log.write(
                f"{self._samples},{fusion},{cycle:.3f},{pack_mt},"
                f"{cache},{wire_col}{algo_col}{pp_col}{shard_col}"
                f"{ov_col}{moe_col}{score:.1f}\n")
            self._log.flush()
        if self._samples > self.warmup_samples:
            self._bo.observe(self._current, score)
            if score > self._best_score:
                self._best_score = score
                self._best = self._current
        try:
            self._metrics_record(score)
        except Exception:  # noqa: BLE001 — telemetry must never kill
            pass           # a tuning session
        if self._samples >= self.max_samples:
            # converge: pin best parameters, stop tuning (reference
            # parameter_manager.cc final tuning state) — and persist
            # them so the next same-shaped job warm-starts here
            self._apply(self._best)
            self.active = False
            try:
                self._save_cache()
            except Exception:  # noqa: BLE001 — the cache is an
                pass           # optimization, never a failure mode
        else:
            self._current = self._bo.suggest()
            self._apply(self._current)
        self._steps = 0
        self._bytes = 0
        self._t0 = None

    def _apply(self, x):
        decoded = self._decode(x)
        fusion, cycle, pack_mt, cache = decoded[:4]
        self.config.fusion_threshold_bytes = fusion
        self.config.cycle_time_ms = cycle
        self.config.pack_mt_threshold_bytes = pack_mt
        self.config.cache_capacity = cache
        i = 4
        if self.tune_wire:
            # one categorical, both halves applied at one instant —
            # the engine's per-entry latch (submit) then freezes the
            # pair per negotiation so a mid-submit flip can never
            # split one tensor across wire formats
            inner, outer = decoded[i]
            self.config.wire_inner = inner
            self.config.wire_dtype = outer
            i += 1
        if self.tune_algorithm:
            self.config.algorithm = decoded[i]
            i += 1
        if self.tune_pipeline:
            # one categorical again: schedule and n_micro flip
            # together; the pipeline runtime latches the pair at its
            # next step start (and the engine per negotiation entry),
            # so the running step finishes under its own schedule
            sched, m = decoded[i]
            self.config.pp_schedule = sched
            self.config.pp_n_micro = int(m)
            i += 1
        if self.tune_sharded:
            # the sharded updaters re-read this at their coordinated
            # re-shard vote (a flip re-shards between steps, never
            # splits one)
            self.config.shard_layout = decoded[i]
            i += 1
        if self.tune_overlap:
            # the compiled reducer latches this per stream (every
            # stream re-reads it at construction), so a flip takes
            # effect at the NEXT step's first bucket — one step can
            # never split across bucket layouts
            self.config.overlap_bucket_bytes = int(decoded[i])
            i += 1
        if self.tune_moe:
            # one categorical: ep and capacity factor flip together;
            # the MoE layer latches the pair at its next step start
            # (snapping ep to a divisor of the set size), so the
            # running step's routing geometry never splits
            ep, cf = decoded[i]
            self.config.moe_ep = int(ep)
            self.config.moe_capacity_factor = float(cf)

    def best_parameters(self):
        return self._decode(self._best)

    # -- warm-start cache ----------------------------------------------------

    def note_bucket_signature(self, sig):
        """The engine observed its first fusion bucket: ``sig`` (a
        stable hash of the bucket's tensor keys/shapes/dtype)
        completes the cache key — (bucket signature, topology, world
        size) — and triggers the one warm-start lookup.  Idempotent;
        only the first signature counts (steady-state training re-forms
        the same buckets every cycle, which is what makes the key
        stable across jobs)."""
        if self._cache_key is not None:
            return
        self._cache_key = f"{sig}|{self._key_suffix}"
        if self.cache_path:
            try:
                self._load_cache()
            except Exception:  # noqa: BLE001 — a corrupt cache file
                pass           # must never take down a job

    def _cache_entry(self):
        decoded = self._decode(self._best)
        fusion, cycle, pack_mt, cache = decoded[:4]
        entry = {"fusion_threshold_bytes": int(fusion),
                 "cycle_time_ms": float(cycle),
                 "pack_mt_threshold_bytes": int(pack_mt),
                 "cache_capacity": int(cache),
                 "score_bytes_per_sec": float(self._best_score)
                 if self._best_score != -np.inf else 0.0}
        i = 4
        if self.tune_wire:
            entry["wire_inner"], entry["wire_outer"] = decoded[i]
            i += 1
        if self.tune_algorithm:
            entry["algorithm"] = decoded[i]
            i += 1
        if self.tune_pipeline:
            entry["pp_schedule"], entry["pp_n_micro"] = decoded[i]
            i += 1
        if self.tune_sharded:
            entry["shard_layout"] = decoded[i]
            i += 1
        if self.tune_overlap:
            entry["overlap_bucket_bytes"] = int(decoded[i])
            i += 1
        if self.tune_moe:
            ep, cf = decoded[i]
            entry["moe_ep"] = int(ep)
            entry["moe_capacity_factor"] = float(cf)
        return entry

    def _load_cache(self):
        if not (self.cache_path and os.path.exists(self.cache_path)):
            return
        with open(self.cache_path) as f:
            data = json.load(f)
        entry = data.get(self._cache_key)
        if not isinstance(entry, dict):
            return
        seed = self._encode(
            entry.get("fusion_threshold_bytes",
                      self.config.fusion_threshold_bytes),
            entry.get("cycle_time_ms", self.config.cycle_time_ms),
            entry.get("pack_mt_threshold_bytes",
                      getattr(self.config, "pack_mt_threshold_bytes",
                              8 << 20)),
            entry.get("cache_capacity",
                      getattr(self.config, "cache_capacity", 1024)),
            (entry.get("wire_inner"), entry.get("wire_outer")),
            entry.get("algorithm"),
            (entry.get("pp_schedule"), entry.get("pp_n_micro", 0)),
            entry.get("shard_layout"),
            entry.get("overlap_bucket_bytes"),
            (entry.get("moe_ep", 0),
             entry.get("moe_capacity_factor", 0.0)))
        # start the sweep AT the cached optimum: it becomes both the
        # applied config and the BO's incumbent, so early suggestions
        # explore around it instead of from scratch
        self._best = self._current = seed
        self._apply(seed)
        # the log-scale encoding quantizes integers by ~1 ulp; apply
        # the EXACT cached values on top so the job runs yesterday's
        # optimum verbatim, not its nearest grid point
        for attr, key in (("fusion_threshold_bytes",
                           "fusion_threshold_bytes"),
                          ("cycle_time_ms", "cycle_time_ms"),
                          ("pack_mt_threshold_bytes",
                           "pack_mt_threshold_bytes"),
                          ("cache_capacity", "cache_capacity"),
                          ("overlap_bucket_bytes",
                           "overlap_bucket_bytes")):
            if key in entry:
                setattr(self.config, attr, entry[key])
        self.warm_started = True

    def _save_cache(self):
        if not (self.cache_path and self._cache_key):
            return
        os.makedirs(os.path.dirname(os.path.abspath(self.cache_path)),
                    exist_ok=True)
        # advisory lock on a sidecar (os.replace swaps the cache
        # file's inode, so locking the cache itself would not
        # serialize writers): two jobs sharing one cache converge
        # concurrently under DIFFERENT keys — without the lock the
        # second read-merge-replace drops the first job's entry
        lock = open(f"{self.cache_path}.lock", "w")
        try:
            try:
                import fcntl
                fcntl.flock(lock, fcntl.LOCK_EX)
            except (ImportError, OSError):
                pass   # no flock: keep the lock-free best effort
            data = {}
            if os.path.exists(self.cache_path):
                try:
                    with open(self.cache_path) as f:
                        data = json.load(f)
                except (OSError, ValueError):
                    data = {}
            if not isinstance(data, dict):
                data = {}
            prior = data.get(self._cache_key) or {}
            if prior.get("score_bytes_per_sec", -1.0) > \
                    float(self._best_score):
                return   # never clobber a better prior optimum
            data[self._cache_key] = self._cache_entry()
            tmp = f"{self.cache_path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(data, f, indent=1, sort_keys=True)
            os.replace(tmp, self.cache_path)   # readers never see a
        finally:                               # torn file
            lock.close()

    def close(self):
        if self._log:
            self._log.close()
            self._log = None

"""Async completion handles.

TPU-native analogue of the reference torch binding's ``HandleManager``
(torch/handle_manager.h): every enqueued collective returns an integer
handle which ``poll()``/``synchronize()`` resolve.  Unlike the
reference (busy-wait over a Status table), completion is event-based.
"""

import threading
from typing import Any, Optional


class Handle:
    """Completion record for one enqueued tensor operation."""

    __slots__ = ("_event", "result", "error", "extra", "kind",
                 "inplace_target", "inplace_targets", "returns_splits",
                 "grouped")

    def __init__(self):
        self._event = threading.Event()
        self.result: Any = None
        self.error: Optional[BaseException] = None
        # op-specific side data (e.g. alltoall received splits)
        self.extra: Any = None
        # API-layer metadata: original tensor kind(s), in-place target,
        # whether synchronize() should return (tensor, recv_splits).
        self.kind: Any = "numpy"
        self.inplace_target: Any = None
        # grouped in-place variant: per-tensor write-back targets
        self.inplace_targets: Any = None
        self.returns_splits: bool = False
        # grouped ops always resolve to a list of tensors
        self.grouped: bool = False

    def done(self) -> bool:
        return self._event.is_set()

    def set_result(self, result, extra=None):
        self.result = result
        self.extra = extra
        self._event.set()

    def set_error(self, exc: BaseException):
        self.error = exc
        self._event.set()

    def wait(self, timeout=None):
        if not self._event.wait(timeout):
            raise TimeoutError("collective operation did not complete in time")
        if self.error is not None:
            raise self.error
        return self.result


class HandleManager:
    """Maps integer handles to Handle records (reference
    torch/handle_manager.h:24-41)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._next = 0
        self._handles = {}

    def allocate(self) -> int:
        with self._lock:
            h = self._next
            self._next += 1
            self._handles[h] = Handle()
            return h

    def get(self, handle: int) -> Handle:
        with self._lock:
            rec = self._handles.get(handle)
        if rec is None:
            raise ValueError(f"unknown or already-released handle {handle}")
        return rec

    def poll(self, handle: int) -> bool:
        return self.get(handle).done()

    def release(self, handle: int):
        with self._lock:
            self._handles.pop(handle, None)

    def synchronize(self, handle: int, timeout=None):
        rec = self.get(handle)
        try:
            return rec.wait(timeout)
        finally:
            self.release(handle)

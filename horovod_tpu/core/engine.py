"""Core runtime: rank contexts, negotiation, fusion, dispatch.

TPU-native analogue of the reference's core
(``horovod/common/operations.cc`` BackgroundThreadLoop/RunLoopOnce +
``controller.cc`` ComputeResponseList):

* Each **rank** is a rank context bound to a device of the mesh.  On a
  TPU host one process drives all local chips, so ranks live as threads
  of one process — not one OS process per accelerator the way CUDA
  forces.  Multi-host jobs run one such process per host.
* Rank threads **enqueue** tensors (EnqueueTensorAllreduce analogue);
  a single background thread negotiates readiness (a tensor executes
  only when every participating rank has submitted it — the exact
  contract of controller.cc:74-474), **fuses** ready allreduces into
  buckets under the fusion threshold (FuseResponses,
  controller.cc:901-1080), and dispatches each bucket to a cached
  compiled XLA collective (ops/xla_ops.py).
* Single-process: the negotiation table *is* shared memory — no wire
  protocol.  Multi-process: a :class:`StoreController` reports local
  readiness to the launcher-hosted coordinator and executes the
  coordinator's ordered response log, which keeps every process
  issuing identical SPMD programs (core/store_controller.py).
* Completion flows back through async handles
  (torch/handle_manager.h analogue).
"""

import logging
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from ..common import env as env_mod
from ..utils import profiler
from ..common.exceptions import (
    DuplicateNameError,
    HorovodInternalError,
    HorovodInitError,
    StalledTensorError,
    TensorShapeMismatchError,
)
from . import integrity as integrity_mod
from .message import ReduceOp, Request, RequestType
from .handles import Handle, HandleManager

logger = logging.getLogger("horovod_tpu")


@dataclass
class Submission:
    """One rank's (possibly grouped) tensor submission — the engine-side
    TensorTableEntry (reference common.h TensorTableEntry)."""
    rank: int
    request: Request
    names: List[str]
    payloads: List[np.ndarray]          # host buffers, one per tensor
    handle: Handle
    enq_time: float = field(default_factory=time.monotonic)
    #: submit-time payload digests (core/integrity.digest64, one per
    #: payload) — re-verified after fusion packing so a bit flipped in
    #: the gradient between submit and encode is detected and
    #: attributed to this rank instead of trained on
    payload_fp: Optional[List[int]] = None


class NegotiationEntry:
    """Readiness table row (reference controller.cc:1115-1140
    IncrementTensorCount)."""

    __slots__ = ("key", "subs", "first_time", "wire_default",
                 "wire_inner_default", "algo_default", "pp_default",
                 "ready_ts", "trace_id", "meta_fp")

    def __init__(self, key):
        self.key = key
        self.subs: Dict[int, Submission] = {}
        self.first_time = time.monotonic()
        # memoized meta fingerprint (core/bypass.py): the meta is
        # invariant once the entry is fully submitted, and the armed
        # bypass consults it every engine tick
        self.meta_fp = None
        # process-wide wire default LATCHED when the first local rank
        # arrives, so an autotune sweep flipping config.wire_dtype
        # between two ranks' submits of the same tensor cannot split
        # one negotiation across two wire formats
        self.wire_default = None
        # ditto for the inner (ICI) hop of the per-hop wire pair
        # (config.wire_inner) and the reduction algorithm
        # (config.algorithm)
        self.wire_inner_default = None
        self.algo_default = None
        # ditto for the pipeline-schedule tag (parallel/runtime.py
        # sets config.pp_sched_tag for the duration of a step)
        self.pp_default = None
        # timeline-clock instant this entry became locally ready (the
        # flow-event "s" anchor) and its job-unique trace id
        # (coordinator-minted in store mode, engine-minted locally)
        self.ready_ts = None
        self.trace_id = None


class ProcessSetState:
    """Runtime state for one process set (reference process_set.h:26-84:
    controller + tensor queue + joined state per set)."""

    def __init__(self, ps_id, ranks, executor, local_ranks=None):
        self.id = ps_id
        self.ranks = list(ranks)            # global ranks, sorted
        self.index = {r: i for i, r in enumerate(self.ranks)}
        self.local_ranks = list(local_ranks) if local_ranks is not None \
            else list(self.ranks)           # subset hosted by this process
        self.executor = executor
        self.pending: "OrderedDict[str, NegotiationEntry]" = OrderedDict()
        self.awaiting: Dict[str, NegotiationEntry] = {}  # store mode
        self.joined = set()                 # local ranks that called join()
        self.last_joined = -1
        self.join_waiters: Dict[int, Handle] = {}
        self.join_reported = False

    @property
    def size(self):
        return len(self.ranks)


class Engine:
    """The per-process core runtime (reference HorovodGlobalState +
    BackgroundThreadLoop, global_state.h:39-126, operations.cc:409-749).

    ``num_ranks`` ranks are hosted in this process, covering global
    ranks [rank_offset, rank_offset + num_ranks) of a ``global_size``
    world.  Single-process: offset 0, global == local.
    """

    def __init__(self, num_ranks, devices, config=None, topology=None,
                 timeline=None, controller=None, rank_offset=0,
                 global_size=None, ranks_of_proc=None, chaos=None):
        from ..ops.xla_ops import MeshExecutor

        self.config = config or env_mod.Config()
        self.num_local = num_ranks
        self.global_size = global_size if global_size else num_ranks
        self.rank_offset = rank_offset
        # per-process rank counts for heterogeneous host:slots jobs
        # (reference -H h1:4,h2:2); None => uniform num_local per proc
        self.ranks_of_proc = list(ranks_of_proc) if ranks_of_proc \
            else None
        if self.ranks_of_proc:
            starts, acc = [], 0
            for n in self.ranks_of_proc:
                starts.append(acc)
                acc += n
            if acc != self.global_size:
                raise ValueError(
                    f"ranks_of_proc sums to {acc} but global size is "
                    f"{self.global_size}")
            self._proc_starts = starts
        self.devices = list(devices)
        self.topology = topology
        self.controller = controller
        self.handle_manager = HandleManager()
        self.timeline = timeline

        self._lock = threading.Condition()  # hvdlint: lock[engine:20]
        self._shutdown = False
        self._aborted: Optional[BaseException] = None
        self._shutdown_done = threading.Event()

        self._MeshExecutor = MeshExecutor
        ps0 = self._make_process_set_state(0, range(self.global_size))
        self.process_sets: Dict[int, ProcessSetState] = {0: ps0}
        self._next_ps_id = 1
        # removal barrier bookkeeping (see remove_process_set)
        self._removal_events: Dict[int, threading.Event] = {}
        self._removal_votes: Dict[int, set] = {}
        self._removed_ps_ids: set = set()

        self.autotuner = None
        if self.config.autotune and controller is None:
            # autotune is per-process; in multi-process mode fusion is
            # the coordinator's decision (reference: coordinator tunes,
            # SynchronizeParameters broadcasts — a future round)
            from .autotune import ParameterManager
            # topology fingerprint for the warm-start cache key: slot
            # counts per host (the layout that decides hierarchical /
            # torus viability), or flat<N> without a host map
            if topology is not None and topology.host_of_rank:
                counts = {}
                for h in topology.host_of_rank:
                    counts[h] = counts.get(h, 0) + 1
                topo_fp = "h" + "-".join(
                    str(counts[h]) for h in sorted(counts))
            else:
                topo_fp = f"flat{self.global_size}"
            self.autotuner = ParameterManager(
                self.config,
                warmup_samples=self.config.autotune_warmup_samples,
                steps_per_sample=self.config.autotune_steps_per_sample,
                max_samples=self.config.autotune_max_samples,
                log_path=self.config.autotune_log,
                tune_pipeline=getattr(self.config, "pp_stages", 1) > 1,
                tune_sharded=bool(getattr(self.config,
                                          "sharded_optimizer", False)),
                tune_overlap=bool(getattr(self.config,
                                          "overlap_autotune", False)),
                tune_moe=getattr(self.config, "moe_experts", 0) > 0,
                cache_path=getattr(self.config, "autotune_cache", None),
                topo_fp=topo_fp, world_size=self.global_size)
        #: first-fusion-bucket signature noted exactly once per
        #: lifecycle (autotuner warm-start cache key)
        self._autotune_sig_noted = False

        from . import native as _native
        self._arena = _native.Arena()

        self._stall_warned = set()
        self._algo_warned = set()
        #: alltoall error-feedback residuals, keyed (ps.id, rank):
        #: the quantization error of the last exchange's padded
        #: per-peer-slot layout, re-injected slot-by-slot into the
        #: next exchange with the same layout.  Cleared on quarantine
        #: (a residual from the corrupted step must not seed the
        #: replay) and dropped whenever the layout changes.
        self._a2a_ef = {}
        # local-mode trace ids (store mode uses coordinator-minted
        # ones); offset by the rank window so per-process single-mode
        # traces merged offline never collide
        self._next_trace_id = self.rank_offset << 24
        # local stall inspector's deferred flight-recorder dump reason
        # (set under the lock, dumped outside it — the dump may do IO)
        self._pending_trace_dump = None
        # one fresh registry per engine lifecycle (telemetry/registry):
        # every counter the benchmarks and the /metrics endpoints read
        # lives here; the legacy engine attributes (logical_wire_bytes,
        # algo_runs, ...) are deprecated property shims over these
        # families — see docs/observability.md
        self._install_metrics()
        #: hold_cycles() depth — while >0 the loop parks (no dispatch)
        self._hold_depth = 0
        self._tl_queues_nonzero = False
        self._metrics_pusher = None
        self._start_metrics_push()
        self._clock_sync = None
        self._start_clock_sync()
        #: chaos fault injector (chaos/inject.py FaultInjector): the
        #: background loop calls its on_collectives hook right before
        #: report_ready, so slow-rank scenarios delay exactly the
        #: report the coordinator's stall attribution watches
        self.chaos = chaos
        #: end-to-end step integrity (core/integrity.py): submit-time
        #: payload digests + encode-time wire digests re-verified at
        #: decode, with the per-bucket implicated-rank MIN vote making
        #: detection (and the quarantine it triggers) unanimous across
        #: processes; None when HOROVOD_INTEGRITY=0
        self.integrity = None
        if getattr(self.config, "integrity", True):
            self.integrity = integrity_mod.IntegrityChecker(
                evict_after=getattr(self.config,
                                    "integrity_evict_after", 3))
        #: steady-state negotiation bypass (core/bypass.py): armed by
        #: the coordinator's bypass_arm record once every proc voted
        #: the same stable cycle fingerprint; while active the
        #: background loop runs _bypass_cycle instead of _store_cycle
        self._bypass = None
        if self.multiproc and \
                getattr(self.config, "bypass_after_cycles", 0) > 0:
            from .bypass import BypassState
            self._bypass = BypassState(
                self.config.bypass_after_cycles,
                getattr(self.config, "bypass_wait_secs", 10.0))
        self._hb_stop = threading.Event()
        self._hb_thread = None
        self._start_heartbeat()
        self._thread = threading.Thread(
            target=self._background_loop, name="horovod_tpu-engine",
            daemon=True)
        self._thread.start()

    # ------------------------------------------------------------------
    # compat + helpers

    @property
    def num_ranks(self):
        """Global world size (API surface: hvd.size())."""
        return self.global_size

    @property
    def multiproc(self):
        return self.controller is not None

    # ------------------------------------------------------------------
    # telemetry

    def _install_metrics(self):
        """Create this engine's registry and the standard families
        (telemetry/registry.py).  Families the compiled path, the
        autotuner and the elastic driver update are pre-declared too,
        so a scrape always shows the full catalogue (zero-valued until
        touched) — the contract docs/observability.md documents."""
        from .. import telemetry

        m = self.metrics = telemetry.fresh_registry()
        self._m_logical = m.counter(
            "horovod_wire_logical_bytes_total",
            "Full-width payload bytes handed to reductions",
            labelnames=("wire",))
        self._m_actual = m.counter(
            "horovod_wire_actual_bytes_total",
            "Bytes the wire encoding actually puts on the interconnect",
            labelnames=("wire",))
        self._m_cross = m.counter(
            "horovod_wire_cross_bytes_total",
            "Bytes that crossed the slow (cross-host / DCN) hop",
            labelnames=("wire",))
        self._m_algo = m.counter(
            "horovod_allreduce_runs_total",
            "Allreduce buckets executed per reduction algorithm",
            labelnames=("algorithm",))
        self._m_quantized = m.counter(
            "horovod_quantized_buckets_total",
            "Buckets executed over a block-scaled quantized "
            "(int8/int4) wire")
        self._m_hop = m.counter(
            telemetry.WIRE_HOP_BYTES_FAMILY,
            telemetry.WIRE_HOP_BYTES_HELP,
            labelnames=telemetry.WIRE_HOP_BYTES_LABELS)
        self._m_fused_ag = m.counter(
            "horovod_fused_allgather_runs_total",
            "Fused allgather buckets executed")
        # fused quantized alltoall (the MoE dispatch/combine wire):
        # byte families split by destination hop x wire, plus the
        # per-path runs counter — pre-declared so a scrape always
        # shows them; ops/compiled.py bumps the same names through
        # the telemetry helpers
        self._m_a2a_logical = m.counter(
            telemetry.ALLTOALL_LOGICAL_BYTES_FAMILY,
            telemetry.ALLTOALL_LOGICAL_BYTES_HELP,
            labelnames=telemetry.ALLTOALL_LOGICAL_BYTES_LABELS)
        self._m_a2a_wire = m.counter(
            telemetry.ALLTOALL_WIRE_BYTES_FAMILY,
            telemetry.ALLTOALL_WIRE_BYTES_HELP,
            labelnames=telemetry.ALLTOALL_WIRE_BYTES_LABELS)
        self._m_a2a_runs = m.counter(
            telemetry.ALLTOALL_RUNS_FAMILY,
            telemetry.ALLTOALL_RUNS_HELP,
            labelnames=telemetry.ALLTOALL_RUNS_LABELS)
        m.counter(telemetry.ALLTOALL_EXPOSED_SECONDS_FAMILY,
                  telemetry.ALLTOALL_EXPOSED_SECONDS_HELP,
                  labelnames=telemetry.ALLTOALL_EXPOSED_SECONDS_LABELS)
        # weight-update sharding (core/sharded.py): the runs counter
        # is bumped by the updaters, the state gauge by the frontends
        # after they build their shard state — pre-declared here so a
        # scrape always shows the families (zero until sharded mode
        # actually runs)
        self._m_sharded = m.counter(
            telemetry.SHARDED_UPDATE_RUNS_FAMILY,
            telemetry.SHARDED_UPDATE_RUNS_HELP)
        m.gauge(telemetry.OPTIMIZER_STATE_BYTES_FAMILY,
                telemetry.OPTIMIZER_STATE_BYTES_HELP,
                labelnames=telemetry.OPTIMIZER_STATE_BYTES_LABELS)
        self._m_negotiation = m.histogram(
            "horovod_negotiation_seconds",
            "First local submission to locally-ready, per op",
            labelnames=("op",))
        self._m_execution = m.histogram(
            "horovod_execution_seconds",
            "Bucket dispatch to completion, per op",
            labelnames=("op",))
        self._m_cycle = m.histogram(
            "horovod_cycle_seconds",
            "Active portion of engine cycles that produced work")
        self._m_cycles = m.counter(
            "horovod_engine_cycles_total",
            "Engine negotiation cycles that produced work")
        self._m_pending = m.gauge(
            "horovod_pending_entries",
            "Negotiation entries awaiting local submissions",
            labelnames=("process_set",))
        self._m_awaiting = m.gauge(
            "horovod_awaiting_entries",
            "Locally-ready entries awaiting the coordinator's schedule",
            labelnames=("process_set",))
        self._m_stalled = m.gauge(
            "horovod_stalled_tensors",
            "Entries currently past the stall warning time",
            labelnames=("process_set",))
        self._m_stall_warn = m.counter(
            "horovod_stall_warnings_total",
            "Stall warnings issued; 'ranks' names the global ranks "
            "attributed (locally-missing ranks, or every rank a "
            "non-reporting process hosts)",
            labelnames=("ranks",))
        self._m_ring_dumps = m.counter(
            "horovod_trace_ring_dumps_total",
            "Flight-recorder ring dumps (stall auto-dumps, coordinator"
            " requests, hvd.dump_trace)",
            labelnames=("reason",))
        # steady-state negotiation bypass + coordinator crash survival
        # (docs/fault_tolerance.md): hit cycles ran without touching
        # the coordinator, fallback cycles disengaged (labeled by
        # reason); the histogram times vote + execution of hit cycles.
        # Resyncs are counted by the StoreController on epoch bumps.
        self._m_bypass = m.counter(
            telemetry.BYPASS_CYCLES_FAMILY,
            telemetry.BYPASS_CYCLES_HELP,
            labelnames=("outcome",))
        self._m_bypass_cycle = m.histogram(
            telemetry.BYPASS_CYCLE_SECONDS_FAMILY,
            telemetry.BYPASS_CYCLE_SECONDS_HELP)
        m.counter(telemetry.COORD_RESYNCS_FAMILY,
                  telemetry.COORD_RESYNCS_HELP)
        # families owned by other layers, pre-declared for the
        # catalogue (names+helps live ONCE in telemetry/__init__.py;
        # hvdlint checker 4 rejects literal copies)
        m.counter(telemetry.PROGRAM_CACHE_HITS_FAMILY,
                  telemetry.PROGRAM_CACHE_HITS_HELP)
        m.counter(telemetry.PROGRAM_CACHE_MISSES_FAMILY,
                  telemetry.PROGRAM_CACHE_MISSES_HELP)
        m.counter(telemetry.COMPILE_SECONDS_FAMILY,
                  telemetry.COMPILE_SECONDS_HELP)
        m.counter(telemetry.AUTOTUNE_SAMPLES_FAMILY,
                  telemetry.AUTOTUNE_SAMPLES_HELP)
        m.gauge(telemetry.AUTOTUNE_BEST_SCORE_FAMILY,
                telemetry.AUTOTUNE_BEST_SCORE_HELP)
        m.gauge(telemetry.AUTOTUNE_BEST_CONFIG_FAMILY,
                telemetry.AUTOTUNE_BEST_CONFIG_HELP,
                labelnames=telemetry.AUTOTUNE_BEST_CONFIG_LABELS)
        m.counter(telemetry.ELASTIC_RESIZE_FAMILY,
                  telemetry.ELASTIC_RESIZE_HELP,
                  labelnames=("direction",))
        # fabric/chaos/liveness families (docs/fault_tolerance.md):
        # retries are counted by the StoreClient, injections by the
        # chaos injector, and worker_alive is set by the heartbeat
        # thread (the coordinator's /metrics adds its authoritative
        # per-proc view, including the 0 a dead worker can't push)
        m.counter(telemetry.FABRIC_RETRIES_FAMILY,
                  telemetry.FABRIC_RETRIES_HELP, labelnames=("verb",))
        m.counter(telemetry.FAULTS_INJECTED_FAMILY,
                  telemetry.FAULTS_INJECTED_HELP, labelnames=("kind",))
        # step-integrity families (core/integrity.py; docs/
        # fault_tolerance.md "Silent data corruption"): checks are
        # counted at every verification site, rollbacks once per
        # quarantined step, and the histogram times sentinel rounds
        m.counter(telemetry.INTEGRITY_CHECKS_FAMILY,
                  telemetry.INTEGRITY_CHECKS_HELP,
                  labelnames=telemetry.INTEGRITY_CHECKS_LABELS)
        m.counter(telemetry.INTEGRITY_ROLLBACKS_FAMILY,
                  telemetry.INTEGRITY_ROLLBACKS_HELP,
                  labelnames=telemetry.INTEGRITY_ROLLBACKS_LABELS)
        m.histogram(telemetry.INTEGRITY_SENTINEL_SECONDS_FAMILY,
                    telemetry.INTEGRITY_SENTINEL_SECONDS_HELP)
        self._m_alive = m.gauge(
            telemetry.WORKER_ALIVE_FAMILY, telemetry.WORKER_ALIVE_HELP,
            labelnames=("proc",))
        ws = m.gauge("horovod_world_size", "Global number of ranks")
        ws.set(self.global_size)

    def _start_metrics_push(self):
        """Multi-process jobs push periodic registry snapshots to the
        launcher's KV store over the existing fabric; the coordinator
        merges them into its job-wide /metrics."""
        if not self.multiproc:
            return
        secs = getattr(self.config, "metrics_push_secs", 0.0)
        if secs <= 0:
            return
        from ..telemetry import MetricsPusher
        self._metrics_pusher = MetricsPusher(
            self.controller.client, self.controller.proc_id,
            interval=secs,
            # round + proc let the coordinator drop stale snapshots
            # (elastic downsizes, previous rounds) from the aggregate
            meta={"rank_offset": self.rank_offset,
                  "num_local": self.num_local,
                  "round": self.controller.round_id}).start()

    def push_metrics(self):
        """Push this worker's snapshot to the coordinator NOW (the
        periodic pusher's out-of-band hook — tests and short jobs)."""
        if self._metrics_pusher is not None:
            self._metrics_pusher.push_now()

    # ------------------------------------------------------------------
    # job-wide tracing (docs/timeline.md "Job-wide traces")

    def _start_clock_sync(self):
        """Multi-process jobs map this worker's timeline epoch onto
        the launcher's clock (NTP midpoint over the coordinator's
        ``clock`` verb, re-sampled for drift) so per-worker traces
        merge onto one time axis.  Single-process timelines carry a
        wall-clock mapping from birth — nothing to sync against.
        Idempotent: also re-invoked when ``hvd.start_timeline()``
        creates the first timeline after init."""
        if self._clock_sync is not None:
            return
        if not self.multiproc or self.timeline is None:
            return
        secs = getattr(self.config, "clock_sync_secs", 0.0)
        if secs <= 0:
            return
        from ..utils.clock_sync import ClockSync
        # resolve the timeline at every sync round: start_timeline /
        # stop_timeline may swap it at runtime
        self._clock_sync = ClockSync(
            lambda: self.timeline, self.controller.client,
            interval=secs).start()

    # ------------------------------------------------------------------
    # worker liveness (docs/fault_tolerance.md "Liveness")

    def _start_heartbeat(self):
        """Multi-process jobs beat the coordinator's ``heartbeat``
        verb from a dedicated thread (NOT the background loop — a
        wedged dispatch loop must still be seen as alive only while
        the process itself is healthy; a chaos ``hang`` wedges both,
        which is exactly what the coordinator must detect)."""
        if not self.multiproc:
            return
        secs = getattr(self.config, "heartbeat_secs", 0.0)
        if secs <= 0:
            return
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, args=(secs,),
            name="horovod_tpu-heartbeat", daemon=True)
        self._hb_thread.start()

    def _heartbeat_loop(self, interval):
        ranks = list(self._local_global_ranks())
        host = env_mod.get_str(env_mod.HOROVOD_HOSTNAME)
        alive = self._m_alive.labels(proc=str(self.controller.proc_id))
        while not self._hb_stop.is_set():
            if self.chaos is not None and self.chaos.hung:
                # simulated full-process hang: stop beating so the
                # coordinator's liveness scan declares us dead
                return
            try:
                dead = self.controller.heartbeat(ranks=ranks, host=host)
                alive.set(1)
                if dead:
                    # the coordinator already failed our peers'
                    # collectives on our behalf (a hang that woke up,
                    # a partition that healed): computing on would
                    # diverge from the job — abort into the elastic
                    # recovery path instead
                    alive.set(0)
                    self.abort(HorovodInternalError(
                        "coordinator declared this worker dead after "
                        "missed heartbeats"))
                    return
            except Exception:  # noqa: BLE001 — coordinator restart or
                # teardown; the fabric client already retried with
                # backoff, so just beat again next interval
                pass
            self._hb_stop.wait(interval)

    def _stop_heartbeat(self):
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2.0)
            self._hb_thread = None
            if not (self.chaos is not None and self.chaos.hung) \
                    and self._aborted is None:
                # clean shutdown: deregister so an elastic teardown is
                # never mistaken for a death
                try:
                    self.controller.heartbeat(bye=True)
                except Exception:  # noqa: BLE001 — coordinator gone
                    pass

    def dump_trace(self, path=None, reason="manual", dump_id=None):
        """Dump the flight-recorder ring: push it over the KV fabric
        (multi-process — feeds the launcher's ``GET /timeline``) and,
        when ``path`` or ``HOROVOD_TRACE_DUMP_DIR`` names a
        destination, write it as a stand-alone Chrome trace file.
        Returns the file path written (or None).  Called by the stall
        path automatically and by ``hvd.dump_trace()`` on demand."""
        tl = self.timeline
        if tl is None:
            return None
        events = tl.ring_dump()
        self._m_ring_dumps.labels(reason=reason).inc()
        proc = self.controller.proc_id if self.multiproc else 0
        if self.multiproc:
            from ..utils.trace_merge import TRACE_KV_PREFIX
            import json as _json
            payload = {"proc": proc, "pid": tl.pid,
                       "dump_id": dump_id, "reason": reason,
                       "round": self.controller.round_id,
                       "events": events}
            try:
                self.controller.client.put(
                    f"{TRACE_KV_PREFIX}{proc}",
                    _json.dumps(payload).encode())
            except Exception:  # noqa: BLE001 — the coordinator may be
                # gone during teardown; tracing must never kill a worker
                pass
        if path is None and getattr(self.config, "trace_dump_dir", None):
            import os as _os
            _os.makedirs(self.config.trace_dump_dir, exist_ok=True)
            path = _os.path.join(self.config.trace_dump_dir,
                                 f"hvd_flight_p{proc}.json")
        if path:
            import json as _json
            try:
                with open(path, "w") as f:
                    _json.dump(events, f)
            except OSError as exc:
                logger.warning("could not write flight-recorder dump "
                               "%s: %s", path, exc)
                return None
        return path

    # -- deprecated counter shims: the pre-telemetry attribute surface.
    #    Benchmarks and tests historically read these off the engine;
    #    new code reads telemetry snapshots (hvd.metrics()).  Each is a
    #    read-only view over the registry family that replaced it.

    @property
    def logical_wire_bytes(self):
        return int(self._m_logical.total())

    @property
    def actual_wire_bytes(self):
        return int(self._m_actual.total())

    @property
    def cross_wire_bytes(self):
        return int(self._m_cross.total())

    @property
    def algo_runs(self):
        return {k: int(v) for k, v in self._m_algo.as_dict().items()}

    @property
    def quantized_bucket_runs(self):
        return int(self._m_quantized.total())

    @property
    def fused_allgather_runs(self):
        return int(self._m_fused_ag.total())

    @property
    def sharded_update_runs(self):
        return int(self._m_sharded.total())

    def _local_global_ranks(self):
        return range(self.rank_offset, self.rank_offset + self.num_local)

    def _proc_of(self, global_rank):
        """Hosting process of a global rank: table lookup for
        heterogeneous host:slots jobs, integer division for the
        uniform layout the launcher otherwise enforces."""
        if self.ranks_of_proc:
            from bisect import bisect_right
            return bisect_right(self._proc_starts, global_rank) - 1
        return global_rank // self.num_local

    def _make_process_set_state(self, ps_id, ranks):
        ranks = sorted(ranks)
        local = [r for r in ranks
                 if self.rank_offset <= r < self.rank_offset + self.num_local]
        devices = self._devices_for(ranks)
        positions = [ranks.index(r) for r in local] \
            if len(local) < len(ranks) else None
        executor = self._MeshExecutor(devices, len(ranks),
                                      local_positions=positions)
        return ProcessSetState(ps_id, ranks, executor, local_ranks=local)

    def _devices_for(self, ranks):
        nd = len(self.devices)
        if self.multiproc:
            if self.ranks_of_proc:
                return [self._device_of_rank(r) for r in ranks]
            # one device per global rank; self.devices is the global
            # device list (jax.devices() after jax.distributed init).
            # A process can expose MORE devices than the ranks it
            # hosts (a forced multi-device host platform): rank r then
            # lives on the (r % num_local)'th device OF ITS OWN
            # process — flat indexing would cross process boundaries
            # and stage rows onto non-addressable devices.
            per = self._uniform_proc_devices()
            if per is not None:
                return [per[r // self.num_local][r % self.num_local]
                        for r in ranks]
            return [self.devices[r] for r in ranks]
        return [self.devices[r % nd] for r in ranks]

    def _uniform_proc_devices(self):
        """Per-process device groups for the uniform layout, or None
        when the global device view doesn't match one-process-per-
        num_local-ranks (then the flat table is the only contract)."""
        per = getattr(self, "_per_proc_uniform", False)
        if per is False:
            grouped = {}
            for d in self.devices:
                grouped.setdefault(getattr(d, "process_index", 0),
                                   []).append(d)
            per = [grouped[k] for k in sorted(grouped)]
            nprocs = -(-self.global_size // self.num_local)
            if len(per) != nprocs \
                    or any(len(g) < self.num_local for g in per):
                per = None
            self._per_proc_uniform = per
        return per

    def _device_of_rank(self, global_rank):
        """Heterogeneous layouts: rank r of process p uses p's
        (r - start_p)'th device — indexing the flat global list by
        rank would cross process boundaries when counts differ."""
        per = getattr(self, "_per_proc_devices", None)
        if per is None:
            grouped = {}
            for d in self.devices:
                grouped.setdefault(getattr(d, "process_index", 0),
                                   []).append(d)
            per = [grouped[k] for k in sorted(grouped)]
            if len(per) != len(self.ranks_of_proc):
                raise ValueError(
                    f"{len(per)} device-owning processes but "
                    f"{len(self.ranks_of_proc)} launcher processes")
            for p, (devs, n) in enumerate(zip(per, self.ranks_of_proc)):
                if len(devs) < n:
                    raise ValueError(
                        f"process {p} hosts {n} ranks but only "
                        f"{len(devs)} devices")
            self._per_proc_devices = per
        p = self._proc_of(global_rank)
        return per[p][global_rank - self._proc_starts[p]]

    # ------------------------------------------------------------------
    # process sets

    def add_process_set(self, ranks) -> int:
        ranks = sorted(set(int(r) for r in ranks))
        if any(r < 0 or r >= self.global_size for r in ranks):
            raise ValueError(f"process set ranks {ranks} out of range")
        with self._lock:
            for ps in self.process_sets.values():
                if ps.ranks == ranks:
                    # every rank registers the same set (SPMD pattern);
                    # re-registration returns the existing id
                    return ps.id
            ps_id = self._next_ps_id
            self._next_ps_id += 1
            self.process_sets[ps_id] = self._make_process_set_state(
                ps_id, ranks)
            return ps_id

    def remove_process_set(self, ps_id, rank=None) -> bool:
        """Deregister a process set.  Removal is a BARRIER across the
        local rank threads (reference process_set.h:89-171 removal
        barriers): it takes effect only once every local rank has
        requested it, then in-flight fully-submitted collectives on the
        set DRAIN before the set disappears — so a fast rank cannot
        kill work its peers (or it itself, via an unsynchronized async
        handle) still have outstanding.  Returns True once the set is
        gone.

        Multi-process contract (weaker than the reference's coordinated
        removal): the vote barrier spans only the LOCAL rank threads of
        this process.  With one rank per process, removal finalizes
        locally after the drain timeout without a cross-process
        rendezvous — a fast process may drop the set while a peer still
        has a collective on it mid-negotiation; that peer's collective
        then fails with ProcessSetError rather than deadlocking.
        Callers needing a strict cross-process barrier should issue
        ``barrier(process_set=ps)`` immediately before removal."""
        if ps_id == 0:
            raise ValueError("cannot remove the global process set")
        timeout = self.config.ps_removal_timeout_secs
        with self._lock:
            ps = self.process_sets.get(ps_id)
            if ps is None:
                # already removed (our vote may have been the follower's)
                return ps_id in self._removed_ps_ids
            if self.num_local > 1 and rank is not None:
                # rank-bound callers vote; an unbound (administrative)
                # caller removes immediately
                ev = self._removal_events.setdefault(
                    ps_id, threading.Event())
                voters = self._removal_votes.setdefault(ps_id, set())
                voters.add(rank)
                if len(voters) < self.num_local:
                    wait_ev = ev
                else:
                    wait_ev = None
            else:
                wait_ev = None
            if wait_ev is None:
                self._finalize_removal_locked(ps_id, ps, timeout)
                return True
        # vote recorded; wait for the remaining votes AND the drain
        # (the event is set by the finalizer, abort() and shutdown()).
        # The window covers both phases; waiters never mutate the
        # shared barrier state — only the finalizer does.
        wait_ev.wait(timeout=2 * timeout)
        with self._lock:
            removed = ps_id in self._removed_ps_ids
        if removed:
            return True
        if self._aborted is not None:
            raise HorovodInternalError(
                f"a peer rank failed during remove_process_set: "
                f"{self._aborted!r}")
        if self._shutdown:
            raise HorovodInternalError(
                "engine shut down during remove_process_set")
        raise HorovodInternalError(
            f"remove_process_set({ps_id}) timed out waiting for "
            f"peer rank threads to request removal")

    def _finalize_removal_locked(self, ps_id, ps, timeout):
        """Drain then drop the set (called with the lock held by the
        final voter / an administrative caller)."""
        # every local member rank has requested removal, so no further
        # submissions can arrive: entries whose non-JOINED local subs
        # are all present just need the background thread to execute
        # them (joined ranks contribute zeros — same rule as
        # _collect_ready_locked); entries missing live local subs can
        # never complete and are abandoned.
        deadline = time.monotonic() + timeout

        def incomplete(entry):
            return any(r not in entry.subs
                       for r in ps.local_ranks if r not in ps.joined)

        while (ps.pending or ps.awaiting) \
                and self._aborted is None and not self._shutdown:
            for table in (ps.pending, ps.awaiting):
                for key, entry in list(table.items()):
                    if incomplete(entry) or time.monotonic() > deadline:
                        table.pop(key, None)
                        self._discard_stall_mark(ps_id, key)
                        if self.multiproc:
                            self.controller.forget(key)
                        for sub in entry.subs.values():
                            sub.handle.set_error(HorovodInternalError(
                                f"process set {ps_id} removed while "
                                f"{key} pending"))
            if not (ps.pending or ps.awaiting):
                break
            self._lock.wait(timeout=0.05)   # let the engine drain
        self.process_sets.pop(ps_id, None)
        # the set's gauge children go with it — a phantom nonzero
        # queue depth for a dead set would trip alerting forever
        for fam in (self._m_pending, self._m_awaiting,
                    self._m_stalled):
            fam.remove(process_set=ps_id)
        self._removed_ps_ids.add(ps_id)
        ev = self._removal_events.pop(ps_id, None)
        self._removal_votes.pop(ps_id, None)
        if ev is not None:
            ev.set()

    def get_process_set(self, ps_id) -> ProcessSetState:
        ps = self.process_sets.get(ps_id)
        if ps is None:
            raise ValueError(f"unknown process set id {ps_id}")
        return ps

    def process_set_ranks(self, ps_id):
        return list(self.get_process_set(ps_id).ranks)

    # ------------------------------------------------------------------
    # submission (rank threads)

    # hvdlint: seam[determinism]
    def submit(self, sub: Submission) -> Handle:
        """EnqueueTensorAllreduce/... analogue (operations.cc:1408-2060):
        register the submission in the negotiation table; the background
        thread executes it once all participating ranks arrive."""
        if self.integrity is not None and sub.request.request_type in (
                RequestType.ALLREDUCE, RequestType.ADASUM):
            # submit-time payload digests (outside the lock: one
            # xor-fold pass per payload, rank threads digest in
            # parallel) — re-verified after fusion packing so grad
            # corruption is attributed to the submitting rank
            sub.payload_fp = [integrity_mod.digest64([p])
                              for p in sub.payloads]
        with self._lock:
            if self._shutdown:
                raise HorovodInitError("horovod_tpu has been shut down")
            if self._aborted is not None:
                sub.handle.set_error(HorovodInternalError(
                    f"horovod_tpu aborted: {self._aborted!r}"))
                return sub.handle
            ps = self.get_process_set(sub.request.process_set_id)
            if sub.rank not in ps.index:
                raise ValueError(
                    f"rank {sub.rank} is not part of process set {ps.id}")
            if sub.rank not in ps.local_ranks:
                raise ValueError(
                    f"rank {sub.rank} is not hosted by this process")
            key = self._negotiation_key(ps, sub)
            entry = ps.pending.get(key)
            if entry is None and key in ps.awaiting:
                sub.handle.set_error(DuplicateNameError(
                    f"tensor {sub.names} resubmitted while a prior "
                    f"submission is still executing"))
                return sub.handle
            if entry is None:
                entry = NegotiationEntry(key)
                entry.wire_default = self.config.wire_dtype
                entry.wire_inner_default = getattr(
                    self.config, "wire_inner", None)
                entry.algo_default = getattr(
                    self.config, "algorithm", None)
                entry.pp_default = getattr(
                    self.config, "pp_sched_tag", None)
                ps.pending[key] = entry
            req = sub.request
            if (req.wire_dtype is None and entry.wire_default
                    and req.request_type in (RequestType.ALLREDUCE,
                                             RequestType.REDUCESCATTER)
                    and req.reduce_op in (ReduceOp.SUM,
                                          ReduceOp.AVERAGE)):
                # resolve the (entry-latched) process-wide default INTO
                # the request before negotiation: every local rank of
                # this negotiation sees one default even if autotune
                # flips config.wire_dtype mid-submit, while processes
                # whose configs genuinely diverge (env drift) fail the
                # cross-rank wire check loudly instead of executing
                # different collective programs against each other
                req.wire_dtype = entry.wire_default
            if (req.wire_dtype is None and entry.wire_default
                    and req.request_type == RequestType.ALLTOALL):
                # alltoall has no reduce_op, so it gets its own latch
                # branch: the exchange moves raw payloads (no
                # accumulation to commute with), so ANY float payload
                # may ride the process-wide wire default — the MoE
                # dispatch/combine wire follows the reduction wire
                # without per-call plumbing
                req.wire_dtype = entry.wire_default
            if (req.wire_inner is None and entry.wire_inner_default
                    and req.request_type in (RequestType.ALLREDUCE,
                                             RequestType.ALLTOALL)
                    and (req.request_type == RequestType.ALLTOALL
                         or req.reduce_op in (ReduceOp.SUM,
                                              ReduceOp.AVERAGE))):
                # same latch for the inner-hop wire: the per-hop pair
                # is tuned as ONE categorical (core/autotune.py), so
                # both halves resolve at the same instant
                req.wire_inner = entry.wire_inner_default
            if (req.algorithm is None and entry.algo_default
                    and req.request_type == RequestType.ALLREDUCE
                    and req.reduce_op in (ReduceOp.SUM,
                                          ReduceOp.AVERAGE)):
                # same latch for the reduction algorithm (autotune's
                # sixth dimension): one negotiation, one algorithm
                req.algorithm = entry.algo_default
            if (req.pp_sched is None and entry.pp_default
                    and req.request_type == RequestType.ALLREDUCE):
                # same latch for the pipeline-schedule tag (autotune's
                # SEVENTH dimension): the runtime's bubble-overlapped
                # gradient reduces all carry the step's latched
                # schedule@n_micro even if autotune flips the config
                # default mid-step
                req.pp_sched = entry.pp_default
            if sub.rank in entry.subs:
                sub.handle.set_error(DuplicateNameError(
                    f"tensor {sub.names} submitted twice by rank "
                    f"{sub.rank} before completion"))
                return sub.handle
            entry.subs[sub.rank] = sub
            if self.timeline is not None:
                self.timeline.negotiate_start(sub.names[0],
                                              sub.request.request_type.name)
            self._lock.notify_all()
        return sub.handle

    def join(self, rank, ps_id=0) -> Handle:
        """Join op (operations.cc:1991-2024): the rank stops submitting;
        pending/future allreduces treat it as a zero contributor.  The
        handle completes when every rank of the set has joined, with
        result = the last rank to join (message.h last_joined_rank)."""
        handle = Handle()
        with self._lock:
            if self._shutdown:
                raise HorovodInitError("horovod_tpu has been shut down")
            if self._aborted is not None:
                handle.set_error(HorovodInternalError(
                    f"horovod_tpu aborted: {self._aborted!r}"))
                return handle
            ps = self.get_process_set(ps_id)
            if rank in ps.joined:
                handle.set_error(HorovodInternalError(
                    f"rank {rank} already joined"))
                return handle
            ps.joined.add(rank)
            ps.last_joined = rank
            ps.join_waiters[rank] = handle
            self._lock.notify_all()
        if self.multiproc:
            if self._bypass is not None:
                # a joined rank stops submitting: the cached list can
                # never be fully ready again — make the next agreement
                # round fall back promptly instead of waiting it out
                self._bypass.poison("join")
            self.controller.report_join(
                ps_id, rank, len(ps.ranks),
                proc_members=len(ps.local_ranks))
        return handle

    def _negotiation_key(self, ps, sub: Submission):
        return (f"{sub.request.request_type.name}"
                f"|{'/'.join(sub.names)}|ps{ps.id}")

    def hold_cycles(self):
        """Context manager parking the negotiation loop: entries
        submitted inside the ``with`` accumulate and dispatch together
        in ONE cycle on exit.  Deterministic fusion-bucket formation —
        the timing-independent way to exercise/observe the fusion
        paths (tests, timeline experiments).  Re-entrant."""
        import contextlib

        @contextlib.contextmanager
        def _hold():
            with self._lock:
                self._hold_depth += 1
            try:
                yield self
            finally:
                with self._lock:
                    self._hold_depth = max(0, self._hold_depth - 1)
                    self._lock.notify_all()
        return _hold()

    # ------------------------------------------------------------------
    # background loop

    def _background_loop(self):
        while True:
            # re-read each iteration: the autotuner adjusts cycle time
            cycle = max(self.config.cycle_time_ms, 0.05) / 1000.0
            with self._lock:
                if not self._shutdown:
                    self._lock.wait(timeout=cycle)
                if self._shutdown:
                    self._fail_all_pending_locked(
                        HorovodInitError("shutdown during pending collective"))
                    break
                if self._hold_depth:
                    # hold_cycles(): park so concurrent submissions
                    # accumulate and dispatch in ONE cycle on release
                    continue
                cycle_t0 = time.monotonic()
                work = self._collect_ready_locked()
                self._check_stalls_locked()
                self._observe_queues_locked()
            if self.timeline is not None and work:
                # reference timeline.cc MarkCycleStart: one instant
                # marker per negotiation cycle that produced work
                # (HOROVOD_TIMELINE_MARK_CYCLES)
                self.timeline.mark_cycle()
            if self._pending_trace_dump is not None:
                # local stall inspector requested a flight-recorder
                # dump; it runs here, outside the lock (KV put / file
                # IO must not block submitters)
                reason, self._pending_trace_dump = \
                    self._pending_trace_dump, None
                self.dump_trace(reason=reason)
            if self.multiproc:
                if self._bypass is not None and self._bypass.active:
                    # armed fast path: agree via the collective-path
                    # bitvector and execute the cached response list —
                    # zero coordinator traffic (and the reason steps
                    # keep flowing while the coordinator is down)
                    self._bypass_cycle()
                else:
                    self._store_cycle(work)
            else:
                for ps, batch in work:
                    if self.chaos is not None:
                        # single-process twin of the store-cycle hook:
                        # slow-rank faults delay dispatch here
                        self.chaos.on_collectives(len(batch))
                    self._execute_batch(ps, batch)
            if work:
                # idle cycles are just the wait timeout expiring; only
                # cycles that produced work say anything about dispatch
                self._m_cycles.inc()
                self._m_cycle.observe(time.monotonic() - cycle_t0)
        self._shutdown_done.set()

    def _observe_queues_locked(self):
        """Queue-depth gauges per process set, mirrored as Chrome
        counter ("C") events on the timeline so traces and metrics
        tell one story (docs/timeline.md)."""
        pending = awaiting = 0
        for ps in self.process_sets.values():
            self._m_pending.labels(process_set=ps.id).set(
                len(ps.pending))
            self._m_awaiting.labels(process_set=ps.id).set(
                len(ps.awaiting))
            pending += len(ps.pending)
            awaiting += len(ps.awaiting)
        tl = self.timeline
        if tl is not None and (pending or awaiting
                               or self._tl_queues_nonzero):
            self._tl_queues_nonzero = bool(pending or awaiting)
            tl.counter("queue_depth", {"pending": pending,
                                       "awaiting": awaiting})
            tl.counter("wire_bytes", {
                "logical": self.logical_wire_bytes,
                "actual": self.actual_wire_bytes,
                "cross": self.cross_wire_bytes})

    def _collect_ready_locked(self):
        """ComputeResponseList analogue: pull locally-ready negotiation
        entries (readiness = submissions from every non-joined LOCAL
        rank of the set, controller.cc:269-327 for the joined case) and
        resolve single-process join barriers."""
        work = []
        for ps in list(self.process_sets.values()):
            if not self.multiproc and ps.joined and \
                    len(ps.joined) == ps.size \
                    and not ps.pending and not ps.awaiting:
                # resolve the join barrier only once pending collectives
                # have drained: clearing ps.joined earlier would strand
                # entries submitted before the join (their readiness
                # test would suddenly require the joined ranks again)
                for r, h in ps.join_waiters.items():
                    h.set_result(ps.last_joined)
                ps.join_waiters.clear()
                ps.joined.clear()
                ps.last_joined = -1
            ready = []
            for key in list(ps.pending.keys()):
                entry = ps.pending[key]
                # ready when every non-joined local rank has submitted;
                # if all submitters have since joined, the entry still
                # executes with their pre-join data (entries always
                # hold >= 1 submission)
                needed = [r for r in ps.local_ranks if r not in ps.joined]
                if all(r in entry.subs for r in needed):
                    ready.append(entry)
                    del ps.pending[key]
                    if self.multiproc:
                        ps.awaiting[key] = entry
                    if self.timeline is not None:
                        # flow-event anchor: the instant this process
                        # became ready (the straggler's lands last)
                        entry.ready_ts = self.timeline._ts()
                    self._discard_stall_mark(ps.id, key)
                    self._m_negotiation.labels(
                        op=key.split("|", 1)[0]).observe(
                            time.monotonic() - entry.first_time)
            if ready:
                work.append((ps, ready))
        return work

    def _stall_ranks_label(self, ranks):
        """Bounded label value naming the attributed ranks: the first
        eight rank ids verbatim (+count of the rest), folding into
        ``other`` once the family holds 64 distinct children.  Keeps
        the exported labels naming ranks (the log line always carries
        the full list) without the unbounded-cardinality anti-pattern
        a flapping large job would otherwise mint."""
        label = ",".join(str(r) for r in ranks[:8])
        if len(ranks) > 8:
            label += f",+{len(ranks) - 8}"
        seen = self._m_stall_warn.as_dict()
        if label not in seen and len(seen) >= 64:
            return "other"
        return label

    def _discard_stall_mark(self, ps_id, key):
        """Drop the once-per-stall warning mark for a tensor.  MUST be
        called from every path that removes an entry from pending OR
        awaiting — ready collection, coordinator batch/error responses,
        stall shutdown, validation failure, abort — or a re-used tensor
        name that stalls again warns only once per process lifetime."""
        self._stall_warned.discard((ps_id, key))

    def _check_stalls_locked(self):
        """Stall inspector (reference stall_inspector.{h,cc}): warn when
        a tensor is ready on some-but-not-all ranks past the warning
        time; error everyone past the shutdown time.

        Attribution is GLOBAL in multi-process jobs: the coordinator
        aggregates which processes never reported a stalled tensor and
        names the missing global ranks in a ``stall`` response
        (runner/http/http_server.py _scan_stalls → _apply_response),
        exactly the reference's coordinator-side
        ``StallInspector::CheckForStalledTensors``.  The local check
        here covers what only this process can see — ranks IT hosts
        that never submitted — and falls back for the awaiting table
        only after 2x the warning time, so the coordinator's
        rank-attributed warning lands first when it is alive."""
        if self.config.stall_check_disable:
            return
        now = time.monotonic()
        stalled = {}
        for ps in self.process_sets.values():
            tables = [("pending", ps.pending), ("awaiting", ps.awaiting)]
            for where, table in tables:
                for key, entry in list(table.items()):
                    age = now - entry.first_time
                    wkey = (ps.id, key)
                    if age > self.config.stall_warning_secs:
                        stalled[ps.id] = stalled.get(ps.id, 0) + 1
                    warn_after = self.config.stall_warning_secs
                    if where == "awaiting":
                        warn_after *= 2
                    if (age > warn_after
                            and wkey not in self._stall_warned):
                        if where == "pending":
                            # ps.local_ranks hold GLOBAL rank ids; this
                            # process can attribute its own ranks
                            missing = [r for r in ps.local_ranks
                                       if r not in entry.subs
                                       and r not in ps.joined]
                            logger.warning(
                                "One or more tensors were submitted to "
                                "be reduced by some ranks but not all: "
                                "%s stalled for %.0fs (missing ranks: "
                                "%s, hosted by this process)",
                                key, age, missing)
                            self._m_stall_warn.labels(
                                ranks=self._stall_ranks_label(
                                    missing)).inc()
                        else:
                            logger.warning(
                                "Tensor %s reported ready %.0fs ago but "
                                "the coordinator has not scheduled it "
                                "(peer process missing or stalled; no "
                                "coordinator stall report received)",
                                key, age)
                            self._m_stall_warn.labels(ranks="").inc()
                        self._stall_warned.add(wkey)
                        # ship the warning with the trace that explains
                        # it (multi-process stalls normally dump via
                        # the coordinator's trace_dump broadcast; this
                        # covers local-only and coordinator-dead cases)
                        self._pending_trace_dump = "stall"
                    if (self.config.stall_shutdown_secs > 0
                            and age > self.config.stall_shutdown_secs):
                        del table[key]
                        self._discard_stall_mark(ps.id, key)
                        if where == "awaiting" and self.multiproc:
                            # no coordinator response will ever name
                            # this key for us: un-mark it as reported
                            # so a resubmission negotiates again
                            self.controller.forget(key)
                        for sub in entry.subs.values():
                            sub.handle.set_error(StalledTensorError(
                                f"tensor {key} stalled for {age:.0f}s"))
        for ps in self.process_sets.values():
            self._m_stalled.labels(process_set=ps.id).set(
                stalled.get(ps.id, 0))

    def _fail_all_pending_locked(self, exc):
        self._stall_warned.clear()
        for ps in self.process_sets.values():
            for entry in list(ps.pending.values()) + \
                    list(ps.awaiting.values()):
                for sub in entry.subs.values():
                    sub.handle.set_error(exc)
            if self.multiproc:
                for key in ps.awaiting:
                    self.controller.forget(key)
            ps.pending.clear()
            ps.awaiting.clear()
            for h in ps.join_waiters.values():
                h.set_error(exc)
            ps.join_waiters.clear()

    # ------------------------------------------------------------------
    # store-controller (multi-process) cycle

    # hvdlint: seam[determinism]
    def _meta_for(self, ps, entry):
        """Negotiation metadata sent to the coordinator — the Request
        wire message (reference message.h:59-143 via FlatBuffers)."""
        first = next(iter(entry.subs.values()))
        req = first.request
        nbytes = sum(int(p.nbytes) for p in first.payloads)
        nprocs = len({self._proc_of(r) for r in ps.ranks})
        members = getattr(ps, "_members_by_proc", None)
        if members is None:
            # per-process member ranks: the coordinator's stall
            # inspector maps a non-reporting process back to the
            # GLOBAL ranks it hosts (reference stall_inspector.cc
            # names ranks, not hosts).  Static per set — cached.
            members = {}
            for r in ps.ranks:
                members.setdefault(str(self._proc_of(r)), []).append(r)
            ps._members_by_proc = members
        meta = {
            "key": entry.key,
            "type": req.request_type.name,
            "dtype": req.dtype,
            "shape": list(req.shape),
            "op": int(req.reduce_op),
            "pre": req.prescale_factor,
            "post": req.postscale_factor,
            "wire": req.wire_dtype,
            "wi": req.wire_inner,
            "algo": req.algorithm,
            "pp": req.pp_sched,
            "sfp": req.shard_fp,
            "ps": ps.id,
            "nbytes": nbytes,
            "nprocs": nprocs,
            "nranks": ps.size,
            "root": req.root_rank,
            "members": members,
            "aux": {},
        }
        if req.group_shapes is not None:
            meta["gshapes"] = [list(s) for s in req.group_shapes]
        if req.request_type == RequestType.ALLGATHER:
            # per-local-rank first dims, ordered by global rank; the
            # coordinator merges them into the global dim0 table (the
            # reference's allgather shape exchange)
            meta["aux"]["dim0s"] = [
                [int(entry.subs[r].payloads[i].shape[0])
                 if entry.subs[r].payloads[i].ndim else 1
                 for i in range(len(first.payloads))]
                for r in ps.local_ranks if r in entry.subs
            ]
        if req.request_type == RequestType.ALLTOALL:
            meta["aux"]["splits"] = [
                list(entry.subs[r].request.splits)
                for r in ps.local_ranks if r in entry.subs
            ]
        return meta

    def _store_cycle(self, work):
        """Report locally-ready entries; execute coordinator responses
        in log order."""
        metas = []
        for ps, batch in work:
            for entry in batch:
                err = self._validate(ps, entry, local_only=True)
                if err is not None:
                    with self._lock:
                        ps.awaiting.pop(entry.key, None)
                        self._discard_stall_mark(ps.id, entry.key)
                    for sub in entry.subs.values():
                        sub.handle.set_error(err)
                    # tell the coordinator so peer processes holding
                    # this tensor fail instead of waiting forever
                    meta = self._meta_for(ps, entry)
                    meta["error"] = str(err)
                    metas.append(meta)
                    continue
                metas.append(self._meta_for(ps, entry))
        if self.chaos is not None and metas:
            # chaos slow_rank injection point: sleeping HERE — after
            # the entries went locally ready, before report_ready —
            # makes this process the straggler the coordinator's
            # global stall attribution names and the stall-triggered
            # flight recorder captures (docs/fault_tolerance.md)
            self.chaos.on_collectives(len(metas))
        try:
            if metas:
                self.controller.report_ready(metas)
            responses = self.controller.poll(wait=0.2)
        except Exception as exc:  # noqa: BLE001 — coordinator death
            self.abort(exc)
            return
        tuned = self.controller.tuned
        if tuned:
            # coordinator-side autotune broadcast (reference
            # SynchronizeParameters, controller.cc:40-54)
            if "cycle_time_ms" in tuned:
                self.config.cycle_time_ms = tuned["cycle_time_ms"]
            if "pack_mt_threshold_bytes" in tuned:
                self.config.pack_mt_threshold_bytes = \
                    tuned["pack_mt_threshold_bytes"]
        for resp in responses:
            self._apply_response(resp)
        if self._bypass is not None and not self._bypass.active:
            self._bypass_track(responses)
        if self.controller.take_rereport():
            # the epoch resync drained the restarted coordinator's
            # replayed log; whatever is STILL awaiting was lost with
            # the old coordinator's pending table — re-report it
            self._rereport_awaiting()

    # ------------------------------------------------------------------
    # steady-state negotiation bypass (core/bypass.py)

    def _bypass_track(self, responses):
        """Un-armed detection: feed applied responses to the tracker;
        when the awaiting tables drain, the cycle closes — a list
        stable for K cycles votes its fingerprint to the coordinator
        (idempotent; re-voted each stable cycle until the arm record
        arrives in the log)."""
        bp = self._bypass
        for resp in responses:
            bp.observe_response(resp)
        with self._lock:
            drained = all(not ps.awaiting
                          for ps in self.process_sets.values())
        if not drained:
            return
        fp = bp.cycle_complete()
        if fp is not None:
            try:
                self.controller.bypass_ready(fp)
            except Exception:  # noqa: BLE001 — advisory: the vote is
                # re-sent next stable cycle; a dead coordinator here
                # just delays arming
                pass

    def _bypass_cycle(self):
        """One armed cycle: wait for the cached tensors, agree via a
        1-element MIN allreduce over the existing collective path
        (vote 1 = my locally-ready entries match my cached list), and
        on unanimity execute the cached response list with no
        coordinator traffic.  ANY dissent is unanimous too (same
        collective result everywhere), so all procs fall back into
        full negotiation together."""
        from .bypass import meta_fingerprint
        bp = self._bypass
        with self._lock:
            ps0 = self.process_sets.get(0)
            foreign = any(ps.id != 0 and ps.awaiting
                          for ps in self.process_sets.values())
            awaiting_fps = {}
            if ps0 is not None:
                for key, entry in ps0.awaiting.items():
                    if entry.meta_fp is None:
                        # invariant once awaiting — computed once, not
                        # per engine tick
                        entry.meta_fp = meta_fingerprint(
                            self._meta_for(ps0, entry))
                    awaiting_fps[key] = entry.meta_fp
        if ps0 is None:
            return
        decision = bp.decide(awaiting_fps, foreign)
        if decision is None:
            return
        vote, reason = decision
        if self.chaos is not None and vote == 1:
            # after_collectives triggers must keep counting (and
            # slow_rank must keep making a visible straggler) while
            # armed — the bypass replaces report_ready, so the hook
            # fires here, right before the agreement vote (fallback
            # cycles count via _rereport_awaiting's report instead)
            self.chaos.on_collectives(len(awaiting_fps))
        try:
            agreed = self._bypass_vote(ps0, vote)
        except Exception as exc:  # noqa: BLE001 — a failed agreement
            # collective means a dead/diverged peer: same contract as
            # any peer failure
            self.abort(exc)
            return
        if agreed:
            t0 = time.monotonic()
            bp.cycles += 1
            for i, resp in enumerate(bp.responses):
                self._apply_response(self._bypass_response(resp, i))
            self._m_bypass.labels(outcome="hit").inc()
            self._m_bypass_cycle.observe(time.monotonic() - t0)
        else:
            self._m_bypass.labels(outcome="fallback").inc()
            logger.info(
                "negotiation bypass disengaged (%s); falling back to "
                "full negotiation", reason or "peer mismatch")
            bp.disarm()
            # marks from the pre-arm race window would swallow the
            # re-report of re-used tensor names (the coordinator
            # dropped those entries when it armed)
            self.controller.clear_reported()
            self._rereport_awaiting()

    def _bypass_vote(self, ps0, vote):
        """The all-to-all bitvector exchange (reference
        response_cache CoordinateCacheAndState, collapsed to one MIN
        bit): every rank contributes 1 iff its process's state matches
        its cached list, so the reduced value is 1 only on global
        agreement — and identical on every rank, which is what makes
        the fallback coordinated."""
        rows = [np.full(1, float(vote), np.float32)
                for _ in ps0.local_ranks]
        out = ps0.executor.allreduce(rows, ReduceOp.MIN)
        return bool(out[0][0] >= 0.5)

    def _bypass_response(self, resp, idx):
        """Cached batch response for one bypass execution: fresh
        DETERMINISTIC trace ids (every proc executes the same
        responses in the same order, so the cumulative sequence is
        identical everywhere and cross-rank flow arrows keep
        working), disjoint from the coordinator-minted id space."""
        r = dict(resp)
        bp = self._bypass
        ids = {}
        for k in resp["keys"]:
            bp.trace_seq += 1
            ids[k] = (1 << 40) + bp.trace_seq
        r["trace"] = ids
        return r

    def _rereport_awaiting(self):
        """Re-report every entry still awaiting a coordinator response
        — the recovery shared by the bypass fallback (entries were
        never reported while armed) and the post-restart resync (the
        old coordinator's pending table died with it).  Local
        validation runs here because bypass-mode entries skipped the
        _store_cycle validation pass."""
        with self._lock:
            items = [(ps, key, entry)
                     for ps in self.process_sets.values()
                     for key, entry in list(ps.awaiting.items())]
        metas = []
        for ps, key, entry in items:
            err = self._validate(ps, entry, local_only=True)
            if err is not None:
                with self._lock:
                    ps.awaiting.pop(key, None)
                    self._discard_stall_mark(ps.id, key)
                for sub in entry.subs.values():
                    sub.handle.set_error(err)
                meta = self._meta_for(ps, entry)
                meta["error"] = str(err)
                metas.append(meta)
            else:
                metas.append(self._meta_for(ps, entry))
        if not metas:
            return
        if self.chaos is not None:
            # this IS a ready report: the chaos collectives counter
            # (and slow_rank's pre-report sleep) must see it, exactly
            # like _store_cycle's hook
            self.chaos.on_collectives(len(metas))
        try:
            self.controller.report_ready(metas)
        except Exception as exc:  # noqa: BLE001 — coordinator death
            self.abort(exc)

    def _apply_response(self, resp):
        kind = resp.get("kind")
        if kind == "batch":
            keys = resp["keys"]
            aux = resp.get("aux", {})
            metas = resp.get("metas", {})
            trace_ids = resp.get("trace", {})
            ps = self._ps_for_response(keys, metas)
            if ps is None or not ps.local_ranks:
                # this process hosts no members of the set: the
                # sub-mesh excludes our devices — do not participate
                return
            entries = []
            bad_key = None
            with self._lock:
                popped = {}
                for k in keys:
                    e = ps.awaiting.pop(k, None)
                    if e is not None:
                        popped[k] = e
                        self._discard_stall_mark(ps.id, k)
                for k in keys:
                    e = popped.get(k)
                    if e is None:
                        # our ranks joined before this entry: we must
                        # still run the SPMD program with zero inputs
                        # (the reference Join zero-tensor trick made
                        # compiled: all mesh devices participate)
                        e = self._synthetic_entry(k, metas.get(k))
                    if e is None:
                        bad_key = k
                        break
                    tid = trace_ids.get(k)
                    if tid is not None:
                        # the coordinator-minted job-unique trace id:
                        # every process stamps the same id on this
                        # entry's flow events
                        e.trace_id = tid
                    entries.append(e)
            if bad_key is not None:
                # protocol violation: we cannot participate in this
                # SPMD program — peers would deadlock, so fail loudly
                # everywhere (reference SHUT_DOWN_ERROR, common.h:231)
                err = HorovodInternalError(
                    f"coordinator response for unknown tensor "
                    f"{bad_key}; aborting to avoid a hang")
                for pe in popped.values():
                    for sub in pe.subs.values():
                        sub.handle.set_error(err)
                self.abort(err)
                return
            try:
                self._run_bucket(ps, entries, aux=aux)
            except Exception as exc:  # noqa: BLE001 — deliver to waiters
                logger.exception("collective execution failed")
                wrapped = exc if isinstance(exc, HorovodInternalError) \
                    else HorovodInternalError(str(exc))
                for e in entries:
                    for sub in e.subs.values():
                        sub.handle.set_error(wrapped)
        elif kind == "error":
            with self._lock:
                for cand in self.process_sets.values():
                    e = cand.awaiting.pop(resp["key"], None)
                    if e is not None:
                        self._discard_stall_mark(cand.id, resp["key"])
                        for sub in e.subs.values():
                            sub.handle.set_error(TensorShapeMismatchError(
                                resp.get("message", "negotiation error")))
                        break
        elif kind == "stall":
            # coordinator-side stall attribution (reference
            # stall_inspector.cc CheckForStalledTensors relocated into
            # the launcher's coordinator): the warning names the
            # missing GLOBAL ranks, aggregated across processes —
            # today's local view can only name ranks this process
            # hosts.  The mark doubles as dedup against the local
            # fallback in _check_stalls_locked.
            key = resp.get("key")
            ps_id = resp.get("ps", 0)
            missing = resp.get("missing_ranks") or []
            with self._lock:
                wkey = (ps_id, key)
                fresh = wkey not in self._stall_warned
                if fresh:
                    self._stall_warned.add(wkey)
            if fresh:
                # ranks = every global rank a non-reporting process
                # hosts (the coordinator's attribution granularity is
                # the process; that process's own local inspector
                # narrows to the exact rank it is missing)
                logger.warning(
                    "One or more tensors were submitted to be reduced "
                    "by some ranks but not all: %s stalled for %ss "
                    "(missing global ranks: %s, hosted by "
                    "non-reporting processes %s)",
                    key, resp.get("age", "?"),
                    missing if missing else "unknown",
                    resp.get("missing_procs", []))
                self._m_stall_warn.labels(
                    ranks=self._stall_ranks_label(missing)).inc()
        elif kind == "dead":
            # coordinator liveness verdict: a peer process missed its
            # heartbeats.  A dead peer dooms every collective it
            # belongs to, so treat it exactly like an observed peer
            # failure: abort — every pending AND future handle fails
            # NOW with an error naming the dead global ranks (fast
            # explicit failure instead of stall-timeout limbo), and
            # elastic workers take the exec-restart recovery path a
            # peer death requires (docs/fault_tolerance.md).  The
            # coordinator's per-key error responses, applied above in
            # log order, already failed the entries it knew about.
            msg = resp.get("message") or (
                f"worker process {resp.get('proc')} hosting global "
                f"ranks {resp.get('ranks') or []} declared dead "
                f"after missed heartbeats")
            logger.warning("%s; failing pending collectives", msg)
            self.abort(HorovodInternalError(msg))
        elif kind == "bypass_arm":
            # the coordinated switch point: every proc consumes this
            # record at the same position in its response stream and
            # arms the steady-state bypass (core/bypass.py)
            if self._bypass is not None:
                self._bypass.on_arm(resp.get("fp"))
        elif kind == "trace_dump":
            # coordinator-requested flight-recorder dump (stall
            # auto-dump, POST /trace/dump, GET /timeline): push the
            # ring so the launcher can serve the merged job trace
            self.dump_trace(reason=resp.get("reason", "request"),
                            dump_id=resp.get("id"))
        elif kind == "join_done":
            with self._lock:
                ps = self.process_sets.get(resp.get("ps", 0))
                if ps is not None:
                    for r, h in ps.join_waiters.items():
                        h.set_result(resp.get("last", -1))
                    ps.join_waiters.clear()
                    ps.joined.clear()
                    ps.last_joined = -1

    def _ps_for_response(self, keys, metas):
        for k in keys:
            m = metas.get(k)
            if m is not None:
                return self.process_sets.get(m.get("ps", 0))
            with self._lock:
                for cand in self.process_sets.values():
                    if k in cand.awaiting:
                        return cand
        return None

    def _synthetic_entry(self, key, meta):
        """Zero-contribution entry for a bucket our joined ranks did
        not submit to (allreduce only — other ops reject join)."""
        if meta is None or meta["type"] not in ("ALLREDUCE", "ADASUM"):
            return None
        req = Request(
            request_type=RequestType[meta["type"]], tensor_name=key,
            rank=-1, dtype=meta["dtype"], shape=tuple(meta["shape"]),
            reduce_op=ReduceOp(meta["op"]),
            prescale_factor=meta["pre"], postscale_factor=meta["post"],
            process_set_id=meta["ps"], wire_dtype=meta.get("wire"),
            wire_inner=meta.get("wi"), algorithm=meta.get("algo"))
        dtype = np.dtype(meta["dtype"]) if meta["dtype"] != "bfloat16" \
            else _bfloat16_dtype()
        sub = Submission(rank=-1, request=req, names=[key],
                         payloads=[np.zeros(tuple(meta["shape"]),
                                            dtype=dtype)],
                         handle=Handle())
        entry = NegotiationEntry(key)
        entry.subs[-1] = sub
        return entry

    # ------------------------------------------------------------------
    # validation + fusion + execution (background thread)

    def _execute_batch(self, ps: ProcessSetState, entries):
        """PerformOperation analogue (operations.cc:277-334): validate,
        fuse allreduce entries into buckets, run each response."""
        runnable = []
        for entry in entries:
            err = self._validate(ps, entry)
            if err is not None:
                for sub in entry.subs.values():
                    sub.handle.set_error(err)
                continue
            if entry.trace_id is None:
                # local mode has no coordinator to mint trace ids;
                # engine-minted ones (rank-offset-disjoint) keep the
                # flow events working single-process too
                self._next_trace_id += 1
                entry.trace_id = self._next_trace_id
            runnable.append(entry)

        buckets = self._fuse(ps, runnable)
        for bucket in buckets:
            try:
                self._run_bucket(ps, bucket)
            except Exception as exc:  # noqa: BLE001 — deliver to waiters
                logger.exception("collective execution failed")
                wrapped = exc if isinstance(exc, HorovodInternalError) \
                    else HorovodInternalError(str(exc))
                for entry in bucket:
                    for sub in entry.subs.values():
                        sub.handle.set_error(wrapped)

    def _validate(self, ps, entry, local_only=False) -> Optional[Exception]:
        """Cross-rank consistency checks, mirroring ConstructResponse
        (controller.cc:496-843): dtype, shape, op, scale factors and
        root must agree across ranks.  In multi-process mode this
        covers the local ranks; the coordinator re-validates across
        processes."""
        subs = [entry.subs[r] for r in ps.ranks if r in entry.subs]
        first = subs[0].request
        rt = first.request_type
        for sub in subs[1:]:
            r = sub.request
            if r.dtype != first.dtype:
                return TensorShapeMismatchError(
                    f"Mismatched data types for {first.tensor_name}: rank "
                    f"{sub.rank} sent {r.dtype}, rank {subs[0].rank} sent "
                    f"{first.dtype}")
            if r.reduce_op != first.reduce_op:
                return TensorShapeMismatchError(
                    f"Mismatched reduce ops for {first.tensor_name}")
            if (r.prescale_factor != first.prescale_factor
                    or r.postscale_factor != first.postscale_factor):
                return TensorShapeMismatchError(
                    f"Mismatched prescale/postscale for {first.tensor_name}")
            if r.wire_dtype != first.wire_dtype:
                return TensorShapeMismatchError(
                    f"Mismatched wire dtypes for {first.tensor_name}: "
                    f"rank {sub.rank} sent {r.wire_dtype}, rank "
                    f"{subs[0].rank} sent {first.wire_dtype}")
            if r.wire_inner != first.wire_inner:
                return TensorShapeMismatchError(
                    f"Mismatched inner wire dtypes for "
                    f"{first.tensor_name}: rank {sub.rank} sent "
                    f"{r.wire_inner}, rank {subs[0].rank} sent "
                    f"{first.wire_inner}")
            if r.algorithm != first.algorithm:
                return TensorShapeMismatchError(
                    f"Mismatched algorithms for {first.tensor_name}: "
                    f"rank {sub.rank} sent {r.algorithm}, rank "
                    f"{subs[0].rank} sent {first.algorithm}")
            if r.pp_sched != first.pp_sched:
                return TensorShapeMismatchError(
                    f"Mismatched pipeline schedules for "
                    f"{first.tensor_name}: rank {sub.rank} sent "
                    f"{r.pp_sched}, rank {subs[0].rank} sent "
                    f"{first.pp_sched}")
            if r.shard_fp != first.shard_fp:
                # sharded weight update (core/sharded.py): ranks whose
                # shard LAYOUTS disagree would scatter/gather different
                # slices against each other — corrupt updates, not a
                # crash — so the layout fingerprint fails loudly like
                # the wire pair and algorithm
                return TensorShapeMismatchError(
                    f"Mismatched shard layouts for "
                    f"{first.tensor_name}: rank {sub.rank} sent "
                    f"{r.shard_fp}, rank {subs[0].rank} sent "
                    f"{first.shard_fp}")
            if rt == RequestType.BROADCAST and r.root_rank != first.root_rank:
                return TensorShapeMismatchError(
                    f"Mismatched broadcast root for {first.tensor_name}: "
                    f"{r.root_rank} vs {first.root_rank}")
            if rt in (RequestType.ALLREDUCE, RequestType.ADASUM,
                      RequestType.BROADCAST, RequestType.REDUCESCATTER):
                if r.shape != first.shape:
                    return TensorShapeMismatchError(
                        f"Mismatched shapes for {first.tensor_name}: rank "
                        f"{sub.rank} sent {r.shape}, rank {subs[0].rank} "
                        f"sent {first.shape}")
                if r.group_shapes != first.group_shapes:
                    return TensorShapeMismatchError(
                        f"Mismatched group member shapes for "
                        f"{first.tensor_name}: rank {sub.rank} sent "
                        f"{r.group_shapes}, rank {subs[0].rank} sent "
                        f"{first.group_shapes}")
            elif rt in (RequestType.ALLGATHER, RequestType.ALLTOALL):
                if tuple(r.shape[1:]) != tuple(first.shape[1:]):
                    return TensorShapeMismatchError(
                        f"Mismatched non-first dimensions for "
                        f"{first.tensor_name}")
                gs_a = r.group_shapes or ()
                gs_b = first.group_shapes or ()
                if len(gs_a) != len(gs_b) or any(
                        tuple(a[1:]) != tuple(b[1:])
                        for a, b in zip(gs_a, gs_b)):
                    return TensorShapeMismatchError(
                        f"Mismatched group member non-first dimensions "
                        f"for {first.tensor_name}")
            if rt == RequestType.ALLTOALL:
                if r.splits is None or len(r.splits) != ps.size:
                    return TensorShapeMismatchError(
                        f"alltoall splits for {first.tensor_name} must "
                        f"have one entry per rank of the process set")
                if sum(r.splits) != (r.shape[0] if r.shape else 0):
                    return TensorShapeMismatchError(
                        f"alltoall splits for {first.tensor_name} must sum "
                        f"to the first dimension")
        if rt == RequestType.ALLTOALL:
            r0 = first
            if r0.splits is None or len(r0.splits) != ps.size or \
                    sum(r0.splits) != (r0.shape[0] if r0.shape else 0):
                return TensorShapeMismatchError(
                    f"alltoall splits invalid for {first.tensor_name}")
        if local_only:
            return None
        if len(subs) < ps.size and rt not in (
                RequestType.ALLREDUCE, RequestType.ADASUM):
            return HorovodInternalError(
                f"rank(s) {[r for r in ps.ranks if r not in entry.subs]} "
                f"joined; {rt.name} does not support join")
        return None

    # hvdlint: seam[determinism]
    def _fuse(self, ps, entries):
        """FuseResponses analogue (controller.cc:901-1080): pack
        consecutive ready allreduce entries with matching
        (dtype, op, scales) into buckets up to the fusion threshold,
        and consecutive same-dtype allgathers likewise (the reference
        packs allgather responses with padding rules, :927-947 — the
        TF sparse-gradient path generates exactly this many-small-
        allgather stream).  Other ops execute one-per-bucket."""
        threshold = self.config.fusion_threshold_bytes
        buckets, cur, cur_bytes, cur_sig = [], [], 0, None
        for entry in entries:
            first = next(iter(entry.subs.values()))
            rt = first.request.request_type
            if rt in (RequestType.ALLREDUCE, RequestType.ADASUM):
                # wire dtype AND algorithm are part of the bucket
                # signature: quantized (int8) payloads pack
                # contiguously with each other and never share a
                # fusion buffer with full-width tensors, and a
                # hierarchical bucket never fuses with a flat one
                # (they run different SPMD programs)
                # ... and the shard-layout fingerprint: a sharded
                # update's collectives must never fuse with dense (or
                # differently-laid-out) traffic — the shard slices
                # are positional within their own buckets
                sig = (rt, first.request.dtype,
                       first.request.reduce_op,
                       first.request.prescale_factor,
                       first.request.postscale_factor,
                       first.request.wire_dtype,
                       first.request.wire_inner,
                       first.request.algorithm,
                       first.request.pp_sched,
                       first.request.shard_fp)
                nbytes = sum(p.nbytes for p in first.payloads)
            elif rt == RequestType.ALLGATHER:
                sig = (rt, first.request.dtype,
                       first.request.shard_fp)
                # threshold accounts the OUTPUT (gathered) size, like
                # the reference's fused-buffer accounting
                nbytes = sum(p.nbytes for p in first.payloads) * ps.size
            elif rt == RequestType.ALLTOALL:
                # alltoall is its own bucket type, segregated by wire
                # pair: consecutive exchanges with one (dtype, wire)
                # merge their per-destination segments into ONE fused
                # exchange (the MoE dispatch+combine pair of one layer
                # stack), and a quantized exchange never shares a
                # buffer with a full-width one
                sig = (rt, first.request.dtype,
                       first.request.wire_dtype,
                       first.request.wire_inner,
                       first.request.error_feedback)
                nbytes = sum(p.nbytes for p in first.payloads)
            else:
                if cur:
                    buckets.append(cur)
                    cur, cur_bytes, cur_sig = [], 0, None
                buckets.append([entry])
                continue
            if cur and (sig != cur_sig
                        or cur_bytes + nbytes > threshold):
                buckets.append(cur)
                cur, cur_bytes = [], 0
            cur.append(entry)
            cur_bytes += nbytes
            cur_sig = sig
        if cur:
            buckets.append(cur)
        return buckets

    def _run_bucket(self, ps, bucket, aux=None):
        first = next(iter(bucket[0].subs.values()))
        rt = first.request.request_type
        exec_t0 = time.monotonic()
        if self.timeline is not None:
            names = [n for e in bucket for s in (next(iter(e.subs.values())),)
                     for n in s.names]
            algo = None
            if rt in (RequestType.ALLREDUCE, RequestType.ADASUM):
                algo, _ = self._algo_plan(ps, first.request,
                                          first.request.reduce_op)
            # flow events per negotiation entry: an "s" anchored at
            # the instant THIS process became ready, chained by the
            # job-unique trace id into the execution span's "f" — the
            # merged trace's straggler arrows (docs/timeline.md)
            flows = {}
            for e in bucket:
                if e.trace_id is not None and e.ready_ts is not None:
                    ref = next(iter(e.subs.values()))
                    flows[ref.names[0]] = (e.trace_id, e.ready_ts)
            self.timeline.op_start(names, rt.name, algorithm=algo,
                                   flows=flows or None)
        try:
            if rt in (RequestType.ALLREDUCE, RequestType.ADASUM):
                self._run_allreduce_bucket(ps, bucket)
            elif rt == RequestType.ALLGATHER:
                if len(bucket) > 1:
                    self._run_allgather_fused(ps, bucket, aux=aux)
                else:
                    self._run_allgather(ps, bucket[0], aux=aux)
            elif rt == RequestType.BROADCAST:
                self._run_broadcast(ps, bucket[0])
            elif rt == RequestType.ALLTOALL:
                self._run_alltoall(ps, bucket, aux=aux)
            elif rt == RequestType.REDUCESCATTER:
                self._run_reducescatter(ps, bucket[0])
            elif rt == RequestType.BARRIER:
                for sub in bucket[0].subs.values():
                    sub.handle.set_result(None)
            else:
                raise HorovodInternalError(f"unhandled op {rt}")
        finally:
            self._m_execution.labels(op=rt.name).observe(
                time.monotonic() - exec_t0)
            if self.timeline is not None:
                self.timeline.op_end()

    def _local_subs(self, ps, entry):
        """Local participating submissions, ordered by global rank."""
        return {r: entry.subs[r] for r in ps.local_ranks if r in entry.subs}

    def _run_allreduce_bucket(self, ps, bucket):
        """Fused allreduce: one flat buffer per local rank for the whole
        bucket, one compiled collective, then unpack — the
        MemcpyInFusionBuffer / MemcpyOutFusionBuffer pattern
        (collective_operations.h:38-343) with numpy packing instead of
        a batched-D2D CUDA kernel.  Joined/missing local ranks
        contribute zeros (the reference's Join zero-tensor trick)."""
        first = next(iter(bucket[0].subs.values())).request
        op = first.reduce_op
        if first.request_type == RequestType.ADASUM:
            op = ReduceOp.ADASUM
        dtype = np.dtype(first.dtype) if first.dtype != "bfloat16" else \
            _bfloat16_dtype()
        # layout: [(entry, tensor_idx, offset, size, shape)]
        layout = []
        offset = 0
        for entry in bucket:
            ref_sub = next(iter(entry.subs.values()))
            for i, p in enumerate(ref_sub.payloads):
                layout.append((entry, i, offset, int(p.size), p.shape))
                offset += int(p.size)
        total = offset
        from . import native
        itemsize = dtype.itemsize
        rows = []
        ictx = None
        bad_rank = bad_where = None
        try:
            # annotated so host-side fusion phases appear as named
            # ranges inside jax-profiler device traces (the reference's
            # NVTX role, utils/profiler.py)
            with profiler.annotate("hvd_fusion_pack"):
                for r in ps.local_ranks:
                    arrays, offs_bytes, missing = [], [], False
                    for entry, i, off, size, _ in layout:
                        sub = entry.subs.get(r)
                        if sub is not None:
                            arrays.append(sub.payloads[i].ravel())
                            offs_bytes.append(off * itemsize)
                        else:            # joined ranks contribute zeros
                            missing = True
                    # staging buffer from the native arena (reference
                    # FusionBufferManager persistent buffer): steady
                    # state reuses the same aligned slabs every step
                    buf = self._arena.acquire(total * itemsize, dtype)
                    rows.append(buf)
                    if missing:
                        buf.fill(0)
                    # one native batched memcpy per rank per bucket
                    # (the reference's batched-D2D kernel,
                    # cuda_kernels.cu:27-292); multithreaded above 8 MiB
                    if total * itemsize >= \
                            self.config.pack_mt_threshold_bytes:
                        native.pack_mt(arrays, buf, offs_bytes)
                    else:
                        native.pack(arrays, buf, offs_bytes)
            if self.chaos is not None:
                # deterministic corruption chaos at the REAL encode
                # seam: the grad site counts this bucket and applies
                # due bitflip_grad events to the packed payload; the
                # wire site (inside dispatch, after the encode
                # digests) applies bitflip_wire to the encoded bytes
                self.chaos.corrupt_bucket("grad", rows)
            if self.integrity is not None:
                ictx = integrity_mod.BucketWatch(
                    f"{first.tensor_name}+{len(layout) - 1}")
            results = self._dispatch_allreduce(ps, first, op, dtype,
                                               rows, total, ictx=ictx)
            if self.integrity is not None:
                # decode-site verification, BEFORE the arena reuses
                # the slabs: submit-time payload digests against the
                # packed rows, encode-time wire digests against the
                # encoded buffers the collective consumed
                bad_rank, bad_where = self._integrity_scan(
                    ps, bucket, layout, rows, ictx)
        finally:
            # a pack/collective failure must not leak slabs — the
            # engine survives bucket errors (_execute_batch catches)
            for buf in rows:
                self._arena.release(buf)
        if self.integrity is not None:
            self._integrity_gate(ps, bad_rank, bad_where)
        if self.autotuner is not None:
            if not self._autotune_sig_noted:
                # the FIRST bucket's identity keys the warm-start
                # cache: steady-state training re-forms the same
                # buckets every cycle, so (keys, shapes, dtype) is a
                # stable job fingerprint
                self._autotune_sig_noted = True
                import hashlib
                parts = ",".join(sorted(
                    f"{e.key}:{s}" for e, _i, _o, _sz, s in layout))
                self.autotuner.note_bucket_signature(hashlib.md5(
                    f"{dtype}|{parts}".encode()).hexdigest()[:12])
            self.autotuner.record_bytes(total * dtype.itemsize)
        by_rank = dict(zip(ps.local_ranks, results))
        # single pass over layout, grouping outputs per (entry, rank)
        per_entry = {}
        with profiler.annotate("hvd_fusion_unpack"):
            for entry, i, off, size, shape in layout:
                for r in entry.subs:
                    if r in by_rank:
                        per_entry.setdefault((id(entry), r), []).append(
                            by_rank[r][off:off + size].reshape(shape))
        for entry in bucket:
            for r, sub in self._local_subs(ps, entry).items():
                outs = per_entry[(id(entry), r)]
                sub.handle.set_result(
                    outs if len(sub.payloads) > 1 else outs[0])

    def _integrity_scan(self, ps, bucket, layout, rows, ictx):
        """Decode-site verification of one allreduce bucket: every
        wire watch the dispatch registered (encode digests), plus the
        submit-time payload digests against the packed fusion rows.
        Returns ``(bad_rank, message)`` for the lowest implicated
        global rank, or ``(None, None)`` — raising is the gate's job,
        AFTER the cross-process vote, so peers never deadlock in a
        collective this process skipped."""
        bad, where = ictx.scan() if ictx is not None else (None, None)
        for row_i, r in enumerate(ps.local_ranks):
            if row_i >= len(rows) or (bad is not None and r >= bad):
                continue
            buf = rows[row_i]
            for entry, i, off, size, _shape in layout:
                sub = entry.subs.get(r)
                if sub is None or not sub.payload_fp:
                    continue
                if integrity_mod.digest64(
                        [buf[off:off + size]]) == sub.payload_fp[i]:
                    continue
                bad = r
                where = (
                    f"payload checksum mismatch in bucket "
                    f"{ictx.label if ictx else '?'!r}: tensor "
                    f"{sub.names[i]!r} of global rank {r} corrupted "
                    f"between submit and encode")
                break
        return bad, where

    def _integrity_vote(self, ps, bad_rank):
        """The implicated-rank agreement — a 1-element MIN allreduce
        over the existing collective path (the bypass-vote shape,
        :meth:`_bypass_vote`): every rank votes its lowest
        locally-detected corrupt rank (OK_VOTE when clean), so the
        reduced value names the same implicated rank on EVERY process
        at once — which is what makes the quarantine unanimous."""
        vote = integrity_mod.OK_VOTE if bad_rank is None \
            else float(bad_rank)
        rows = [np.full(1, vote, np.float32) for _ in ps.local_ranks]
        out = ps.executor.allreduce(rows, ReduceOp.MIN)
        v = float(out[0][0])
        return None if v >= integrity_mod.OK_VOTE else int(v)

    def _integrity_gate(self, ps, bad, where):
        """Per-bucket integrity verdict.  Multi-process buckets vote
        first (:meth:`_integrity_vote`) so a detection on ANY process
        quarantines the step on ALL of them before any rank's
        optimizer applies the corrupt update; single-process detection
        raises directly (every local rank's handle errors together in
        :meth:`_execute_batch`)."""
        from .. import telemetry

        voted = bad
        if self.multiproc:
            voted = self._integrity_vote(ps, bad)
            if voted is not None and voted != bad:
                where = None
        if voted is None:
            telemetry.count_integrity_check("ok", "engine")
            return
        telemetry.count_integrity_check("corrupt", "engine")
        evict = self.integrity.record_detection(voted) \
            and voted in ps.local_ranks
        self.quarantine_step(
            integrity_mod.WireIntegrityError.reason, rank=voted)
        msg = where or (
            f"a peer process detected wire corruption attributed to "
            f"global rank {voted}")
        logger.error(
            "integrity: %s — quarantining the step and rolling back "
            "to the last commit", msg)
        if evict:
            raise integrity_mod.HostEvictionError(
                f"integrity: global rank {voted} implicated in "
                f"{self.integrity.detections.get(voted, 0)} "
                f"detections (HOROVOD_INTEGRITY_EVICT_AFTER="
                f"{self.integrity.evict_after}) — exiting so the "
                f"driver's blacklist verdict evicts this host; "
                f"last detection: {msg}", rank=voted)
        raise integrity_mod.WireIntegrityError(msg, rank=voted,
                                               site="engine")

    def quarantine_step(self, reason, rank=None):
        """Step-quarantine hygiene (docs/fault_tolerance.md "Silent
        data corruption"): count the rollback, poison/disarm the
        negotiation bypass (the corrupted cycle must never [re-]arm
        or execute again), drop the autotuner's in-flight sample (its
        timing window now spans a replay) and clear the compiled
        path's EF residuals — a stale residual after rollback is
        itself a divergence bug.  The frontends' EF residuals reset
        through their own ``reset_wire_state`` seam when the elastic
        restore re-forms the job."""
        from .. import telemetry

        telemetry.count_integrity_rollback(reason)
        logger.warning(
            "integrity: step quarantined (%s%s)", reason,
            f", implicated rank {rank}" if rank is not None else "")
        bp = self._bypass
        if bp is not None:
            if bp.active:
                bp.poison("integrity")
            else:
                bp.disarm()
        if self.autotuner is not None:
            self.autotuner.abort_sample()
        try:
            from ..ops.compiled import reset_ef_state
            reset_ef_state()
        except Exception:  # noqa: BLE001 — hygiene must not mask detection
            logger.exception("integrity: compiled EF reset failed")
        # alltoall EF residuals are engine-held (per peer-slot): same
        # rule — a residual mutated by the quarantined exchange must
        # not seed the replay
        self._a2a_ef.clear()
        # engine-path EF residuals live on the frontends' updaters
        # (torch/TF DistributedOptimizer, the sharded updaters), which
        # the in-place rollback never re-creates: a residual mutated
        # by the quarantined step's submit must not seed the replay
        integrity_mod.reset_registered_wire_state()

    def _wire_for(self, req, dtype, op):
        """Effective wire format for a float reduction.  The process-
        wide default (HOROVOD_WIRE_DTYPE / autotune) was already
        resolved into the request at submit() — before negotiation —
        so this is a pure function of the cross-rank-validated request.
        'f32' is the explicit full-width override.  Non-float payloads
        and non-linear reductions (min/max/product/adasum — their math
        does not commute with per-rank decode) ship full width, as do
        combinations where the "compression" would not shrink the wire
        (bf16 wire for an already-16-bit tensor)."""
        if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
            return None
        if not (np.issubdtype(dtype, np.floating)
                or str(dtype) == "bfloat16"):
            return None
        wire = req.wire_dtype
        if wire == "f32":
            return None
        if wire in ("fp16", "bf16") and dtype.itemsize <= 2:
            return None
        return wire

    def _wire_for_alltoall(self, req, dtype):
        """Effective wire format for an alltoall exchange.  Unlike the
        reductions there is no accumulation to commute with — the
        exchange moves raw segments — so ANY float payload may ride a
        narrow wire; non-float payloads and no-op "compressions"
        (16-bit wire for an already-16-bit tensor) ship full width."""
        if not (np.issubdtype(dtype, np.floating)
                or str(dtype) == "bfloat16"):
            return None
        wire = req.wire_dtype
        if wire == "f32":
            return None
        if wire in ("fp16", "bf16") and dtype.itemsize <= 2:
            return None
        return wire

    def _spans_hosts(self, ps=None):
        """Whether the job (or one process set) crosses a DCN hop."""
        topo = self.topology
        if topo is None or not topo.host_of_rank:
            return False
        if ps is None:
            return topo.num_hosts > 1
        hosts = {topo.host_of_rank[r] for r in ps.ranks
                 if r < len(topo.host_of_rank)}
        return len(hosts) > 1

    def _account_wire(self, logical, actual, cross=None, wire=None):
        """``cross`` = bytes over the slow (cross-host) hop; ``None``
        means the collective was flat, so its whole wire crosses DCN
        whenever the job spans hosts (topology-aware dispatch passes
        its decomposed cross-hop bytes explicitly).  ``wire`` labels
        the metric family with the encoding that produced the bytes
        (None = full width)."""
        if cross is None:
            cross = actual if self._spans_hosts() else 0
        w = wire or "f32"
        self._m_logical.labels(wire=w).inc(int(logical))
        self._m_actual.labels(wire=w).inc(int(actual))
        self._m_cross.labels(wire=w).inc(int(cross))

    def _account_hop(self, hop, wire, nbytes):
        """Per-hop byte accounting (telemetry WIRE_HOP_BYTES_FAMILY):
        ``hop`` is the decomposition stage ('inner' = the fast
        ICI stage, 'cross' = the slow DCN stage), ``wire`` that hop's
        encoding — the split that shows WHERE a per-hop pair actually
        spends its bytes."""
        self._m_hop.labels(hop=hop, wire=wire or "f32").inc(
            int(nbytes))

    def _encode_quantized_rows(self, rows, logical_nbytes, wire):
        """Block-quantize per-rank rows for the int8 or int4 wire
        (shared by the allreduce and reducescatter paths) and account
        the actual bytes: codes + bf16 scales — 1 B/elem for int8,
        0.5 B/elem (packed nibbles) for int4.  Returns
        (q_rows, s_rows, n_elems) where n_elems is the padded element
        count of the code layout."""
        from ..ops import quantize as qz
        encode = qz.np_quantize_blockwise_int4 if wire == "int4" \
            else qz.np_quantize_blockwise
        q_rows, s_rows = [], []
        with profiler.annotate("hvd_quantize_encode"):
            for r in rows:
                q, s, _ = encode(r)
                q_rows.append(q)
                s_rows.append(s)
        self._account_wire(logical_nbytes,
                           q_rows[0].nbytes + s_rows[0].nbytes,
                           wire=wire)
        self._m_quantized.inc()
        return q_rows, s_rows, s_rows[0].size * qz.BLOCK

    def _algo_plan(self, ps, req, op):
        """Effective (algorithm, inner-axis size) for an allreduce
        bucket.  Non-flat algorithms need a float Sum/Average payload,
        shard mode (one device per rank — decomposition is meaningless
        when rank threads share a chip) and a topology that factors
        (common/topology.plan_decomposition); anything else degrades
        to flat, the reference's ``is_homogeneous`` fallback."""
        algo = req.algorithm
        if req.request_type == RequestType.ADASUM:
            op = ReduceOp.ADASUM
        if algo in (None, "flat"):
            return "flat", None
        if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
            return "flat", None
        if req.dtype != "bfloat16" and \
                not np.issubdtype(np.dtype(req.dtype), np.floating):
            return "flat", None
        if not ps.executor.shard_mode:
            return "flat", None
        from ..common.topology import plan_decomposition
        inner = plan_decomposition(algo, self.topology, ps.ranks)
        if inner is None:
            key = (ps.id, algo)
            if key not in self._algo_warned:
                self._algo_warned.add(key)
                logger.debug(
                    "%s allreduce requested but process set %d "
                    "(%d ranks) does not decompose; running flat",
                    algo, ps.id, ps.size)
            return "flat", None
        return algo, inner

    def _inner_wire_for(self, req, outer, dtype):
        """Effective INNER (ICI) hop wire for a decomposed reduction
        (the one uniform-shorthand rule,
        quantize.effective_inner_wire)."""
        from ..ops import quantize as qz
        return qz.effective_inner_wire(req.wire_inner, outer,
                                       dtype.itemsize)

    def _dispatch_allreduce(self, ps, req, op, dtype, rows, total,
                            ictx=None):
        """Run the fused allreduce over the configured wire PAIR and
        algorithm: full width, 16-bit cast, or block-scaled int8/int4
        (encode -> quantized collective -> f32 decode) x flat /
        hierarchical / torus (ops/xla_ops.allreduce_2d, which fuses
        the per-hop codecs into the one decomposed program).  ``ictx``
        (core/integrity.BucketWatch) captures encode-time digests of
        the ACTUAL wire buffers — the 16-bit cast or the codes+scales;
        raw f32 rows are covered by the submit-time payload digests —
        and the chaos injector's wire site flips bits right after
        those digests, so the decode-side scan is what detects it."""
        wire = self._wire_for(req, dtype, op)
        algo, inner = self._algo_plan(ps, req, op)
        self._m_algo.labels(algorithm=algo).inc()
        itemsize = dtype.itemsize
        if algo != "flat":
            return self._dispatch_allreduce_2d(
                ps, req, op, dtype, rows, total, wire, inner)
        spans = self._spans_hosts(ps)
        flat_hop = "cross" if spans else "inner"
        flat_cross = total * itemsize if spans else 0
        if wire is None:
            self._account_wire(total * itemsize, total * itemsize,
                               cross=flat_cross)
            self._account_hop(flat_hop, None, total * itemsize)
            if self.chaos is not None:
                self.chaos.corrupt_bucket("wire", rows)
            return ps.executor.allreduce(
                rows, op, req.prescale_factor, req.postscale_factor)
        if wire in ("fp16", "bf16"):
            wdt = np.dtype(np.float16) if wire == "fp16" \
                else _bfloat16_dtype()
            self._account_wire(total * itemsize, total * 2,
                               cross=total * 2 if flat_cross else 0,
                               wire=wire)
            self._account_hop(flat_hop, wire, total * 2)
            wrows = [r.astype(wdt) for r in rows]
            if ictx is not None:
                ictx.watch("engine", flat_hop, wire, wrows,
                           ps.local_ranks)
            if self.chaos is not None:
                self.chaos.corrupt_bucket("wire", wrows)
            out = ps.executor.allreduce(
                wrows, op, req.prescale_factor, req.postscale_factor)
            return [o.astype(dtype) for o in out]
        q_rows, s_rows, npad = self._encode_quantized_rows(
            rows, total * itemsize, wire)
        self._account_hop(flat_hop, wire,
                          q_rows[0].nbytes + s_rows[0].nbytes)
        if ictx is not None:
            ictx.watch("engine", flat_hop, wire,
                       list(zip(q_rows, s_rows)), ps.local_ranks)
        if self.chaos is not None:
            self.chaos.corrupt_bucket("wire", q_rows + s_rows)
        out = ps.executor.allreduce_quantized(
            q_rows, s_rows, op, req.prescale_factor,
            req.postscale_factor, nbits=4 if wire == "int4" else 8,
            n_elems=npad)
        with profiler.annotate("hvd_quantize_decode"):
            return [o[:total].astype(dtype) for o in out]

    def _dispatch_allreduce_2d(self, ps, req, op, dtype, rows, total,
                               wire, inner):
        """Hierarchical / torus bucket with the PER-HOP wire pair:
        reducescatter along the fast (inner) axis over the inner
        wire, allreduce the 1/inner shard along the slow (outer)
        axis over the outer wire — shared-scale quantized integer
        partials for int8/int4, the codec fused into the one
        compiled program (ops/xla_ops._build_allreduce_2d) — then
        allgather back over the inner wire.  Cross-hop accounting
        shows the decomposition's whole point: only the shard crosses
        DCN, at the outer wire's width.  Like the flat branch, cross
        bytes are attributed only when the set actually spans hosts —
        a single-host torus run has no DCN hop, and counting one
        would invert the flat-vs-torus comparison the field exists
        for.  The hop family accounts both stages unconditionally
        (the inner stage is real traffic either way)."""
        from ..ops import quantize as qz
        if self.chaos is not None:
            # the decomposed program fuses the codec on-device, so the
            # host-visible wire IS the packed rows (already digested
            # at submit): the wire site flips them here and the
            # payload scan at decode detects it
            self.chaos.corrupt_bucket("wire", rows)
        itemsize = dtype.itemsize
        m = -(-total // inner)          # cross-hop shard elements
        spans = self._spans_hosts(ps)
        inner_wire = self._inner_wire_for(req, wire, dtype)
        iw_width = 2 if inner_wire else itemsize
        # the inner stage moves the payload twice: the psum_scatter
        # into shards and the all_gather back
        self._account_hop("inner", inner_wire, 2 * total * iw_width)
        if wire in ("int8", "int4"):
            bits = 4 if wire == "int4" else 8
            # local hops ship the inner wire (ICI is cheap); the cross
            # hop ships shared-scale integer partials + bf16 scales
            cross = qz.quantized_psum_wire_nbytes(
                m, ps.size // inner, bits=bits)
            self._account_wire(total * itemsize, total * iw_width,
                               cross=cross if spans else 0, wire=wire)
            self._account_hop("cross", wire, cross)
            self._m_quantized.inc()
            out = ps.executor.allreduce_2d(
                rows, op, req.prescale_factor, req.postscale_factor,
                inner, inner_wire=inner_wire, outer_wire=wire)
            return [o.astype(dtype, copy=False) for o in out]
        if wire in ("fp16", "bf16"):
            cross = m * 2
        else:
            cross = m * itemsize
        self._account_wire(total * itemsize, total * iw_width
                           if (inner_wire or wire) else total * itemsize,
                           cross=cross if spans else 0, wire=wire)
        self._account_hop("cross", wire, cross)
        out = ps.executor.allreduce_2d(
            rows, op, req.prescale_factor, req.postscale_factor,
            inner, inner_wire=inner_wire, outer_wire=wire)
        return [o.astype(dtype, copy=False) for o in out]

    def _global_dim0s(self, ps, entry, aux, n_tensors):
        """Global per-rank first-dim table for allgather.  Local mode
        reads the submissions; store mode merges the coordinator's
        per-process aux (reference allgather shape exchange)."""
        if not self.multiproc:
            return [
                [int(entry.subs[r].payloads[i].shape[0])
                 if entry.subs[r].payloads[i].ndim else 1
                 for r in ps.ranks]
                for i in range(n_tensors)
            ]
        per_proc = aux.get(entry.key, {}) if aux else {}
        dim0s_by_rank = {}
        for proc_str, a in per_proc.items():
            proc = int(proc_str)
            members = [r for r in ps.ranks
                       if self._proc_of(r) == proc]
            for local_i, r in enumerate(members):
                dim0s_by_rank[r] = a["dim0s"][local_i]
        return [
            [int(dim0s_by_rank[r][i]) for r in ps.ranks]
            for i in range(n_tensors)
        ]

    def _run_allgather(self, ps, entry, aux=None):
        """Allgather with per-rank first-dim sizes: pad to max rows
        (the reference exchanges shapes during negotiation and sizes the
        fused buffer accordingly, controller.cc:901-1080).  The sharded
        updater's PARAM wire rides this path, so it carries the same
        encode-digest / decode-verify / vote integrity as the gradient
        wires — a corrupted gathered shard installs IDENTICALLY on
        every replica, which the divergence sentinel can never see."""
        subs = self._local_subs(ps, entry)
        first = next(iter(subs.values()))
        n_tensors = len(first.payloads)
        dim0_tables = self._global_dim0s(ps, entry, aux, n_tensors)
        local_ranks = list(subs)
        ictx = None
        if self.integrity is not None:
            ictx = integrity_mod.BucketWatch(
                f"{first.request.tensor_name}/ag")
        results_per_rank = {r: [] for r in subs}
        for i in range(n_tensors):
            dim0 = dim0_tables[i]
            rest = tuple(first.payloads[i].shape[1:])
            max_d = max(dim0) if dim0 else 0
            rest_n = int(np.prod(rest, dtype=np.int64)) if rest else 1
            rows = []
            for r in subs:
                p = subs[r].payloads[i]
                flat = np.ravel(p)
                buf = np.zeros(max_d * rest_n, dtype=p.dtype)
                buf[:flat.size] = flat
                rows.append(buf)
            if ictx is not None:
                ictx.watch("engine", "gather", None, rows, local_ranks)
            if self.chaos is not None:
                self.chaos.corrupt_bucket("grad", rows)
                self.chaos.corrupt_bucket("wire", rows)
            gathered = ps.executor.allgather(rows, dim0, rest)
            for r, g in zip(subs, gathered):
                results_per_rank[r].append(g)
        if ictx is not None:
            self._integrity_gate(ps, *ictx.scan())
        for r, sub in subs.items():
            outs = results_per_rank[r]
            sub.handle.set_result(outs if n_tensors > 1 else outs[0])

    def _run_allgather_fused(self, ps, bucket, aux=None):
        """Fused allgather bucket: every entry's tensors pack into ONE
        flat per-rank buffer and ONE compiled gather (FuseResponses
        allgather packing, controller.cc:901-1080 with the :927-947
        padding role).  The wire pads each rank to the max TOTAL
        contribution instead of per-tensor max rows, and a stream of
        small gathers (sparse embedding rows) costs one program
        dispatch instead of one each."""
        self._m_fused_ag.inc()
        R = ps.size
        tables = []     # (entry, subs, n_tensors, rest_shapes, dim0s)
        for entry in bucket:
            subs = self._local_subs(ps, entry)
            ref = next(iter(subs.values()))
            n_tensors = len(ref.payloads)
            dim0s = self._global_dim0s(ps, entry, aux, n_tensors)
            rests = [tuple(ref.payloads[i].shape[1:])
                     for i in range(n_tensors)]
            tables.append((entry, subs, n_tensors, rests, dim0s))
        rest_ns = [
            [int(np.prod(r, dtype=np.int64)) if r else 1 for r in rests]
            for _, _, _, rests, _ in tables]
        # per-global-rank flat totals (elements) — the wire dim0s
        totals = []
        for pos in range(R):
            t = 0
            for (entry, subs, n, rests, dim0s), rns in \
                    zip(tables, rest_ns):
                for i in range(n):
                    t += dim0s[i][pos] * rns[i]
            totals.append(t)
        dtype = next(iter(bucket[0].subs.values())).payloads[0].dtype
        max_t = max(totals) if totals else 0
        rows = []
        local = [r for r in ps.local_ranks if r in bucket[0].subs]
        for r in local:
            parts = [np.ravel(subs[r].payloads[i])
                     for (entry, subs, n, rests, dim0s) in tables
                     for i in range(n)]
            flat = np.concatenate(parts) if parts else \
                np.zeros(0, dtype=dtype)
            buf = np.zeros(max_t, dtype=dtype)
            buf[:flat.size] = flat
            rows.append(buf)
        ictx = None
        if self.integrity is not None:
            ref0 = next(iter(bucket[0].subs.values()))
            ictx = integrity_mod.BucketWatch(
                f"{ref0.request.tensor_name}+{len(bucket) - 1}/ag")
            ictx.watch("engine", "gather", None, rows, local)
        if self.chaos is not None:
            self.chaos.corrupt_bucket("grad", rows)
            self.chaos.corrupt_bucket("wire", rows)
        gathered = ps.executor.allgather(rows, totals, ())
        if ictx is not None:
            self._integrity_gate(ps, *ictx.scan())
        # slice table: absolute [start, end) of (entry_idx, tensor,
        # source position) inside the concatenated exact buffer
        rank_starts = np.cumsum([0] + totals[:-1])
        slices = {}
        for pos in range(R):
            off = int(rank_starts[pos])
            for e_idx, ((entry, subs, n, rests, dim0s), rns) in \
                    enumerate(zip(tables, rest_ns)):
                for i in range(n):
                    sz = dim0s[i][pos] * rns[i]
                    slices[(e_idx, i, pos)] = (off, off + sz)
                    off += sz
        for r, g in zip(local, gathered):
            for e_idx, (entry, subs, n, rests, dim0s) in \
                    enumerate(tables):
                outs = []
                for i in range(n):
                    segs = []
                    for pos in range(R):
                        a, b = slices[(e_idx, i, pos)]
                        segs.append(g[a:b].reshape(
                            (dim0s[i][pos],) + rests[i]))
                    outs.append(np.concatenate(segs, axis=0))
                subs[r].handle.set_result(
                    outs if n > 1 else outs[0])

    def _run_broadcast(self, ps, entry):
        subs = self._local_subs(ps, entry)
        first = next(iter(subs.values()))
        root = first.request.root_rank
        root_pos = ps.index.get(root)
        if root_pos is None:
            for sub in subs.values():
                sub.handle.set_error(HorovodInternalError(
                    f"broadcast root {root} not in process set {ps.id}"))
            return
        n_tensors = len(first.payloads)
        results_per_rank = {r: [] for r in subs}
        for i in range(n_tensors):
            shape = first.payloads[i].shape
            rows = [subs[r].payloads[i].ravel() for r in subs]
            out = ps.executor.broadcast(rows, root_pos)
            for r, o in zip(subs, out):
                results_per_rank[r].append(o.reshape(shape))
        for r, sub in subs.items():
            outs = results_per_rank[r]
            sub.handle.set_result(outs if n_tensors > 1 else outs[0])

    def _global_splits(self, ps, entry, aux):
        """Global alltoall send-split table (one vector per rank)."""
        if not self.multiproc:
            return [list(entry.subs[r].request.splits) for r in ps.ranks]
        per_proc = aux.get(entry.key, {}) if aux else {}
        splits_by_rank = {}
        for proc_str, a in per_proc.items():
            proc = int(proc_str)
            members = [r for r in ps.ranks if self._proc_of(r) == proc]
            for local_i, r in enumerate(members):
                splits_by_rank[r] = a["splits"][local_i]
        return [list(splits_by_rank[r]) for r in ps.ranks]

    def _run_alltoall(self, ps, bucket, aux=None):
        """Fused wire-quantized alltoall bucket (the MoE dispatch/
        combine wire).  All entries of the bucket share one (dtype,
        wire pair) signature — their per-destination segments merge
        into ONE exchange, so a layer stack's dispatch+combine pair
        costs one collective.  The int8/int4 wire pads every
        (rank, destination) slot to a BLOCK multiple so each slot
        owns whole scale blocks: the receiver decodes each peer slot
        against exactly the scales that peer encoded with, error
        feedback accumulates per peer slot, and the encode/decode
        digests (BucketWatch) cover every slot — a corrupted expert
        route is silent by construction, so the alltoall wire gets
        the same digest + implicated-rank-vote integrity as the
        reduction wires."""
        from ..ops import quantize as qz

        R = ps.size
        entries = []
        for e in bucket:
            subs_e = self._local_subs(ps, e)
            first_e = next(iter(subs_e.values()))
            rest_e = tuple(first_e.payloads[0].shape[1:])
            rest_n = int(np.prod(rest_e, dtype=np.int64)) if rest_e else 1
            splits_e = self._global_splits(ps, e, aux)
            entries.append((e, subs_e, first_e, rest_e, rest_n, splits_e))
        subs0 = entries[0][1]
        req = entries[0][2].request
        local_ranks = list(subs0)
        pdtype = entries[0][2].payloads[0].dtype
        itemsize = np.dtype(pdtype).itemsize
        # combined element-split matrix over global positions:
        # comb[src][dst] = elements src sends dst across the bucket
        comb = [[sum(sp[src][dst] * rn
                     for (_, _, _, _, rn, sp) in entries)
                 for dst in range(R)] for src in range(R)]
        # one exact concat-per-destination stream per local rank
        rows = []
        for r in local_ranks:
            p = ps.index[r]
            parts = []
            for dst in range(R):
                for (_, subs_e, _, _, rn, sp) in entries:
                    flat = np.ravel(subs_e[r].payloads[0])
                    start = sum(sp[p][:dst]) * rn
                    parts.append(flat[start:start + sp[p][dst] * rn])
            rows.append(np.concatenate(parts) if parts
                        else np.zeros(0, dtype=pdtype))
        wire = self._wire_for_alltoall(req, np.dtype(pdtype)) \
            if R > 1 else None
        seg = max((comb[s][d] for s in range(R) for d in range(R)),
                  default=0)
        if wire in ("int8", "int4") and seg == 0:
            wire = None
        hop = "cross" if self._spans_hosts(ps) else "inner"
        rank0 = local_ranks[0]
        pos0 = ps.index[rank0]
        topo = self.topology
        host0 = topo.host_of_rank[rank0] \
            if topo is not None and topo.host_of_rank else None

        def hop_of(dst):
            if host0 is None:
                return "inner"
            g = ps.ranks[dst]
            if g >= len(topo.host_of_rank):
                return "inner"
            return "cross" if topo.host_of_rank[g] != host0 else "inner"

        def account(wire_seg_bytes):
            """Split rank0's exchange bytes by destination hop; a
            callable maps a destination's element count to its wire
            bytes (None = one fixed padded slot cost per peer)."""
            by_hop = {}
            for dst in range(R):
                h = hop_of(dst)
                lg, ac = by_hop.get(h, (0, 0))
                by_hop[h] = (lg + comb[pos0][dst] * itemsize,
                             ac + wire_seg_bytes(comb[pos0][dst]))
            for h, (lg, ac) in by_hop.items():
                self._m_a2a_logical.labels(hop=h,
                                           wire=wire or "f32").inc(lg)
                self._m_a2a_wire.labels(hop=h,
                                        wire=wire or "f32").inc(ac)
                self._account_hop(h, wire, ac)
            tot_l = sum(v[0] for v in by_hop.values())
            tot_a = sum(v[1] for v in by_hop.values())
            self._account_wire(tot_l, tot_a, wire=wire)

        ictx = None
        if self.integrity is not None:
            ictx = integrity_mod.BucketWatch(f"{req.tensor_name}/a2a")
            ictx.watch("engine", hop, None, rows, local_ranks)
        if self.chaos is not None:
            self.chaos.corrupt_bucket("grad", rows)
        if wire in ("int8", "int4"):
            # pad every (rank, dest) slot to a whole number of scale
            # blocks: slot boundaries align with the block grid, so
            # the receiver decodes each peer slot against exactly
            # that peer's scales and EF stays per-slot
            seg_pad = -(-seg // qz.BLOCK) * qz.BLOCK
            nbseg = seg_pad // qz.BLOCK
            encode = qz.np_quantize_blockwise_int4 if wire == "int4" \
                else qz.np_quantize_blockwise
            decode = qz.np_dequantize_blockwise_int4 if wire == "int4" \
                else qz.np_dequantize_blockwise
            q_rows, s_rows = [], []
            with profiler.annotate("hvd_a2a_quantize_encode"):
                for i, r in enumerate(local_ranks):
                    p = ps.index[r]
                    padded = np.zeros(R * seg_pad, np.float32)
                    flat32 = rows[i].astype(np.float32)
                    off = 0
                    for dst in range(R):
                        ln = comb[p][dst]
                        padded[dst * seg_pad:dst * seg_pad + ln] = \
                            flat32[off:off + ln]
                        off += ln
                    key = (ps.id, r)
                    if not req.error_feedback:
                        # stateless encode (bit-exact-replay mode):
                        # no residual injected, none carried — and a
                        # residual left by an earlier EF-on exchange
                        # must not leak into a later EF-on one across
                        # this stateless step
                        self._a2a_ef.pop(key, None)
                        q, s, _ = encode(padded)
                        q_rows.append(q)
                        s_rows.append(s)
                        continue
                    prev = self._a2a_ef.get(key)
                    if prev is not None and prev.shape == padded.shape:
                        # per peer-slot error feedback: only positions
                        # inside the slot's CURRENT segment re-inject
                        # (residual under stale padding stays inert)
                        for dst in range(R):
                            ln = comb[p][dst]
                            sl = slice(dst * seg_pad,
                                       dst * seg_pad + ln)
                            padded[sl] += prev[sl]
                    elif prev is not None:
                        # layout changed (splits / world resize):
                        # stale residuals never cross layouts
                        del self._a2a_ef[key]
                    q, s, _ = encode(padded)
                    self._a2a_ef[key] = padded - decode(
                        q, s, R * seg_pad)
                    q_rows.append(q)
                    s_rows.append(s)
            q_seg = q_rows[0].size // R
            s_seg = nbseg
            account(lambda _n, _q=q_rows[0], _s=s_rows[0]:
                    _q.nbytes // R + _s.nbytes // R)
            self._m_quantized.inc()
            if ictx is not None:
                ictx.watch("engine", hop, wire,
                           list(zip(q_rows, s_rows)), local_ranks)
            if self.chaos is not None:
                self.chaos.corrupt_bucket("wire", q_rows + s_rows)
            eq_q = [[q_seg] * R for _ in range(R)]
            eq_s = [[s_seg] * R for _ in range(R)]
            q_res, _ = ps.executor.alltoall(q_rows, eq_q, ())
            s_res, _ = ps.executor.alltoall(s_rows, eq_s, ())
            flat_recv = []
            for i, r in enumerate(local_ranks):
                p = ps.index[r]
                full = decode(np.asarray(q_res[i]),
                              np.asarray(s_res[i]), R * seg_pad)
                flat_recv.append(np.concatenate(
                    [full[src * seg_pad:src * seg_pad + comb[src][p]]
                     for src in range(R)]) if R else full)
        elif wire in ("fp16", "bf16"):
            wdt = np.dtype(np.float16) if wire == "fp16" \
                else _bfloat16_dtype()
            wrows = [row.astype(wdt) for row in rows]
            account(lambda n: n * 2)
            if ictx is not None:
                ictx.watch("engine", hop, wire, wrows, local_ranks)
            if self.chaos is not None:
                self.chaos.corrupt_bucket("wire", wrows)
            results, _ = ps.executor.alltoall(
                wrows, [list(c) for c in comb], ())
            flat_recv = [np.asarray(res) for res in results]
        else:
            account(lambda n: n * itemsize)
            if self.chaos is not None:
                self.chaos.corrupt_bucket("wire", rows)
            results, _ = ps.executor.alltoall(
                rows, [list(c) for c in comb], ())
            flat_recv = [np.asarray(res) for res in results]
        self._m_a2a_runs.labels(path="engine",
                                wire=wire or "f32").inc()
        if ictx is not None:
            # decode-site scan + ONE gate (and vote) per bucket, after
            # the exchange, so peers never desync on a mid-bucket raise
            self._integrity_gate(ps, *ictx.scan())
        # de-interleave the received stream back into per-entry
        # outputs: per source, the bucket's segments arrive in entry
        # order (the same order the send side concatenated them)
        for i, r in enumerate(local_ranks):
            p = ps.index[r]
            buf = flat_recv[i]
            per_entry = {id(e): [] for (e, *_rest) in entries}
            off = 0
            for src in range(R):
                for (e, _, _, _, rn, sp) in entries:
                    ln = sp[src][p] * rn
                    per_entry[id(e)].append(buf[off:off + ln])
                    off += ln
            for (e, subs_e, _, rest_e, rn, sp) in entries:
                parts = per_entry[id(e)]
                out = np.concatenate(parts) if parts else \
                    np.zeros(0, dtype=pdtype)
                out = out.astype(pdtype).reshape((-1,) + rest_e)
                rsp = np.array([sp[src][p] for src in range(R)],
                               dtype=np.int32)
                subs_e[r].handle.set_result(out, extra=rsp)

    def _run_reducescatter(self, ps, entry):
        """Reducescatter; grouped submissions carry several payloads
        and resolve to a list per rank (like _run_allgather).  The
        sharded updater's gradient wire rides this path, so it gets
        the same encode-digest / decode-verify / implicated-rank-vote
        integrity as the allreduce buckets — the assembled rows are
        digested right after encode (a reducescatter spreads one
        rank's corruption into every rank's shard, which the sentinel
        could NOT catch: the replicas stay bit-identical and wrong)."""
        subs = self._local_subs(ps, entry)
        first = next(iter(subs.values()))
        req = first.request
        op = req.reduce_op
        n_tensors = len(first.payloads)
        R = ps.size
        local_ranks = list(subs)
        ictx = None
        if self.integrity is not None:
            ictx = integrity_mod.BucketWatch(f"{req.tensor_name}/rs")
        results_per_rank = {r: [] for r in subs}
        for i in range(n_tensors):
            shape = first.payloads[i].shape
            d0 = int(shape[0]) if shape else 1
            rest = tuple(shape[1:])
            rest_n = int(np.prod(rest, dtype=np.int64)) if rest else 1
            chunks = ps.executor.chunk_sizes(d0, R)
            max_chunk = max(chunks) if chunks else 0
            offsets = np.cumsum([0] + chunks[:-1])
            rows = []
            for r in subs:
                flat = np.ravel(subs[r].payloads[i])
                buf = np.zeros(R * max_chunk * rest_n, dtype=flat.dtype)
                for j in range(R):
                    src = offsets[j] * rest_n
                    dst = j * max_chunk * rest_n
                    buf[dst:dst + chunks[j] * rest_n] = \
                        flat[src:src + chunks[j] * rest_n]
                rows.append(buf)
            hop = "cross" if self._spans_hosts(ps) else "inner"
            if ictx is not None:
                # the assembled rows ARE this path's submit-equivalent
                # payload; digest before the chaos sites so both
                # bitflip kinds land after the digest and are caught
                # by the decode scan
                ictx.watch("engine", hop, None, rows, local_ranks)
            if self.chaos is not None:
                self.chaos.corrupt_bucket("grad", rows)
            wire = self._wire_for(req, np.dtype(rows[0].dtype), op)
            if wire in ("int8", "int4"):
                dtype = rows[0].dtype
                q_rows, s_rows, npad = self._encode_quantized_rows(
                    rows, rows[0].nbytes, wire)
                self._account_hop(
                    hop, wire, q_rows[0].nbytes + s_rows[0].nbytes)
                if ictx is not None:
                    ictx.watch("engine", hop, wire,
                               list(zip(q_rows, s_rows)), local_ranks)
                if self.chaos is not None:
                    self.chaos.corrupt_bucket("wire", q_rows + s_rows)
                results = [
                    res.astype(dtype)
                    for res in ps.executor.reducescatter_quantized(
                        q_rows, s_rows, d0, rest, op,
                        req.prescale_factor, req.postscale_factor,
                        nbits=4 if wire == "int4" else 8,
                        n_elems=npad)
                ]
            else:
                if wire in ("fp16", "bf16"):
                    dtype = rows[0].dtype
                    wdt = np.dtype(np.float16) if wire == "fp16" \
                        else _bfloat16_dtype()
                    self._account_wire(rows[0].nbytes,
                                       rows[0].size * 2, wire=wire)
                    wrows = [row.astype(wdt) for row in rows]
                    if ictx is not None:
                        ictx.watch("engine", hop, wire, wrows,
                                   local_ranks)
                    if self.chaos is not None:
                        self.chaos.corrupt_bucket("wire", wrows)
                    results = [
                        res.astype(dtype)
                        for res in ps.executor.reducescatter(
                            wrows, d0, rest, op, req.prescale_factor,
                            req.postscale_factor)
                    ]
                else:
                    self._account_wire(rows[0].nbytes, rows[0].nbytes)
                    if self.chaos is not None:
                        self.chaos.corrupt_bucket("wire", rows)
                    results = ps.executor.reducescatter(
                        rows, d0, rest, op, req.prescale_factor,
                        req.postscale_factor)
            for r, res in zip(subs, results):
                results_per_rank[r].append(res)
        if ictx is not None:
            # decode-site scan + ONE gate (and vote) per entry, after
            # every tensor dispatched, so peers never desync on a
            # mid-entry raise
            self._integrity_gate(ps, *ictx.scan())
        for r, sub in subs.items():
            outs = results_per_rank[r]
            sub.handle.set_result(outs if n_tensors > 1 else outs[0])

    # ------------------------------------------------------------------

    def abort(self, exc: BaseException):
        """One rank failed — fail every pending and future collective so
        no rank blocks forever (the reference ends all ranks with
        SHUT_DOWN_ERROR, common.h:231, when a peer dies)."""
        with self._lock:
            if self._aborted is not None or self._shutdown:
                return
            self._aborted = exc
            self._fail_all_pending_locked(HorovodInternalError(
                f"a peer rank failed: {exc!r}"))
            # wake threads parked in the process-set removal barrier —
            # they re-check _aborted and surface the peer failure
            for ev in self._removal_events.values():
                ev.set()
            self._lock.notify_all()

    def shutdown(self):
        with self._lock:
            if self._shutdown:
                return
            self._shutdown = True
            # wake threads parked in the process-set removal barrier
            for ev in self._removal_events.values():
                ev.set()
            self._lock.notify_all()
        self._shutdown_done.wait(timeout=30)
        if self.multiproc:
            # stop beating (with a goodbye) BEFORE the controller's
            # fabric goes away, so a clean teardown never reads as a
            # missed-heartbeat death
            self._stop_heartbeat()
        if self._clock_sync is not None:
            self._clock_sync.stop()
            self._clock_sync = None
        if self._metrics_pusher is not None:
            # final snapshot so short jobs still land in the job-wide
            # /metrics aggregation
            self._metrics_pusher.stop()
            self._metrics_pusher = None
        if self.autotuner is not None:
            self.autotuner.close()


def _bfloat16_dtype():
    import ml_dtypes
    return np.dtype(ml_dtypes.bfloat16)

"""Core runtime: rank contexts, negotiation, fusion, dispatch.

TPU-native analogue of the reference's core
(``horovod/common/operations.cc`` BackgroundThreadLoop/RunLoopOnce +
``controller.cc`` ComputeResponseList):

* Each **rank** is a rank context bound to a device of the mesh.  On a
  TPU host one process drives all local chips, so ranks live as threads
  of one process (launcher) or as positions in an SPMD program — not as
  one OS process per accelerator the way CUDA forces.
* Rank threads **enqueue** tensors (EnqueueTensorAllreduce analogue);
  a single background thread negotiates readiness (a tensor executes
  only when every participating rank has submitted it — the exact
  contract of controller.cc:74-474), **fuses** ready allreduces into
  buckets under the fusion threshold (FuseResponses,
  controller.cc:901-1080), and dispatches each bucket to a cached
  compiled XLA collective (ops/xla_ops.py).
* Completion flows back through async handles
  (torch/handle_manager.h analogue).

The in-process controller needs no gatherv/bcast wire protocol: the
negotiation table *is* shared memory.  Multi-host deployments layer a
store-based controller on top (runner/), with this same engine running
per host.
"""

import logging
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from ..common import env as env_mod
from ..common.exceptions import (
    DuplicateNameError,
    HorovodInternalError,
    HorovodInitError,
    StalledTensorError,
    TensorShapeMismatchError,
)
from .message import ReduceOp, Request, RequestType
from .handles import Handle, HandleManager

logger = logging.getLogger("horovod_tpu")


@dataclass
class Submission:
    """One rank's (possibly grouped) tensor submission — the engine-side
    TensorTableEntry (reference common.h TensorTableEntry)."""
    rank: int
    request: Request
    names: List[str]
    payloads: List[np.ndarray]          # host buffers, one per tensor
    handle: Handle
    enq_time: float = field(default_factory=time.monotonic)


class NegotiationEntry:
    """Readiness table row (reference controller.cc:1115-1140
    IncrementTensorCount)."""

    __slots__ = ("key", "subs", "first_time")

    def __init__(self, key):
        self.key = key
        self.subs: Dict[int, Submission] = {}
        self.first_time = time.monotonic()


class ProcessSetState:
    """Runtime state for one process set (reference process_set.h:26-84:
    controller + tensor queue + joined state per set)."""

    def __init__(self, ps_id, ranks, executor):
        self.id = ps_id
        self.ranks = list(ranks)            # global ranks, sorted
        self.index = {r: i for i, r in enumerate(self.ranks)}
        self.executor = executor
        self.pending: "OrderedDict[str, NegotiationEntry]" = OrderedDict()
        self.joined = set()                 # ranks that called join()
        self.last_joined = -1
        self.join_waiters: Dict[int, Handle] = {}

    @property
    def size(self):
        return len(self.ranks)


class Engine:
    """The per-process core runtime (reference HorovodGlobalState +
    BackgroundThreadLoop, global_state.h:39-126, operations.cc:409-749).
    """

    def __init__(self, num_ranks, devices, config=None, topology=None,
                 timeline=None):
        from ..ops.xla_ops import MeshExecutor

        self.config = config or env_mod.Config()
        self.num_ranks = num_ranks
        self.devices = list(devices)
        self.topology = topology
        self.handle_manager = HandleManager()
        self.timeline = timeline

        self._lock = threading.Condition()
        self._shutdown = False
        self._aborted: Optional[BaseException] = None
        self._shutdown_done = threading.Event()

        self._MeshExecutor = MeshExecutor
        ps0 = ProcessSetState(
            0, range(num_ranks),
            MeshExecutor(self._devices_for(range(num_ranks)), num_ranks))
        self.process_sets: Dict[int, ProcessSetState] = {0: ps0}
        self._next_ps_id = 1

        self._stall_warned = set()
        self._thread = threading.Thread(
            target=self._background_loop, name="horovod_tpu-engine",
            daemon=True)
        self._thread.start()

    # ------------------------------------------------------------------
    # process sets

    def _devices_for(self, ranks):
        nd = len(self.devices)
        return [self.devices[r % nd] for r in ranks]

    def add_process_set(self, ranks) -> int:
        ranks = sorted(set(int(r) for r in ranks))
        if any(r < 0 or r >= self.num_ranks for r in ranks):
            raise ValueError(f"process set ranks {ranks} out of range")
        with self._lock:
            for ps in self.process_sets.values():
                if ps.ranks == ranks:
                    raise ValueError(
                        f"process set with ranks {ranks} already exists "
                        f"(id {ps.id})")
            ps_id = self._next_ps_id
            self._next_ps_id += 1
            self.process_sets[ps_id] = ProcessSetState(
                ps_id, ranks,
                self._MeshExecutor(self._devices_for(ranks), len(ranks)))
            return ps_id

    def remove_process_set(self, ps_id) -> bool:
        if ps_id == 0:
            raise ValueError("cannot remove the global process set")
        with self._lock:
            ps = self.process_sets.pop(ps_id, None)
            if ps is None:
                return False
            for entry in ps.pending.values():
                for sub in entry.subs.values():
                    sub.handle.set_error(HorovodInternalError(
                        f"process set {ps_id} removed while "
                        f"{entry.key[0]} pending"))
            return True

    def get_process_set(self, ps_id) -> ProcessSetState:
        ps = self.process_sets.get(ps_id)
        if ps is None:
            raise ValueError(f"unknown process set id {ps_id}")
        return ps

    def process_set_ranks(self, ps_id):
        return list(self.get_process_set(ps_id).ranks)

    # ------------------------------------------------------------------
    # submission (rank threads)

    def submit(self, sub: Submission) -> Handle:
        """EnqueueTensorAllreduce/... analogue (operations.cc:1408-2060):
        register the submission in the negotiation table; the background
        thread executes it once all participating ranks arrive."""
        with self._lock:
            if self._shutdown:
                raise HorovodInitError("horovod_tpu has been shut down")
            if self._aborted is not None:
                sub.handle.set_error(HorovodInternalError(
                    f"horovod_tpu aborted: {self._aborted!r}"))
                return sub.handle
            ps = self.get_process_set(sub.request.process_set_id)
            if sub.rank not in ps.index:
                raise ValueError(
                    f"rank {sub.rank} is not part of process set {ps.id}")
            key = self._negotiation_key(sub)
            entry = ps.pending.get(key)
            if entry is None:
                entry = NegotiationEntry(key)
                ps.pending[key] = entry
            if sub.rank in entry.subs:
                sub.handle.set_error(DuplicateNameError(
                    f"tensor {sub.names} submitted twice by rank "
                    f"{sub.rank} before completion"))
                return sub.handle
            entry.subs[sub.rank] = sub
            if self.timeline is not None:
                self.timeline.negotiate_start(sub.names[0],
                                              sub.request.request_type.name)
            self._lock.notify_all()
        return sub.handle

    def join(self, rank, ps_id=0) -> Handle:
        """Join op (operations.cc:1991-2024): the rank stops submitting;
        pending/future allreduces treat it as a zero contributor.  The
        handle completes when every rank of the set has joined, with
        result = the last rank to join (message.h last_joined_rank)."""
        handle = Handle()
        with self._lock:
            if self._shutdown:
                raise HorovodInitError("horovod_tpu has been shut down")
            if self._aborted is not None:
                handle.set_error(HorovodInternalError(
                    f"horovod_tpu aborted: {self._aborted!r}"))
                return handle
            ps = self.get_process_set(ps_id)
            if rank in ps.joined:
                handle.set_error(HorovodInternalError(
                    f"rank {rank} already joined"))
                return handle
            ps.joined.add(rank)
            ps.last_joined = rank
            ps.join_waiters[rank] = handle
            self._lock.notify_all()
        return handle

    def _negotiation_key(self, sub: Submission):
        return (sub.request.request_type, tuple(sub.names))

    # ------------------------------------------------------------------
    # background loop

    def _background_loop(self):
        cycle = max(self.config.cycle_time_ms, 0.05) / 1000.0
        while True:
            with self._lock:
                if not self._shutdown:
                    self._lock.wait(timeout=cycle)
                if self._shutdown:
                    self._fail_all_pending_locked(
                        HorovodInitError("shutdown during pending collective"))
                    break
                work = self._collect_ready_locked()
                self._check_stalls_locked()
            for ps, batch in work:
                self._execute_batch(ps, batch)
        self._shutdown_done.set()

    def _collect_ready_locked(self):
        """ComputeResponseList analogue: pull fully-ready negotiation
        entries (readiness = submissions from every non-joined rank of
        the set, controller.cc:269-327 for the joined case) and resolve
        join barriers."""
        work = []
        for ps in list(self.process_sets.values()):
            # join barrier: every rank joined -> release all waiters
            if ps.joined and len(ps.joined) == ps.size:
                for r, h in ps.join_waiters.items():
                    h.set_result(ps.last_joined)
                ps.join_waiters.clear()
                ps.joined.clear()
                ps.last_joined = -1
            ready = []
            for key in list(ps.pending.keys()):
                entry = ps.pending[key]
                needed = [r for r in ps.ranks if r not in ps.joined]
                if all(r in entry.subs for r in needed):
                    ready.append(entry)
                    del ps.pending[key]
                    self._stall_warned.discard((ps.id,) + key)
            if ready:
                work.append((ps, ready))
        return work

    def _check_stalls_locked(self):
        """Stall inspector (reference stall_inspector.{h,cc}): warn when
        a tensor is ready on some-but-not-all ranks past the warning
        time; error everyone past the shutdown time."""
        if self.config.stall_check_disable:
            return
        now = time.monotonic()
        for ps in self.process_sets.values():
            for key, entry in list(ps.pending.items()):
                age = now - entry.first_time
                wkey = (ps.id,) + key
                if (age > self.config.stall_warning_secs
                        and wkey not in self._stall_warned):
                    missing = [r for r in ps.ranks
                               if r not in entry.subs and r not in ps.joined]
                    logger.warning(
                        "One or more tensors were submitted to be reduced "
                        "by some ranks but not all: %s stalled for %.0fs "
                        "(missing ranks: %s)", key[1], age, missing)
                    self._stall_warned.add(wkey)
                if (self.config.stall_shutdown_secs > 0
                        and age > self.config.stall_shutdown_secs):
                    del ps.pending[key]
                    for sub in entry.subs.values():
                        sub.handle.set_error(StalledTensorError(
                            f"tensor {key[1]} stalled for {age:.0f}s"))

    def _fail_all_pending_locked(self, exc):
        for ps in self.process_sets.values():
            for entry in ps.pending.values():
                for sub in entry.subs.values():
                    sub.handle.set_error(exc)
            ps.pending.clear()
            for h in ps.join_waiters.values():
                h.set_error(exc)
            ps.join_waiters.clear()

    # ------------------------------------------------------------------
    # validation + fusion + execution (background thread)

    def _execute_batch(self, ps: ProcessSetState, entries):
        """PerformOperation analogue (operations.cc:277-334): validate,
        fuse allreduce entries into buckets, run each response."""
        runnable = []
        for entry in entries:
            err = self._validate(ps, entry)
            if err is not None:
                for sub in entry.subs.values():
                    sub.handle.set_error(err)
                continue
            runnable.append(entry)

        buckets = self._fuse(ps, runnable)
        for bucket in buckets:
            try:
                self._run_bucket(ps, bucket)
            except Exception as exc:  # noqa: BLE001 — deliver to waiters
                logger.exception("collective execution failed")
                wrapped = exc if isinstance(exc, HorovodInternalError) \
                    else HorovodInternalError(str(exc))
                for entry in bucket:
                    for sub in entry.subs.values():
                        sub.handle.set_error(wrapped)

    def _validate(self, ps, entry) -> Optional[Exception]:
        """Cross-rank consistency checks, mirroring ConstructResponse
        (controller.cc:496-843): dtype, shape, op, scale factors and
        root must agree across ranks."""
        subs = [entry.subs[r] for r in ps.ranks if r in entry.subs]
        first = subs[0].request
        rt = first.request_type
        for sub in subs[1:]:
            r = sub.request
            if r.dtype != first.dtype:
                return TensorShapeMismatchError(
                    f"Mismatched data types for {first.tensor_name}: rank "
                    f"{sub.rank} sent {r.dtype}, rank {subs[0].rank} sent "
                    f"{first.dtype}")
            if r.reduce_op != first.reduce_op:
                return TensorShapeMismatchError(
                    f"Mismatched reduce ops for {first.tensor_name}")
            if (r.prescale_factor != first.prescale_factor
                    or r.postscale_factor != first.postscale_factor):
                return TensorShapeMismatchError(
                    f"Mismatched prescale/postscale for {first.tensor_name}")
            if rt == RequestType.BROADCAST and r.root_rank != first.root_rank:
                return TensorShapeMismatchError(
                    f"Mismatched broadcast root for {first.tensor_name}: "
                    f"{r.root_rank} vs {first.root_rank}")
            if rt in (RequestType.ALLREDUCE, RequestType.ADASUM,
                      RequestType.BROADCAST, RequestType.REDUCESCATTER):
                if r.shape != first.shape:
                    return TensorShapeMismatchError(
                        f"Mismatched shapes for {first.tensor_name}: rank "
                        f"{sub.rank} sent {r.shape}, rank {subs[0].rank} "
                        f"sent {first.shape}")
            elif rt in (RequestType.ALLGATHER, RequestType.ALLTOALL):
                if tuple(r.shape[1:]) != tuple(first.shape[1:]):
                    return TensorShapeMismatchError(
                        f"Mismatched non-first dimensions for "
                        f"{first.tensor_name}")
            if rt == RequestType.ALLTOALL:
                if r.splits is None or len(r.splits) != ps.size:
                    return TensorShapeMismatchError(
                        f"alltoall splits for {first.tensor_name} must "
                        f"have one entry per rank of the process set")
                if sum(r.splits) != (r.shape[0] if r.shape else 0):
                    return TensorShapeMismatchError(
                        f"alltoall splits for {first.tensor_name} must sum "
                        f"to the first dimension")
        if rt == RequestType.ALLTOALL:
            r0 = first
            if r0.splits is None or len(r0.splits) != ps.size or \
                    sum(r0.splits) != (r0.shape[0] if r0.shape else 0):
                return TensorShapeMismatchError(
                    f"alltoall splits invalid for {first.tensor_name}")
        if len(subs) < ps.size and rt not in (
                RequestType.ALLREDUCE, RequestType.ADASUM):
            return HorovodInternalError(
                f"rank(s) {[r for r in ps.ranks if r not in entry.subs]} "
                f"joined; {rt.name} does not support join")
        return None

    def _fuse(self, ps, entries):
        """FuseResponses analogue (controller.cc:901-1080): pack
        consecutive ready allreduce entries with matching
        (dtype, op, scales) into buckets up to the fusion threshold.
        Non-allreduce ops execute one-per-bucket."""
        threshold = self.config.fusion_threshold_bytes
        buckets, cur, cur_bytes, cur_sig = [], [], 0, None
        for entry in entries:
            first = next(iter(entry.subs.values()))
            rt = first.request.request_type
            if rt not in (RequestType.ALLREDUCE, RequestType.ADASUM):
                if cur:
                    buckets.append(cur)
                    cur, cur_bytes, cur_sig = [], 0, None
                buckets.append([entry])
                continue
            sig = (rt, first.request.dtype, first.request.reduce_op,
                   first.request.prescale_factor,
                   first.request.postscale_factor)
            nbytes = sum(p.nbytes for p in first.payloads)
            if cur and (sig != cur_sig
                        or cur_bytes + nbytes > threshold):
                buckets.append(cur)
                cur, cur_bytes = [], 0
            cur.append(entry)
            cur_bytes += nbytes
            cur_sig = sig
        if cur:
            buckets.append(cur)
        return buckets

    def _run_bucket(self, ps, bucket):
        first = next(iter(bucket[0].subs.values()))
        rt = first.request.request_type
        if self.timeline is not None:
            names = [n for e in bucket for s in (next(iter(e.subs.values())),)
                     for n in s.names]
            self.timeline.op_start(names, rt.name)
        try:
            if rt in (RequestType.ALLREDUCE, RequestType.ADASUM):
                self._run_allreduce_bucket(ps, bucket)
            elif rt == RequestType.ALLGATHER:
                self._run_allgather(ps, bucket[0])
            elif rt == RequestType.BROADCAST:
                self._run_broadcast(ps, bucket[0])
            elif rt == RequestType.ALLTOALL:
                self._run_alltoall(ps, bucket[0])
            elif rt == RequestType.REDUCESCATTER:
                self._run_reducescatter(ps, bucket[0])
            elif rt == RequestType.BARRIER:
                for sub in bucket[0].subs.values():
                    sub.handle.set_result(None)
            else:
                raise HorovodInternalError(f"unhandled op {rt}")
        finally:
            if self.timeline is not None:
                self.timeline.op_end()

    def _run_allreduce_bucket(self, ps, bucket):
        """Fused allreduce: one flat buffer per rank for the whole
        bucket, one compiled collective, then unpack — the
        MemcpyInFusionBuffer / MemcpyOutFusionBuffer pattern
        (collective_operations.h:38-343) with numpy packing instead of
        a batched-D2D CUDA kernel."""
        first = next(iter(bucket[0].subs.values())).request
        op = first.reduce_op
        if first.request_type == RequestType.ADASUM:
            op = ReduceOp.ADASUM
        dtype = np.dtype(first.dtype) if first.dtype != "bfloat16" else \
            _bfloat16_dtype()
        # layout: [(entry, tensor_idx, offset, size, shape)]
        layout = []
        offset = 0
        for entry in bucket:
            ref_sub = next(iter(entry.subs.values()))
            for i, p in enumerate(ref_sub.payloads):
                layout.append((entry, i, offset, int(p.size), p.shape))
                offset += int(p.size)
        total = offset
        rows = []
        for r in ps.ranks:
            buf = np.zeros(total, dtype=dtype)
            for entry, i, off, size, _ in layout:
                sub = entry.subs.get(r)
                if sub is not None:      # joined ranks contribute zeros
                    buf[off:off + size] = sub.payloads[i].ravel()
            rows.append(buf)
        results = ps.executor.allreduce(
            rows, op, first.prescale_factor, first.postscale_factor)
        per_entry_results = {}
        for entry, i, off, size, shape in layout:
            for r, sub in entry.subs.items():
                out = results[ps.index[r]][off:off + size].reshape(shape)
                per_entry_results.setdefault((id(entry), r), []).append(out)
        for entry in bucket:
            for r, sub in entry.subs.items():
                outs = per_entry_results[(id(entry), r)]
                sub.handle.set_result(
                    outs if len(sub.payloads) > 1 else outs[0])

    def _run_allgather(self, ps, entry):
        """Allgather with per-rank first-dim sizes: pad to max rows
        (the reference exchanges shapes during negotiation and sizes the
        fused buffer accordingly, controller.cc:901-1080)."""
        subs = {r: entry.subs[r] for r in ps.ranks}
        n_tensors = len(next(iter(subs.values())).payloads)
        results_per_rank = {r: [] for r in ps.ranks}
        for i in range(n_tensors):
            dim0 = [int(subs[r].payloads[i].shape[0]) if subs[r].payloads[i].ndim
                    else 1 for r in ps.ranks]
            rest = tuple(next(iter(subs.values())).payloads[i].shape[1:])
            max_d = max(dim0) if dim0 else 0
            rest_n = int(np.prod(rest, dtype=np.int64)) if rest else 1
            rows = []
            for r in ps.ranks:
                p = subs[r].payloads[i]
                flat = np.ravel(p)
                buf = np.zeros(max_d * rest_n, dtype=p.dtype)
                buf[:flat.size] = flat
                rows.append(buf)
            gathered = ps.executor.allgather(rows, dim0, rest)
            for r in ps.ranks:
                results_per_rank[r].append(gathered[ps.index[r]])
        for r, sub in subs.items():
            outs = results_per_rank[r]
            sub.handle.set_result(outs if n_tensors > 1 else outs[0])

    def _run_broadcast(self, ps, entry):
        subs = {r: entry.subs[r] for r in ps.ranks}
        first = next(iter(subs.values()))
        root = first.request.root_rank
        root_pos = ps.index.get(root)
        if root_pos is None:
            for sub in subs.values():
                sub.handle.set_error(HorovodInternalError(
                    f"broadcast root {root} not in process set {ps.id}"))
            return
        n_tensors = len(first.payloads)
        results_per_rank = {r: [] for r in ps.ranks}
        for i in range(n_tensors):
            shape = first.payloads[i].shape
            rows = [subs[r].payloads[i].ravel() for r in ps.ranks]
            out = ps.executor.broadcast(rows, root_pos)
            for r in ps.ranks:
                results_per_rank[r].append(
                    out[ps.index[r]].reshape(shape))
        for r, sub in subs.items():
            outs = results_per_rank[r]
            sub.handle.set_result(outs if n_tensors > 1 else outs[0])

    def _run_alltoall(self, ps, entry):
        subs = {r: entry.subs[r] for r in ps.ranks}
        first = next(iter(subs.values()))
        rest = tuple(first.payloads[0].shape[1:])
        rest_n = int(np.prod(rest, dtype=np.int64)) if rest else 1
        splits = [list(subs[r].request.splits) for r in ps.ranks]
        R = ps.size
        max_seg = max((s for sp in splits for s in sp), default=0)
        rows = []
        for pos, r in enumerate(ps.ranks):
            p = subs[r].payloads[0]
            flat = np.ravel(p)
            buf = np.zeros(R * max_seg * rest_n, dtype=p.dtype)
            off = 0
            for j in range(R):
                seg = splits[pos][j] * rest_n
                buf[j * max_seg * rest_n: j * max_seg * rest_n + seg] = \
                    flat[off:off + seg]
                off += seg
            rows.append(buf)
        results, recv_splits = ps.executor.alltoall(rows, splits, rest)
        for pos, r in enumerate(ps.ranks):
            subs[r].handle.set_result(
                results[pos], extra=np.array(recv_splits[pos], dtype=np.int32))

    def _run_reducescatter(self, ps, entry):
        subs = {r: entry.subs[r] for r in ps.ranks}
        first = next(iter(subs.values()))
        req = first.request
        op = req.reduce_op
        shape = first.payloads[0].shape
        d0 = int(shape[0]) if shape else 1
        rest = tuple(shape[1:])
        rest_n = int(np.prod(rest, dtype=np.int64)) if rest else 1
        R = ps.size
        chunks = ps.executor.chunk_sizes(d0, R)
        max_chunk = max(chunks) if chunks else 0
        offsets = np.cumsum([0] + chunks[:-1])
        rows = []
        for r in ps.ranks:
            flat = np.ravel(subs[r].payloads[0])
            buf = np.zeros(R * max_chunk * rest_n, dtype=flat.dtype)
            for j in range(R):
                src = offsets[j] * rest_n
                dst = j * max_chunk * rest_n
                buf[dst:dst + chunks[j] * rest_n] = \
                    flat[src:src + chunks[j] * rest_n]
            rows.append(buf)
        results = ps.executor.reducescatter(
            rows, d0, rest, op, req.prescale_factor, req.postscale_factor)
        for r in ps.ranks:
            subs[r].handle.set_result(results[ps.index[r]])

    # ------------------------------------------------------------------

    def abort(self, exc: BaseException):
        """One rank failed — fail every pending and future collective so
        no rank blocks forever (the reference ends all ranks with
        SHUT_DOWN_ERROR, common.h:231, when a peer dies)."""
        with self._lock:
            if self._aborted is not None or self._shutdown:
                return
            self._aborted = exc
            self._fail_all_pending_locked(HorovodInternalError(
                f"a peer rank failed: {exc!r}"))
            self._lock.notify_all()

    def shutdown(self):
        with self._lock:
            if self._shutdown:
                return
            self._shutdown = True
            self._lock.notify_all()
        self._shutdown_done.wait(timeout=30)


def _bfloat16_dtype():
    import ml_dtypes
    return np.dtype(ml_dtypes.bfloat16)

"""End-to-end step integrity: wire checksums, divergence sentinel,
commit-anchored rollback (docs/fault_tolerance.md "Silent data
corruption").

The stack survives every LOUD failure — worker death, coordinator
death, aggregator death, host revocation — but a flipped bit on the
quantized wire, a bad host producing subtly wrong reductions, or a
torn spill file would be absorbed into the model without a trace, and
the per-hop int4/int8 codec (EQuARX, arXiv:2506.17615) widens the
blast radius: one corrupted code byte dequantizes into a whole block
of wrong gradients.  Horovod's coordinated-collective design
(arXiv:1802.05799) gives the natural choke point — every byte that
can diverge replicas crosses the fused-collective seam — so integrity
is enforced there, end to end:

* **Wire checksums** — a cheap xor-folded 64-bit digest
  (:func:`digest64`, one SIMD pass at memory bandwidth) is computed
  over each fused bucket's payload at submit/encode time and
  re-verified at decode on both collective paths.  On the engine path
  detection feeds a 1-element MIN allreduce "implicated-rank vote"
  (the bypass-vote shape, core/engine._integrity_vote) so EVERY
  process quarantines the step before any rank's optimizer applies
  the corrupt update — a single-rank raise would let its peers commit
  the garbage first.
* **Divergence sentinel** — every ``HOROVOD_INTEGRITY_SENTINEL_STEPS``
  ranks fold their params into a 64-bit fingerprint and agree via one
  tiny MIN/MAX allreduce (:class:`StepSentinel`), so replica drift
  from an SDC, a mis-latched wire flip or EF-residual desync is
  detected within a bounded step budget; always-on nonfinite /
  grad-norm guards ride the same class.
* **Commit-anchored rollback** — every detection raises a
  :class:`StepIntegrityError` (a ``HorovodInternalError``), which the
  elastic retry loop (common/elastic.run_fn) answers by restoring the
  last commit and re-rendezvousing — the job replays, it does not
  die.  ``Engine.quarantine_step`` resets the bypass arm, the
  autotuner's in-flight sample and the compiled path's EF residuals
  so no stale step state survives into the replay.
* **Eviction scoring** — repeated detections implicating the same
  rank (:class:`IntegrityChecker` scoreboard,
  ``HOROVOD_INTEGRITY_EVICT_AFTER``) escalate to
  :class:`HostEvictionError` on the hosting process: the worker exits
  instead of restoring, the elastic driver records the slot failure
  and blacklists the host — a genuinely bad host is evicted, not
  endlessly retried.

Torn-write hardening for checkpoints and elastic spills rides the CRC
trailer helpers (:func:`append_crc_trailer` /
:func:`strip_crc_trailer`); ``corrupt_spill`` chaos events exercise
them deterministically (chaos/plan.py).
"""

import logging
import weakref

import numpy as np

from ..common.exceptions import HorovodInternalError

logger = logging.getLogger("horovod_tpu")

#: Process-wide registry of objects holding wire state (EF residuals):
#: the frontends' updaters and the compiled reducers register
#: themselves so a step quarantine can reset EVERY path's residuals —
#: the in-place rollback (restore + resync, no elastic reset()) never
#: reaches the frontends' own reset_wire_state seam, and a residual
#: mutated by the quarantined step's submit would otherwise survive
#: into the replay and diverge it from the clean trajectory.
_WIRE_STATE_REGISTRY = weakref.WeakSet()


def register_wire_state(obj):
    """Register an object exposing ``reset_wire_state()`` for
    quarantine-time residual resets (weakly referenced)."""
    if hasattr(obj, "reset_wire_state"):
        _WIRE_STATE_REGISTRY.add(obj)
    return obj


def reset_registered_wire_state():
    """Reset every registered holder's wire state (engine
    quarantine_step; resilient — hygiene must not mask detection)."""
    for obj in list(_WIRE_STATE_REGISTRY):
        try:
            obj.reset_wire_state()
        except Exception:  # noqa: BLE001
            logger.exception("integrity: wire-state reset failed on %r",
                             type(obj).__name__)

_M64 = (1 << 64) - 1
_M63 = (1 << 63) - 1
_FNV_PRIME = 0x100000001b3
_FNV_SEED = 0xcbf29ce484222325

#: The "no corruption here" value of the implicated-rank MIN vote —
#: exact in float32 and larger than any real global rank, so
#: ``min(votes) < OK_VOTE`` names the lowest implicated rank on every
#: process at once (core/engine._integrity_vote).
OK_VOTE = float(1 << 24)


# ---------------------------------------------------------------------------
# digests


_SUM_MIX = 0x9E3779B97F4A7C15


def _fold(b):
    """Fold a uint8 vector into 64 bits: the xor AND the wrapping sum
    of its uint64 words (plus the little-endian tail).  Two vectorized
    passes at memory bandwidth.  The xor flips for any single flipped
    bit; the sum breaks the xor's pairwise cancellation (N identical
    words xor to 0 for even N — a scaled-duplicate payload must not
    collide with another).  Content-pure: an unaligned view falls back
    to a byte-identical copy, never to a different scheme — the
    submit-time digest of a payload MUST equal the decode-time digest
    of its packed slice."""
    n8 = (b.size // 8) * 8
    x = s = 0
    if n8:
        body = b[:n8]
        try:
            w = body.view(np.uint64)
        except ValueError:          # unaligned slice offset
            w = np.frombuffer(body.tobytes(), np.uint64)
        x = int(np.bitwise_xor.reduce(w))
        s = int(np.add.reduce(w))   # wraps mod 2**64
    if n8 != b.size:
        tail = int.from_bytes(b[n8:].tobytes(), "little")
        x ^= tail
        s = (s + tail) & _M64
    return x ^ ((s * _SUM_MIX) & _M64)


def digest64(buffers) -> int:
    """64-bit content digest of a sequence of array-likes (numpy
    arrays of any dtype, or bytes).  Per-buffer folds are mixed with
    an FNV-style multiply so buffer order and lengths matter; the cost
    is two vectorized passes per buffer — cheap enough for the
    dispatch loop, which is what lets the wire checksums default on."""
    h = _FNV_SEED
    for a in buffers:
        if isinstance(a, (bytes, bytearray, memoryview)):
            b = np.frombuffer(a, dtype=np.uint8)
        else:
            b = np.ascontiguousarray(a).reshape(-1).view(np.uint8)
        h = ((h ^ _fold(b)) * _FNV_PRIME + b.size + 1) & _M64
    return h


def _iter_leaves(tree):
    if tree is None:
        return
    if isinstance(tree, dict):
        for k in sorted(tree, key=str):
            yield from _iter_leaves(tree[k])
    elif isinstance(tree, (list, tuple)):
        for v in tree:
            yield from _iter_leaves(v)
    else:
        yield tree


def fold_fingerprint(tree) -> int:
    """Fold a (possibly nested dict/list/tuple) pytree of arrays into
    a 63-bit fingerprint — the divergence sentinel's per-rank replica
    identity.  Dict keys iterate sorted, so the fold is a pure
    function of the tree's CONTENT (hvdlint determinism rules)."""
    return digest64(np.asarray(leaf) for leaf in _iter_leaves(tree)) \
        & _M63


# ---------------------------------------------------------------------------
# errors


class StepIntegrityError(HorovodInternalError):
    """Base of every integrity detection.  A ``HorovodInternalError``
    on purpose: the elastic retry loop answers it by restoring the
    last commit and replaying — detection quarantines the step, it
    never kills the job (docs/fault_tolerance.md)."""

    #: rollback-reason label for horovod_integrity_rollbacks_total
    reason = "integrity"
    #: set on eviction-grade errors: run_fn re-raises instead of
    #: restoring, so the process dies and the driver blacklists it
    evict = False
    #: integrity detections leave the mesh HEALTHY — the verdict was
    #: unanimous (the implicated-rank vote) and every engine survived
    #: delivering it — so the elastic retry loop rolls back in place:
    #: restore + resync, no mesh teardown / re-rendezvous (run_fn)
    quarantine = True


class WireIntegrityError(StepIntegrityError):
    """A wire/payload checksum mismatch: the bytes a rank encoded are
    not the bytes the collective consumed (or the peers' vote
    implicated a rank).  Carries the implicated global ``rank``."""

    reason = "wire_checksum"

    def __init__(self, message, rank=None, site=None):
        super().__init__(message)
        self.rank = rank
        self.site = site


class ReplicaDivergenceError(StepIntegrityError):
    """The divergence sentinel's MIN/MAX fingerprints disagree:
    replicas no longer hold identical params.  ``suspects`` names the
    minority-fingerprint global ranks (empty when indeterminate, e.g.
    a 1-vs-1 split)."""

    reason = "divergence"

    def __init__(self, message, suspects=()):
        super().__init__(message)
        self.suspects = tuple(suspects)


class NonFiniteUpdateError(StepIntegrityError):
    """The always-on update guard found a nonfinite (or norm-bound
    violating) gradient/update before the optimizer applied it."""

    reason = "nonfinite"


class HostEvictionError(StepIntegrityError):
    """Repeated integrity detections implicated a rank THIS process
    hosts: the elastic retry loop re-raises (never restores), the
    worker exits, and the driver's existing blacklist verdict evicts
    the host (docs/fault_tolerance.md "Silent data corruption")."""

    reason = "eviction"
    evict = True

    def __init__(self, message, rank=None):
        super().__init__(message)
        self.rank = rank


# ---------------------------------------------------------------------------
# bucket-scoped wire watches (engine dispatch)


class BucketWatch:
    """Per-bucket wire-checksum scope: the dispatch path registers
    each hop's actual wire buffers right after encode (codes + scales
    on quantized wires, the 16-bit cast on cast wires, the raw rows on
    f32) and :meth:`scan` re-verifies them at decode, returning the
    lowest implicated global rank plus a message naming the bucket,
    the hop and the wire."""

    __slots__ = ("label", "watches")

    def __init__(self, label):
        self.label = label
        self.watches = []

    @staticmethod
    def _bufs(row):
        return row if isinstance(row, (list, tuple)) else (row,)

    def watch(self, site, hop, wire, rows, ranks):
        """Digest one hop's per-rank wire rows (each row an array or a
        tuple of arrays, e.g. (codes, scales))."""
        fps = [digest64(self._bufs(r)) for r in rows]
        self.watches.append((site, hop, wire, rows, list(ranks), fps))

    def scan(self):
        """Re-verify every watch; returns ``(bad_rank, message)`` for
        the lowest corrupted global rank, or ``(None, None)``."""
        bad, msg = None, None
        for site, hop, wire, rows, ranks, fps in self.watches:
            for i, (row, fp) in enumerate(zip(rows, fps)):
                if digest64(self._bufs(row)) == fp:
                    continue
                rank = ranks[i] if i < len(ranks) else -1
                if bad is None or rank < bad:
                    bad = rank
                    msg = (
                        f"wire checksum mismatch in bucket "
                        f"{self.label!r} (site {site}, hop {hop}, "
                        f"wire {wire or 'f32'}): global rank {rank}'s "
                        f"encoded payload changed between encode and "
                        f"decode")
        return bad, msg


class IntegrityChecker:
    """Per-engine integrity state: the detection scoreboard that
    escalates repeated detections of the same rank into the driver's
    blacklist verdict (``HOROVOD_INTEGRITY_EVICT_AFTER``, 0 = never
    evict)."""

    def __init__(self, evict_after=3):
        self.evict_after = int(evict_after)
        self.detections = {}

    def record_detection(self, rank) -> bool:
        """Score one detection against ``rank``; True once the rank
        crossed the eviction threshold."""
        if rank is None:
            return False
        n = self.detections.get(rank, 0) + 1
        self.detections[rank] = n
        return self.evict_after > 0 and n >= self.evict_after


# ---------------------------------------------------------------------------
# divergence sentinel + update guards


def _quarantine_engine(reason, rank=None):
    """Best-effort engine quarantine from user-loop call sites (the
    sentinel/guards run outside the dispatch loop)."""
    try:
        from ..common import basics
        eng = basics._engine
        if eng is not None:
            eng.quarantine_step(reason, rank=rank)
    except Exception:  # noqa: BLE001 — hygiene must not mask detection
        logger.exception("integrity: engine quarantine failed")


def _sentinel_words(fp):
    """The MIN/MAX agreement payload: four uint16 components of the
    fingerprint and their negations, exact in float32 — [min(w_k)],
    [-max(w_k)] after one MIN allreduce (the bypass-vote shape; int64
    would silently truncate without x64)."""
    w = [float((fp >> (16 * k)) & 0xFFFF) for k in range(4)]
    return np.array(w + [-x for x in w], np.float32)


def sentinel_agree(fp, allreduce_min):
    """One agreement round: True when every rank's fingerprint words
    match (min == max component-wise)."""
    out = np.asarray(allreduce_min(_sentinel_words(fp)),
                     np.float32).reshape(-1)
    mins, maxs = out[:4], -out[4:]
    return bool(np.array_equal(mins, maxs))


class StepSentinel:
    """Training-loop divergence sentinel + always-on update guards.

    >>> sentinel = integrity.StepSentinel()
    >>> ...
    >>> sentinel.after_step(params, grads=grads)   # each step

    ``after_step`` guards the update (nonfinite everywhere;
    grad-norm when ``HOROVOD_INTEGRITY_MAX_GRAD_NORM`` > 0) and every
    ``HOROVOD_INTEGRITY_SENTINEL_STEPS`` (default 50) runs one
    fingerprint agreement round over the existing collective path.
    Divergence attributes the minority fingerprint via a tiny
    allgather and raises :class:`ReplicaDivergenceError`; rollback
    then rides the same commit-anchored path as a wire detection."""

    def __init__(self, every=None, max_grad_norm=None,
                 process_set=None, name="hvd.integrity.sentinel"):
        from ..common import env as env_mod
        self.every = env_mod.get_int(
            env_mod.HOROVOD_INTEGRITY_SENTINEL_STEPS, 50) \
            if every is None else int(every)
        self.max_grad_norm = env_mod.get_float(
            env_mod.HOROVOD_INTEGRITY_MAX_GRAD_NORM, 0.0) \
            if max_grad_norm is None else float(max_grad_norm)
        self.process_set = process_set
        self.name = name
        self.steps = 0
        self.checks = 0

    # -- guards --------------------------------------------------------------

    def guard_update(self, grads):
        """Nonfinite / grad-norm guard over a pytree of gradients (or
        updates) — always on, no collective, runs before the optimizer
        applies."""
        from .. import telemetry

        sq = 0.0
        for leaf in _iter_leaves(grads):
            a = np.asarray(leaf)
            if str(a.dtype) == "bfloat16":
                a = a.astype(np.float32)    # isfinite needs a real
                # IEEE dtype; f32 is the cheap exact widening
            elif not np.issubdtype(a.dtype, np.floating):
                continue
            if not np.all(np.isfinite(a)):
                telemetry.count_integrity_check("corrupt", "guard")
                _quarantine_engine(NonFiniteUpdateError.reason)
                raise NonFiniteUpdateError(
                    "integrity guard: nonfinite gradient/update "
                    "detected before the optimizer applied — "
                    "quarantining the step")
            if self.max_grad_norm > 0:
                # float64 ACCUMULATOR without materializing a float64
                # copy of the leaf (the norm guard is opt-in, but the
                # copies would double its memory traffic)
                sq += float(np.sum(np.square(a, dtype=np.float64)))
        if self.max_grad_norm > 0 and sq ** 0.5 > self.max_grad_norm:
            telemetry.count_integrity_check("corrupt", "guard")
            _quarantine_engine(NonFiniteUpdateError.reason)
            raise NonFiniteUpdateError(
                f"integrity guard: gradient norm {sq ** 0.5:.3e} "
                f"exceeds HOROVOD_INTEGRITY_MAX_GRAD_NORM="
                f"{self.max_grad_norm:.3e} — quarantining the step")
        telemetry.count_integrity_check("ok", "guard")

    # -- the sentinel round --------------------------------------------------

    def check(self, params):
        """One agreement round NOW (cadence ignored).  Returns the
        local fingerprint when replicas agree; raises
        :class:`ReplicaDivergenceError` when they do not."""
        import time as _time

        from .. import telemetry
        from ..ops import api
        from .message import ReduceOp

        t0 = _time.monotonic()
        fp = fold_fingerprint(params)
        kwargs = {} if self.process_set is None \
            else {"process_set": self.process_set}

        def _armin(arr):
            return api.allreduce(arr, op=ReduceOp.MIN,
                                 name=f"{self.name}.{self.checks}",
                                 **kwargs)

        agreed = sentinel_agree(fp, _armin)
        self.checks += 1
        telemetry.observe_sentinel_seconds(_time.monotonic() - t0)
        if agreed:
            telemetry.count_integrity_check("ok", "sentinel")
            return fp
        telemetry.count_integrity_check("corrupt", "sentinel")
        fps = api.allgather_object(
            fp, name=f"{self.name}.who.{self.checks}", **kwargs)
        counts = {}
        for v in fps:
            counts[v] = counts.get(v, 0) + 1
        majority = max(counts.values())
        # allgather order is process-set POSITION order: map minority
        # positions to GLOBAL ranks (misattributing a position as a
        # rank under a non-global set would score — and eventually
        # evict — an innocent host)
        set_ranks = list(getattr(self.process_set, "ranks", []) or []) \
            if self.process_set is not None else None
        suspects = tuple(
            set_ranks[i] if set_ranks and i < len(set_ranks) else i
            for i, v in enumerate(fps)
            if counts[v] < majority) if len(counts) > 1 else ()
        suspect = suspects[0] if suspects else None
        _quarantine_engine(ReplicaDivergenceError.reason, rank=suspect)
        raise ReplicaDivergenceError(
            f"integrity sentinel: replica param fingerprints diverged "
            f"({len(counts)} distinct values across {len(fps)} ranks; "
            f"minority rank(s) {list(suspects) or 'indeterminate'}) — "
            f"quarantining and rolling back to the last commit",
            suspects=suspects)

    def after_step(self, params, grads=None):
        """Per-step driver: guard the update, then run the agreement
        round on the sentinel cadence.  Returns True when a round
        ran."""
        if grads is not None:
            self.guard_update(grads)
        self.steps += 1
        if self.every > 0 and self.steps % self.every == 0:
            self.check(params)
            return True
        return False


# ---------------------------------------------------------------------------
# CRC trailers (torn-write hardening for checkpoints + elastic spills)

TRAILER_MAGIC = b"HVDCRC1\n"
_TRAILER_LEN = len(TRAILER_MAGIC) + 12    # magic + <QI>(length, crc32)


class TrailerCorruptionError(RuntimeError):
    """A CRC-trailed payload failed verification; ``kind`` is
    ``"truncated"`` (length mismatch — a torn write) or
    ``"mismatch"`` (CRC disagrees — bit rot / corruption)."""

    def __init__(self, message, kind):
        super().__init__(message)
        self.kind = kind


def crc_trailer(payload_len, crc):
    import struct
    return TRAILER_MAGIC + struct.pack("<QI", payload_len,
                                       crc & 0xFFFFFFFF)


def append_crc_trailer(data: bytes) -> bytes:
    """``payload + magic + (length, crc32)``.  Pickle readers stop at
    the end of their stream, so legacy loaders ignore the trailer —
    the format is forward and backward compatible."""
    import zlib
    return data + crc_trailer(len(data), zlib.crc32(data))


def has_crc_trailer(data: bytes) -> bool:
    return len(data) >= _TRAILER_LEN and \
        data[-_TRAILER_LEN:-12] == TRAILER_MAGIC


def strip_crc_trailer(data: bytes) -> bytes:
    """Verify-and-strip: returns the payload of a trailed blob after
    checking length and CRC (raises :class:`TrailerCorruptionError`
    naming truncation vs corruption), or the input unchanged when no
    trailer is present (legacy files — nothing to verify against)."""
    import struct
    import zlib

    if not has_crc_trailer(data):
        return data
    n, crc = struct.unpack("<QI", data[-12:])
    payload = data[:-_TRAILER_LEN]
    if n != len(payload):
        raise TrailerCorruptionError(
            f"CRC-trailed payload is torn: trailer records "
            f"{n} bytes, file holds {len(payload)}", kind="truncated")
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        raise TrailerCorruptionError(
            "CRC-trailed payload failed checksum verification "
            "(bit corruption in the stored bytes)", kind="mismatch")
    return payload

"""MXNet frontend — ``import horovod_tpu.mxnet as hvd`` (reference
``horovod/mxnet/__init__.py``: DistributedOptimizer :44, gluon
DistributedTrainer :124, broadcast_parameters :245).

The collective surface (allreduce/allgather/broadcast/alltoall/
reducescatter + topology queries) is framework-neutral and works
without mxnet installed; the three mxnet-dependent entry points
(DistributedOptimizer, DistributedTrainer, broadcast_parameters) are
resolved lazily and raise a clear ImportError when mxnet (EOL
upstream) is absent from the image.

STATUS: experimental — mxnet (EOL upstream) is not installable in
the CI image; the wrappers are exercised against a faithful in-process
stand-in (tests/test_mxnet_fake.py: DistributedOptimizer /
DistributedTrainer / broadcast_parameters incl. the deferred-init
hook, over the real engine), and the framework-neutral surface below
them is the same tested engine every other frontend uses.
"""

from ..common.basics import (  # noqa: F401
    init, shutdown, is_initialized,
    rank, size, local_rank, local_size, cross_rank, cross_size,
    is_homogeneous, mpi_threads_supported, mpi_built, gloo_built,
    nccl_built, ddl_built, ccl_built, cuda_built, rocm_built,
    xla_built, tpu_built, start_timeline, stop_timeline, dump_trace,
    metrics, start_metrics_server,
)
from ..common.exceptions import (  # noqa: F401
    HorovodInternalError, HostsUpdatedInterrupt,
)
from ..common.process_sets import (  # noqa: F401
    ProcessSet, add_process_set, remove_process_set, global_process_set,
)
from .compression import Compression  # noqa: F401
from .mpi_ops import (  # noqa: F401
    allreduce, allreduce_, grouped_allreduce, grouped_allreduce_,
    allgather, grouped_allgather,
    broadcast, broadcast_,
    alltoall,
    reducescatter, grouped_reducescatter,
    barrier, join, synchronize, poll,
    broadcast_object, allgather_object,
    Average, Sum, Adasum, Min, Max, Product,
)

_MXNET_NAMES = ("DistributedOptimizer", "DistributedTrainer",
                "broadcast_parameters")


def __getattr__(name):
    if name in _MXNET_NAMES:
        try:
            from . import _impl
        except ImportError as exc:
            raise ImportError(
                f"horovod_tpu.mxnet.{name} requires mxnet, which is not "
                "installed in this environment (mxnet is EOL; prefer the "
                "torch or tensorflow frontends)") from exc
        return getattr(_impl, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")

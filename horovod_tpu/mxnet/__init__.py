"""MXNet frontend (reference ``horovod/mxnet/__init__.py``:
DistributedOptimizer :44, gluon DistributedTrainer :124,
broadcast_parameters :245).

Gated: mxnet (EOL upstream) is not part of this image.  The surface is
declared so ported scripts fail with a clear message instead of an
AttributeError; the collective core they would bind to is the same
framework-agnostic ops/api used by the torch/TF frontends.
"""


def _require_mxnet():
    try:
        import mxnet  # noqa: F401
    except ImportError as exc:
        raise ImportError(
            "horovod_tpu.mxnet requires mxnet, which is not installed "
            "in this environment (mxnet is EOL; prefer the torch or "
            "tensorflow frontends)") from exc


def init(*args, **kwargs):
    from ..common.basics import init as _init
    return _init(*args, **kwargs)


def DistributedOptimizer(optimizer, *args, **kwargs):
    _require_mxnet()


def DistributedTrainer(params, optimizer, *args, **kwargs):
    _require_mxnet()


def broadcast_parameters(params, root_rank=0):
    _require_mxnet()

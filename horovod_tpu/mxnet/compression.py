"""Gradient compression for the MXNet binding (reference
``horovod/mxnet/compression.py``): fp16 wire compression over
NDArrays.  Requires mxnet only when actually compressing."""


class Compressor:
    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    @staticmethod
    def compress(tensor):
        import numpy as np
        # NDArray.dtype is a numpy type class — compare types, not str
        if np.issubdtype(tensor.dtype, np.floating) and \
                tensor.dtype != np.float16:
            return tensor.astype("float16"), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor.astype(ctx) if ctx is not None else tensor


class Compression:
    none = NoneCompressor
    fp16 = FP16Compressor

"""MXNet object collectives (reference ``horovod/mxnet/functions.py``:
broadcast_object :27, allgather_object :64).  Framework-neutral in
this build — objects pickle into uint8 tensors and ride the engine
path (ops/api.py), no mxnet NDArray staging needed."""

from ..ops.api import (  # noqa: F401
    allgather_object, broadcast_object,
)

"""MXNet collective ops (reference ``horovod/mxnet/mpi_ops.py``).

Thin wrappers over the framework-neutral ops/api: MXNet NDArrays stage
to host ndarrays (``.asnumpy()`` — see common/util.to_numpy) and the
fused collective runs as a compiled XLA program on the TPU mesh, the
same data plane the torch/TF frontends use.  The reference's
``priority`` argument ordered NDArray-engine pushes; the engine here
fuses whatever is concurrently pending, so priority is accepted for
API compatibility and ignored.
"""

from ..common.basics import (  # noqa: F401 — reference mpi_ops surface
    init, shutdown, is_initialized,
    rank, size, local_rank, local_size, cross_rank, cross_size,
    mpi_threads_supported,
    mpi_built, gloo_built, nccl_built, ddl_built, ccl_built,
    cuda_built, rocm_built, mpi_enabled, gloo_enabled,
    start_timeline, stop_timeline,
)
from ..common.process_sets import global_process_set
from ..common.util import get_average_backwards_compatibility_fun
from ..ops import api as _api
from ..ops.api import (  # noqa: F401
    Average, Sum, Adasum, Min, Max, Product,
    barrier, join, synchronize, poll,
    broadcast_object, allgather_object,
)

# reference mxnet/mpi_ops.py module constants: the ctypes handle to the
# compiled extension and its path — None/absent by design (pure-Python
# runtime, no dlopen)
MPI_MXNET_LIB_CTYPES = None
dll_path = None

handle_average_backwards_compatibility = \
    get_average_backwards_compatibility_fun(_api)


def allreduce(tensor, average=None, name=None, priority=0, op=None,
              prescale_factor=1.0, postscale_factor=1.0,
              process_set=global_process_set):
    return _api.allreduce(tensor, average, name, op, prescale_factor,
                          postscale_factor, process_set)


def allreduce_(tensor, average=None, name=None, priority=0, op=None,
               prescale_factor=1.0, postscale_factor=1.0,
               process_set=global_process_set):
    return _api.allreduce_(tensor, average, name, op, prescale_factor,
                           postscale_factor, process_set)


def grouped_allreduce(tensors, average=None, name=None, priority=0,
                      op=None, prescale_factor=1.0, postscale_factor=1.0,
                      process_set=global_process_set):
    return _api.grouped_allreduce(tensors, average, name, op,
                                  prescale_factor, postscale_factor,
                                  process_set)


def grouped_allreduce_(tensors, average=None, name=None, priority=0,
                       op=None, prescale_factor=1.0, postscale_factor=1.0,
                       process_set=global_process_set):
    return _api.grouped_allreduce_(tensors, average, name, op,
                                   prescale_factor, postscale_factor,
                                   process_set)


def allgather(tensor, name=None, priority=0,
              process_set=global_process_set):
    return _api.allgather(tensor, name, process_set)


def grouped_allgather(tensors, name=None, priority=0,
                      process_set=global_process_set):
    return _api.grouped_allgather(tensors, name, process_set)


def broadcast(tensor, root_rank, name=None, priority=0,
              process_set=global_process_set):
    return _api.broadcast(tensor, root_rank, name, process_set)


def broadcast_(tensor, root_rank, name=None, priority=0,
               process_set=global_process_set):
    return _api.broadcast_(tensor, root_rank, name, process_set)


def alltoall(tensor, splits=None, name=None, priority=0,
             process_set=global_process_set):
    out, recv_splits = _api.alltoall(tensor, splits, name, process_set)
    if splits is None:
        return out
    return out, recv_splits


def reducescatter(tensor, op=Average, name=None, priority=0,
                  prescale_factor=1.0, postscale_factor=1.0,
                  process_set=global_process_set):
    return _api.reducescatter(tensor, op, name, prescale_factor,
                              postscale_factor, process_set)


def grouped_reducescatter(tensors, op=Average, name=None, priority=0,
                          process_set=global_process_set):
    return _api.grouped_reducescatter(tensors, op, name,
                                      process_set=process_set)

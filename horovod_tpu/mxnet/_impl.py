"""MXNet-dependent pieces of the binding (reference
``horovod/mxnet/__init__.py:44-290``).  Imported lazily from
``horovod_tpu.mxnet`` so the rest of the surface works without mxnet
installed (mxnet is EOL and absent from most modern images)."""

import types
import warnings
from collections import OrderedDict

import mxnet as mx

from ..common import basics
from ..common.process_sets import global_process_set
from .compression import Compression
from .mpi_ops import allreduce_, broadcast_, grouped_allreduce_


def _split_list(xs, n_groups):
    n = max(1, (len(xs) + n_groups - 1) // n_groups)
    return [xs[i:i + n] for i in range(0, len(xs), n)]


class DistributedOptimizer(mx.optimizer.Optimizer):
    """Wraps an mx.optimizer.Optimizer: allreduces gradients before
    every update (reference mxnet/__init__.py:44-116)."""

    def __init__(self, optimizer, gradient_predivide_factor=1.0,
                 num_groups=0, process_set=global_process_set):
        self._optimizer = optimizer
        self._gradient_predivide_factor = gradient_predivide_factor
        self._num_groups = num_groups
        self._process_set = process_set

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def create_state(self, index, weight):
        return self._optimizer.create_state(index, weight)

    def create_state_multi_precision(self, index, weight):
        return self._optimizer.create_state_multi_precision(index, weight)

    def _do_allreduce(self, index, grad):
        if basics.size() == 1:
            return
        pre = 1.0 / self._gradient_predivide_factor
        post = self._gradient_predivide_factor
        if isinstance(index, (tuple, list)):
            if self._num_groups > 0:
                for i, (grads, indices) in enumerate(zip(
                        _split_list(grad, self._num_groups),
                        _split_list(index, self._num_groups))):
                    grouped_allreduce_(
                        tensors=grads, average=True,
                        name=f"{indices[0]}:{indices[-1]}", priority=-i,
                        prescale_factor=pre, postscale_factor=post,
                        process_set=self._process_set)
            else:
                for i in range(len(index)):
                    allreduce_(grad[i], average=True,
                               name=str(index[i]), priority=-i,
                               prescale_factor=pre, postscale_factor=post,
                               process_set=self._process_set)
        else:
            allreduce_(grad, average=True, name=str(index),
                       prescale_factor=pre, postscale_factor=post,
                       process_set=self._process_set)

    def update(self, index, weight, grad, state):
        self._do_allreduce(index, grad)
        self._optimizer.update(index, weight, grad, state)

    def update_multi_precision(self, index, weight, grad, state):
        self._do_allreduce(index, grad)
        self._optimizer.update_multi_precision(index, weight, grad, state)

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def set_lr_mult(self, args_lr_mult):
        self._optimizer.set_lr_mult(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self._optimizer.set_wd_mult(args_wd_mult)


class DistributedTrainer(mx.gluon.Trainer):
    """gluon Trainer whose ``_allreduce_grads`` averages over ranks
    via the TPU collective engine instead of kvstore push/pull
    (reference mxnet/__init__.py:124-234)."""

    def __init__(self, params, optimizer, optimizer_params=None,
                 compression=Compression.none,
                 gradient_predivide_factor=1.0, prefix=None,
                 num_groups=0, process_set=global_process_set):
        self._compression = compression
        self._process_set = process_set
        if isinstance(optimizer, DistributedOptimizer):
            optimizer = optimizer._optimizer
            warnings.warn("DistributedTrainer does not take "
                          "DistributedOptimizer as its optimizer. "
                          "We have unwrapped it for you.")
        # deterministic parameter ordering across ranks: dict keys are
        # sorted; Parameter objects order by name (gluon Parameters
        # define no __lt__)
        if isinstance(params, dict):
            params = OrderedDict(sorted(params.items()))
        elif isinstance(params, (list, tuple)):
            params = sorted(params,
                            key=lambda p: getattr(p, "name", str(p)))
        super().__init__(params, optimizer,
                         optimizer_params=optimizer_params, kvstore=None)
        self._gradient_predivide_factor = gradient_predivide_factor
        assert prefix is None or isinstance(prefix, str)
        self._prefix = prefix if prefix else ""
        self._num_groups = num_groups

    def _allreduce_grads(self):
        if basics.size() == 1:
            return
        pre = 1.0 / self._gradient_predivide_factor
        post = self._gradient_predivide_factor
        entries = []
        for i, param in enumerate(self._params):
            if param.grad_req != "null":
                comp, cctx = self._compression.compress(
                    param.list_grad()[0])
                entries.append((i, param, comp, cctx))
        if self._num_groups > 0:
            for gi, group in enumerate(
                    _split_list(entries, self._num_groups)):
                grouped_allreduce_(
                    tensors=[e[2] for e in group], average=True,
                    name=f"{self._prefix}{group[0][0]}:{group[-1][0]}",
                    priority=-gi, prescale_factor=pre,
                    postscale_factor=post,
                    process_set=self._process_set)
        else:
            for i, _, comp, _ in entries:
                allreduce_(comp, average=True,
                           name=self._prefix + str(i), priority=-i,
                           prescale_factor=pre, postscale_factor=post,
                           process_set=self._process_set)
        if self._compression is not Compression.none:
            for _, param, comp, cctx in entries:
                param.list_grad()[0][:] = \
                    self._compression.decompress(comp, cctx)


def _append_broadcast_init(param, root_rank, name):
    init_impl = getattr(param, "_init_impl")

    def wrapped_init_impl(self, *args, **kwargs):
        init_impl(*args, **kwargs)
        broadcast_(self.data(), root_rank=root_rank, name=name)
    return wrapped_init_impl


def broadcast_parameters(params, root_rank=0, prefix=None):
    """Broadcast a dict / gluon ParameterDict of parameters from root
    (reference mxnet/__init__.py:245-290); deferred-init parameters get
    a post-init broadcast hook."""
    if basics.size() == 1:
        return
    tensors, names = [], []
    assert prefix is None or isinstance(prefix, str)
    prefix = prefix if prefix else ""
    try:
        from mxnet.gluon.parameter import ParameterDict
        valid_types = (dict, ParameterDict)
    except ImportError:
        valid_types = (dict,)
    if not isinstance(params, valid_types):
        raise ValueError(f"invalid params of type: {type(params)}")
    for name, p in sorted(params.items()):
        try:
            if isinstance(p, mx.gluon.parameter.Parameter):
                tensors.append(p.data())
            else:
                tensors.append(p)
            names.append(prefix + str(name))
        except mx.gluon.parameter.DeferredInitializationError:
            new_init = _append_broadcast_init(p, root_rank,
                                              prefix + str(name))
            p._init_impl = types.MethodType(new_init, p)
    for tensor, name in zip(tensors, names):
        broadcast_(tensor, root_rank=root_rank, name=name)

"""Multi-tenant fleet controller — ``horovod_tpu.fleet``
(docs/fleet.md; ``horovodrun --fleet-spec``).

Training and serving jobs co-scheduled on ONE shared host pool with
preemption-by-elasticity: a serving SLO breach shrinks a training
job's dp through the elastic target lever, a job preempted to zero
suspends (journaled, drained at a commit boundary) and resumes from
its last elastic commit, and host health + chaos revocation apply to
every job through one mechanism.
"""

from .spec import (  # noqa: F401
    FleetOptions, FleetSpec, JobSpec, load_spec, parse_spec,
)
from .controller import (  # noqa: F401
    FleetController, FleetDiscovery, ManagedJob, assign_hosts,
    size_jobs, DONE, FAILED, PENDING, RUNNING, SUSPENDED,
)

__all__ = [
    "FleetController", "FleetDiscovery", "FleetSpec", "FleetOptions",
    "JobSpec", "ManagedJob", "load_spec", "parse_spec", "size_jobs",
    "assign_hosts", "PENDING", "RUNNING", "SUSPENDED", "DONE",
    "FAILED",
]

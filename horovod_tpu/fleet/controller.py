"""Launcher-side multi-tenant fleet controller (docs/fleet.md).

One process runs N jobs — training + serving mixes, declared in a
JSON :mod:`fleet spec <.spec>` — over a shared host pool, by composing
the levers previous subsystems built instead of inventing new ones:

* **placement** walks the pool in declared order and sizes every job
  between its ``min_np``/``max_np``, serving jobs first (they carry
  live traffic), a pure deterministic function of (capacity, demands);
* **preemption-by-elasticity**: a serving job whose SLO signals
  (windowed p99 / queue depth off the merged snapshot pushes, read by
  the SAME :class:`~..serving.autoscale.ServingSignals` the per-job
  autoscaler uses) breach gets chips by *shrinking* a training job's
  dp through :meth:`ElasticDriver.set_target_np` — never by killing a
  job that can shrink; idle chips flow back the same way;
* **suspension**: a training job preempted below ``min_np`` suspends
  (:meth:`ElasticDriver.suspend` — coordinator journal flushed,
  workers drained at a commit boundary, committed state in the spill)
  and later resumes from journal + last elastic commit; suspension is
  a control-plane pause, not a restart;
* **fault tolerance composes across jobs**: a host death observed by
  ANY job's driver blacklists the host for ALL jobs (deterministic
  tick-based cooldown — the evidence log must be byte-identical
  across same-seed runs, so no jitter here); the controller journals
  its own transitions and is restartable from that journal without
  double-preempting; and chaos gains ``revoke_host``/``restore_host``
  kinds so a scheduled preemption and a hardware death drill through
  ONE mechanism.

Each job gets its own RendezvousServer + ElasticDriver; the
controller feeds every driver through a :class:`FleetDiscovery` (the
driver's ordinary discovery poll picks placement changes up like any
membership change) and owns every driver's target lever
(:meth:`ElasticDriver.acquire_target_lever` — a per-job autoscaler
racing the fleet serializes out, last-writer-wins by reconcile tick).

``reconcile()`` is one tick and is directly callable — tests and the
day-in-the-life smoke drive it deterministically; ``run()`` loops it
on ``HOROVOD_FLEET_RECONCILE_SECONDS``.
"""

import json
import logging
import os
import threading
import time

from ..common import env as env_mod
from ..runner.elastic.discovery import HostDiscovery
from ..runner.http.journal import CoordJournal
from ..serving.autoscale import AutoscalePolicy, ServingSignals
from .. import telemetry
from .spec import FleetSpec

logger = logging.getLogger("horovod_tpu.fleet")

#: serving goodput unit: requests answered ok (registered by
#: serving/replica.py; read here off the merged snapshots)
SERVING_REQUESTS_FAMILY = telemetry.SERVING_REQUESTS_FAMILY

#: job lifecycle states journaled + exported
PENDING, RUNNING, SUSPENDED, DONE, FAILED = (
    "pending", "running", "suspended", "done", "failed")


class FleetDiscovery(HostDiscovery):
    """The slice of the shared pool the controller currently assigns
    to one job, served through the driver's ordinary discovery poll —
    placement changes reach the driver exactly like real membership
    changes."""

    def __init__(self, slots=None):
        self._lock = threading.Lock()
        self._slots = dict(slots or {})

    def set_slots(self, slots):
        with self._lock:
            self._slots = dict(slots)

    def find_available_hosts_and_slots(self):
        with self._lock:
            return dict(self._slots)


def claim_order(jobs):
    """THE claim ranking every placement pass shares: serving first,
    then priority descending, then spec order.  One definition —
    :func:`size_jobs` and :func:`assign_hosts` walking different
    rankings would place jobs sized by one order onto hosts by
    another."""
    return sorted(
        range(len(jobs)),
        key=lambda i: (jobs[i]["kind"] != "serving",
                       -jobs[i].get("priority", 0), i))


def size_jobs(capacity, jobs):
    """Size every job's worker count from total ``capacity`` slots —
    a PURE, deterministic function (the placement half the evidence
    log's byte-identical guarantee rests on).

    ``jobs``: list of dicts with name/kind/min_np/max_np/demand/
    priority/active, in spec order.  Returns ``{name: np}`` where 0
    means unplaceable (suspend).  Order of claims: serving first,
    then priority descending, then spec order.  Three passes:
    min_np guarantees, then surplus up to each job's demand, then —
    the preemption-by-elasticity rule — an UNMET serving demand may
    suspend whole training jobs (lowest claim first): a training job
    is never left between 0 and min_np, it either runs at >= min_np
    or suspends to zero."""
    order = claim_order(jobs)
    out = {j["name"]: 0 for j in jobs}

    def clamp(j):
        return max(min(int(j.get("demand", j["min_np"])),
                       j["max_np"]), j["min_np"])

    remaining = int(capacity)
    for i in order:
        j = jobs[i]
        if not j.get("active", True):
            continue
        if j["min_np"] <= remaining:
            out[j["name"]] = j["min_np"]
            remaining -= j["min_np"]
    for i in order:
        j = jobs[i]
        if out[j["name"]] == 0:
            continue
        take = min(max(clamp(j) - out[j["name"]], 0), remaining)
        out[j["name"]] += take
        remaining -= take
    # preemption pass: serving SLO demand decides who gets chips —
    # a still-unmet serving claim first drains the pool surplus
    # (including chips an EARLIER claim's suspension freed — they
    # must not strand while a later serving job sits under-
    # provisioned), then suspends training jobs from the lowest-claim
    # end
    for i in order:
        j = jobs[i]
        if j["kind"] != "serving" or out[j["name"]] == 0:
            continue
        need = clamp(j) - out[j["name"]]
        if need <= 0:
            continue
        take = min(need, remaining)
        out[j["name"]] += take
        remaining -= take
        need -= take
        for v in reversed(order):
            if need <= 0:
                break
            vj = jobs[v]
            if vj["kind"] != "training" or out[vj["name"]] == 0:
                continue
            freed = out[vj["name"]]
            out[vj["name"]] = 0
            take = min(freed, need)
            out[j["name"]] += take
            remaining += freed - take
            need -= take
    return out


def assign_hosts(pool, hosts_order, sizes, job_order):
    """Map job sizes onto concrete ``{job: {host: slots}}`` — hosts
    walked in declared pool order, jobs in the SAME claim order as
    :func:`size_jobs`, contiguously, so serving jobs keep the pool
    front across ticks and churn stays minimal.  Pure/deterministic."""
    alloc = {name: {} for name in sizes}
    free = [pool[h] for h in hosts_order]
    for name in job_order:
        need = sizes.get(name, 0)
        for i, host in enumerate(hosts_order):
            if need <= 0:
                break
            take = min(free[i], need)
            if take > 0:
                alloc[name][host] = alloc[name].get(host, 0) + take
                free[i] -= take
                need -= take
    return alloc


class ManagedJob:
    """Per-job runtime state inside the controller."""

    def __init__(self, spec):
        self.spec = spec
        self.state = PENDING
        self.np = 0                  # currently allocated slots
        self.alloc = {}              # {host: slots}
        # training AND eval soak surplus chips up to max_np (both
        # return them on demand — preemption-by-elasticity); only a
        # serving job's demand moves with its SLO signals
        self.demand = spec.max_np if spec.kind != "serving" \
            else spec.min_np
        self.server = None
        self.driver = None
        self.discovery = FleetDiscovery()
        self.signals = None          # ServingSignals (serving jobs)
        self.policy = None           # AutoscalePolicy (serving jobs)
        self.started = False
        self.last_change_tick = -(10 ** 9)
        self._good_prev = {}         # per-KV-key goodput baselines
        if spec.kind == "serving":
            slo = dict(spec.slo or {})
            self.policy = AutoscalePolicy(
                slo_p99_ms=float(slo.get("p99_ms", 100.0)),
                queue_high=int(slo.get("queue_high", 64)),
                breach_evals=int(slo.get("breach_evals", 2)),
                idle_evals=int(slo.get("idle_evals", 6)),
                idle_frac=float(slo.get("idle_frac", 0.25)),
                idle_queue=int(slo.get("idle_queue", 1)),
                cooldown_s=float(slo.get("cooldown_s", 30.0)),
                slo_ttft_ms=slo.get("ttft_ms"))

    @property
    def name(self):
        return self.spec.name

    @property
    def active(self):
        return self.state in (PENDING, RUNNING, SUSPENDED)


class FleetController:
    """Reconciliation loop over one shared host pool (docs/fleet.md).

    ``driver_factory(job_spec, discovery, on_event)`` →
    ``(server, driver)`` — overridable so tests drive the control
    logic with fakes; the default builds a real RendezvousServer +
    ElasticDriver per job."""

    LEVER_OWNER = "fleet"

    def __init__(self, spec: FleetSpec, platform=None, verbose=False,
                 env=None, journal_path=None, evidence_path=None,
                 resume=None, driver_factory=None, metrics_port=None):
        self.spec = spec
        self._platform = platform
        self._verbose = verbose
        self._env = dict(env or {})
        self._journal_path = journal_path if journal_path is not None \
            else env_mod.get_str(env_mod.HOROVOD_FLEET_JOURNAL)
        self._evidence_path = evidence_path \
            if evidence_path is not None \
            else env_mod.get_str(env_mod.HOROVOD_FLEET_EVIDENCE_LOG)
        self._resume = env_mod.get_bool(env_mod.HOROVOD_FLEET_RESUME) \
            if resume is None else bool(resume)
        self._metrics_port = metrics_port if metrics_port is not None \
            else env_mod.get_int(env_mod.HOROVOD_FLEET_METRICS_PORT, 0)
        self.interval_s = env_mod.get_float(
            env_mod.HOROVOD_FLEET_RECONCILE_SECONDS,
            spec.options.reconcile_seconds)
        self._driver_factory = driver_factory or self._build_real_job

        self.jobs = [ManagedJob(j) for j in spec.jobs]
        self._by_name = {j.name: j for j in self.jobs}
        self.tick = 0
        #: fleet-level host health: host -> blacklisted-until tick
        #: (deterministic cooldown, docs/fleet.md "Host health")
        self._blacklisted = {}
        #: hosts removed by chaos revoke_host / a scheduled preemption
        #: (restored only by restore_host)
        self._revoked = set()
        #: host -> first tick it was seen back (settle debounce)
        self._returning = {}
        #: queue of (host, cause) failures reported by job drivers
        self._failed_hosts = []
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread = None
        self._error = False
        #: in-memory evidence (deterministic projection; also appended
        #: to HOROVOD_FLEET_EVIDENCE_LOG as JSON lines)
        self.decisions = []
        self.registry = telemetry.MetricRegistry()
        self._metrics_server = None
        self._journal = None
        self._restored = {}
        if self._journal_path:
            self._journal = CoordJournal(self._journal_path)
            if self._resume:
                self._restored = self._read_journal()
            elif os.path.exists(self._journal_path):
                self._journal.truncate()
        self._fault_states = []
        # the controller must see the SAME effective environment its
        # workers inherit (_spawn_worker merges os.environ under the
        # job env) — `env or os.environ` would hide a shell-exported
        # HOROVOD_FAULT_PLAN / fault log whenever any env dict was
        # passed, and the drill would silently half-run
        self._at_env = dict(os.environ)
        self._at_env.update(self._env)
        self._fault_log_path = self._at_env.get(
            "HOROVOD_FAULT_FLEET_LOG")
        self._arm_fault_plan()

    # -- construction --------------------------------------------------------

    def _build_real_job(self, job_spec, discovery, on_event):
        """One real control plane per job: RendezvousServer (with its
        own coordinator journal when the fleet journal is on) +
        ElasticDriver reading placement through ``discovery``."""
        import secrets as _secrets
        from ..runner.elastic.driver import ElasticDriver
        from ..runner.http.http_server import (
            RendezvousServer, autotune_kwargs,
        )

        at_env = dict(os.environ)
        at_env.update(self._env)
        at_env.update(job_spec.env)
        coord_journal = None
        if self._journal_path:
            coord_journal = f"{self._journal_path}.{job_spec.name}.coord"
        restored = self._restored.get(job_spec.name, {})
        server = RendezvousServer(
            secret=_secrets.token_bytes(16), world_size=0,
            journal_path=coord_journal,
            journal_replay=bool(restored and coord_journal and
                                os.path.exists(coord_journal)),
            **autotune_kwargs(at_env))
        server.start(port=int(restored.get("port", 0)))
        env = dict(self._env)
        env.update(job_spec.env)
        env.setdefault("HOROVOD_METRICS_PUSH_SECONDS", "1")
        driver = ElasticDriver(
            server, discovery, min_np=job_spec.min_np,
            max_np=job_spec.max_np, command=list(job_spec.command),
            env=env, platform=self._platform, verbose=self._verbose,
            on_event=on_event,
            elastic_timeout=float(
                at_env.get("HOROVOD_ELASTIC_TIMEOUT") or 600))
        return server, driver

    def _on_job_event(self, job):
        def handler(event):
            # only REAL slot failures blacklist fleet-wide:
            # worker_failed (the driver's record_failure verdict) and
            # worker_dead (heartbeat liveness).  Plain worker_exit
            # also fires for elastic churn (jax peer-loss aborts that
            # exec-restart) and clean de-assignments — treating those
            # as host deaths would cascade one resize into a
            # fleet-wide blacklist storm.
            if event.get("event") in ("worker_failed", "worker_dead"):
                with self._lock:
                    self._failed_hosts.append(
                        (event.get("host"), job.name))
        return handler

    def start(self):
        """Build every job's control plane, run the first placement
        tick, and start the placed drivers.  Jobs restored as
        SUSPENDED from the journal stay suspended — a restarted
        controller must reconcile, not re-preempt."""
        for job in self.jobs:
            restored = self._restored.get(job.name)
            if restored:
                job.state = restored.get("state", PENDING)
                job.np = int(restored.get("np", 0))
                job.demand = int(restored.get("demand", job.demand))
                if job.state == RUNNING:
                    # the restarted controller must re-start this
                    # job's driver; the preserved np/demand make the
                    # first reconcile reproduce the SAME placement —
                    # a restart reconciles, it never re-preempts
                    job.state = PENDING
                    job.np = 0
            if not job.active:
                # restored in a terminal state: no reconcile path
                # will ever use a control plane — building one would
                # leak a bound rendezvous service per finished job
                continue
            job.server, job.driver = self._driver_factory(
                job.spec, job.discovery, self._on_job_event(job))
            if hasattr(job.driver, "acquire_target_lever"):
                job.driver.acquire_target_lever(self.LEVER_OWNER)
            if job.server is not None:
                job.signals = ServingSignals(
                    job.server,
                    staleness_s=max(3.0 * self.interval_s, 10.0))
        if self._metrics_port:
            self._metrics_server = telemetry.MetricsServer(
                port=self._metrics_port,
                registry_fn=lambda: self.registry)
            self._metrics_server.start()
        self.reconcile()
        return self

    def run(self):
        """Start the background reconcile loop."""
        self._thread = threading.Thread(
            target=self._loop, name="horovod_tpu-fleet", daemon=True)
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.reconcile()
            except Exception:  # noqa: BLE001 — the fleet loop must
                # survive a bad tick; next tick re-evaluates
                logger.exception("fleet reconcile failed")

    def join(self, timeout=None):
        """Block until every job reaches a terminal state (or the
        controller is stopped).  True when no job failed."""
        deadline = time.monotonic() + timeout if timeout else None
        while not self._stop.is_set():
            if all(not j.active for j in self.jobs):
                break
            if deadline and time.monotonic() > deadline:
                raise TimeoutError("fleet join timed out")
            time.sleep(0.2)
        return not self._error and \
            all(j.state != FAILED for j in self.jobs)

    def stop(self):
        self._stop.set()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=5.0)
        for job in self.jobs:
            try:
                if job.driver is not None and job.started:
                    job.driver.stop()
                    if hasattr(job.driver, "join"):
                        job.driver.join(timeout=30)
            except Exception:  # noqa: BLE001 — teardown best-effort
                logger.exception("stopping job %s failed", job.name)
            try:
                if job.server is not None:
                    job.server.stop()
            except Exception:  # noqa: BLE001
                pass
        if self._metrics_server is not None:
            self._metrics_server.stop()

    def crash(self):
        """Simulate the controller PROCESS dying mid-run (the
        tools/fleet_smoke.py crash drill): worker processes are
        killed hard (they die with the controller's process group in
        a real crash), the per-job control planes and the metrics
        endpoint stop, and NOTHING journals a transition — the fleet
        journal and each job's coordinator journal stay exactly as
        the last running state recorded them.  Recover with a fresh
        ``FleetController(resume=True)`` on the same journal path;
        its first reconcile must reproduce the placement without
        double-preempting (the unit contract
        tests/test_fleet.py::test_controller_journal_restart_...)."""
        self._stop.set()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=5.0)
        for job in self.jobs:
            drv = job.driver
            if drv is not None and job.started:
                for p in list(getattr(drv, "_procs", {}).values()):
                    try:
                        if p.poll() is None:
                            p.kill()
                    except Exception:  # noqa: BLE001 — already gone
                        pass
                try:
                    drv.stop()
                    if hasattr(drv, "join"):
                        drv.join(timeout=10)
                except Exception:  # noqa: BLE001 — crash teardown
                    pass
            try:
                if job.server is not None:
                    job.server.stop()
            except Exception:  # noqa: BLE001
                pass
        if self._metrics_server is not None:
            self._metrics_server.stop()

    # -- journal -------------------------------------------------------------

    def _read_journal(self):
        out = {}
        for rec in self._journal.read():
            if rec.get("k") == "fjob":
                out[rec["name"]] = rec
            elif rec.get("k") == "fhost":
                # conservative restore: re-blacklist for a full window
                # from tick 0 (tick counters restart with the process)
                if rec.get("st") == "blacklist":
                    self._blacklisted[rec["host"]] = \
                        self.spec.options.blacklist_ticks
                else:
                    self._blacklisted.pop(rec["host"], None)
            elif rec.get("k") == "snap":
                for name, jrec in rec.get("s", {}).get(
                        "jobs", {}).items():
                    out[name] = jrec
        return out

    def _journal_job(self, job):
        if self._journal is None:
            return
        port = None
        if job.server is not None:
            port = getattr(job.server, "port", None)
        self._journal.append({
            "k": "fjob", "name": job.name, "state": job.state,
            "np": job.np, "demand": job.demand, "port": port})

    def _journal_host(self, host, state):
        if self._journal is not None:
            self._journal.append({"k": "fhost", "host": host,
                                  "st": state})

    # -- evidence ------------------------------------------------------------

    def _evidence(self, rec, wall=None):
        """Append one decision to the deterministic evidence log.
        ``rec`` carries NO wall-clock, measured, or race-ordered
        fields (same-seed runs must produce byte-identical logs);
        ``wall`` extras ride only the on-disk line, every key
        ``t_``-prefixed (the chaos runners' stripping convention —
        timestamps AND racy attribution like ``t_via``)."""
        with self._lock:
            self.decisions.append(dict(rec))
        logger.warning("fleet: %s", json.dumps(rec, sort_keys=True))
        if self._evidence_path:
            try:
                with open(self._evidence_path, "a") as f:
                    f.write(json.dumps(
                        {**rec, **(wall or {})}, sort_keys=True) + "\n")
            except OSError:
                pass

    # -- chaos ---------------------------------------------------------------

    def _arm_fault_plan(self):
        """Install the plan's fleet-side events (revoke_host /
        restore_host).  Tick triggers (``after``) are evaluated inside
        :meth:`reconcile` — deterministic across same-seed runs; wall
        triggers (``after_s``) run on chaos threads."""
        from ..chaos.plan import plan_from_env
        from ..chaos.inject import _EventState, _wall_trigger_loop

        plan = plan_from_env(self._at_env)
        if plan is None:
            return
        for e in plan.fleet_events():
            # loud target validation at ARM time, matching the plan
            # parser's posture — a typo'd pool index must fail the
            # launch, not silently drill the wrong host
            if e.host is not None:
                if e.host not in self.spec.pool:
                    raise ValueError(
                        f"fault plan event #{e.index} ({e.kind}): "
                        f"host {e.host!r} is not in the fleet pool "
                        f"{self.spec.pool_hosts}")
            elif not 0 <= int(e.proc or 0) < len(self.spec.pool_hosts):
                raise ValueError(
                    f"fault plan event #{e.index} ({e.kind}): proc "
                    f"{e.proc} is outside the pool "
                    f"(hosts: {self.spec.pool_hosts})")
            st = _EventState(e, plan.rng_for(e))
            if e.trigger == "wall":
                t = threading.Thread(
                    target=_wall_trigger_loop,
                    args=(st, self._stop, self._fire_fleet_fault),
                    name="horovod_tpu-chaos-fleet", daemon=True)
                t.start()
            else:
                self._fault_states.append(st)
        if plan.fleet_events():
            logger.warning("chaos: %d fleet pool fault(s) armed",
                           len(plan.fleet_events()))

    def _fault_host(self, event):
        if event.host is not None:
            return event.host
        # index validated at arm time (_arm_fault_plan)
        return self.spec.pool_hosts[int(event.proc or 0)]

    def _fire_fleet_fault(self, event, n):
        host = self._fault_host(event)
        rec = {"e": event.kind, "host": host, "event": event.index,
               "n": event.at}
        with self._lock:
            if event.kind == "revoke_host":
                self._revoked.add(host)
            else:
                self._revoked.discard(host)
        try:
            from ..chaos.inject import _count_injected
            _count_injected(event.kind)
        except Exception:  # noqa: BLE001
            pass
        # wall extras carry the t_ prefix (the chaos runners'
        # convention): the deterministic projection the byte-compare
        # strips them by prefix
        self._evidence(rec, wall={"t_fired": time.time()})
        if self._fault_log_path:
            try:
                with open(self._fault_log_path, "a") as f:
                    f.write(json.dumps({**rec,
                                        "t_fired": time.time()},
                                       sort_keys=True) + "\n")
            except OSError:
                pass

    def revoke_host(self, host):
        """Programmatic preemption drill: remove ``host`` from the
        pool (same mechanism chaos ``revoke_host`` uses)."""
        with self._lock:
            self._revoked.add(host)
        self._evidence({"e": "revoke_host", "host": host,
                        "event": -1, "n": self.tick})

    def restore_host(self, host):
        with self._lock:
            self._revoked.discard(host)
        self._evidence({"e": "restore_host", "host": host,
                        "event": -1, "n": self.tick})

    # -- signals -------------------------------------------------------------

    def _payload_total(self, job, fams):
        """Goodput units in ONE pushed snapshot: elastic commits for
        training, eval batches for eval, ok-requests for serving."""
        if job.spec.kind == "training":
            fam = fams.get(telemetry.ELASTIC_COMMITS_FAMILY)
            if not fam:
                return 0.0
            return sum(float(s.get("value", 0.0))
                       for s in fam.get("samples", []))
        if job.spec.kind == "eval":
            # the eval goodput unit: batches scored against journaled
            # eval-shard cursors (data/evaluation.py) — counted per
            # job exactly like training commits
            fam = fams.get(telemetry.EVAL_BATCHES_FAMILY)
            if not fam:
                return 0.0
            return sum(float(s.get("value", 0.0))
                       for s in fam.get("samples", []))
        total = 0.0
        fam = fams.get(SERVING_REQUESTS_FAMILY)
        if fam:
            total += sum(float(s.get("value", 0.0))
                         for s in fam.get("samples", [])
                         if s.get("labels", {}).get("outcome") == "ok")
        # continuous-batching jobs: each generated token is a goodput
        # unit (a streaming job may finish few "requests" per window
        # while emitting thousands of tokens)
        fam = fams.get(telemetry.SERVING_TOKENS_FAMILY)
        if fam:
            total += sum(float(s.get("value", 0.0))
                         for s in fam.get("samples", []))
        return total

    def _observe_job(self, job):
        """Per-tick observation: goodput deltas into the fleet
        registry, SLO signals → demand for serving jobs.  Every job
        (training too) reads its workers' pushed snapshots through a
        :class:`ServingSignals` — the payload/staleness handling is
        identical; only serving jobs also extract SLO signals."""
        if job.signals is None:
            return
        payloads = job.signals.fresh_payloads()
        good = 0.0
        for key, fams in payloads.items():
            total = self._payload_total(job, fams)
            prev = job._good_prev.get(key)
            if prev is None or total < prev:
                # first sight of the key, or a COUNTER RESET (every
                # elastic round installs a fresh worker registry, so
                # the lifetime total restarts at 0 after a resize or
                # resume): Prometheus reset semantics — the whole new
                # total is fresh goodput, clamping it away would
                # silently freeze the metric after the first resize
                good += max(total, 0.0)
            else:
                good += total - prev
            job._good_prev[key] = total
        if good > 0:
            self.registry.counter(
                telemetry.FLEET_GOODPUT_FAMILY,
                telemetry.FLEET_GOODPUT_HELP,
                labelnames=telemetry.FLEET_GOODPUT_LABELS).labels(
                job=job.name).inc(good)
        if job.spec.kind != "serving" or job.policy is None:
            return
        p99, queue, seen, ttft = (None, 0.0, False, None)
        if job.signals is not None:
            w = job.signals.read(payloads)
            p99, queue, seen = w
            ttft = getattr(w, "ttft_p99_s", None)
        breach = (p99 is not None and
                  p99 > job.policy.slo_p99_s) or \
            queue > job.policy.queue_high or \
            (job.policy.slo_ttft_s is not None and ttft is not None
             and ttft > job.policy.slo_ttft_s)
        if breach:
            self.registry.counter(
                telemetry.FLEET_SLO_BREACH_FAMILY,
                telemetry.FLEET_SLO_BREACH_HELP,
                labelnames=telemetry.FLEET_SLO_BREACH_LABELS).labels(
                job=job.name).inc()
        if not seen or job.state != RUNNING:
            return
        # the policy clock is the reconcile tick (deterministic in
        # tests/smokes): cooldown_s counts tick-seconds
        target = job.policy.decide(p99, queue, max(job.np, 1),
                                   now=self.tick * self.interval_s,
                                   ttft_p99_s=ttft)
        job.demand = max(job.spec.min_np,
                         min(target, job.spec.max_np))

    # -- the reconcile tick --------------------------------------------------

    def _available_pool(self):
        """Pool minus blacklisted/revoked hosts, with the settle
        debounce: a host coming back (blacklist expiry or
        restore_host) only re-enters after ``settle_ticks``
        consecutive ticks of health — a flapping host (resize storm)
        re-places once, not once per flap."""
        pool = {}
        settle = self.spec.options.settle_ticks
        with self._lock:
            return self._available_pool_locked(pool, settle)

    def _available_pool_locked(self, pool, settle):
        for host, slots in self.spec.pool.items():
            until = self._blacklisted.get(host)
            bad = (until is not None and self.tick < until) or \
                host in self._revoked
            if bad:
                self._returning.pop(host, None)
                continue
            if until is not None and self.tick >= until:
                del self._blacklisted[host]
                self._journal_host(host, "ok")
            first_ok = self._returning.setdefault(host, self.tick)
            if self.tick - first_ok < settle and first_ok > 1:
                continue            # still settling
            pool[host] = slots
        return pool

    def reconcile(self):
        """One reconciliation tick: harvest failures, fire due
        tick-triggered chaos, observe signals, place, apply diffs.
        Deterministic given the same signal history and tick count."""
        with self._lock:
            self.tick += 1
            tick = self.tick
            failed = list(self._failed_hosts)
            del self._failed_hosts[:]
        # chaos: tick-triggered pool faults fire BEFORE placement so
        # the tick they name is the tick that re-places
        for st in self._fault_states:
            if not st.exhausted and st.due(tick):
                self._fire_fleet_fault(st.event, tick)
        # host deaths reported by any job blacklist for ALL jobs.
        # The reporting job rides the on-disk extras only: with two
        # jobs co-located on a dying host, WHICH driver reports first
        # is a thread race — the byte-compared projection must not
        # carry it
        for host, via in failed:
            if host is None:
                continue
            with self._lock:
                already = self._blacklisted.get(host)
                if already is not None and self.tick < already:
                    continue
                self._blacklisted[host] = \
                    tick + self.spec.options.blacklist_ticks
                self._returning.pop(host, None)
            self._journal_host(host, "blacklist")
            self._evidence({"e": "blacklist", "host": host},
                           wall={"t_via": via})
        # lifecycle: finished/failed drivers leave the pool
        for job in self.jobs:
            if job.started and job.driver is not None and \
                    job.state in (RUNNING,) and \
                    hasattr(job.driver, "finished") and \
                    job.driver.finished():
                ok = True
                if hasattr(job.driver, "_error"):
                    ok = not job.driver._error
                job.state = DONE if ok else FAILED
                job.np = 0
                job.alloc = {}
                job.discovery.set_slots({})
                self._journal_job(job)
                self._evidence({"e": "done" if ok else "failed",
                                "job": job.name})
        # observe signals + goodput
        for job in self.jobs:
            if job.active and job.started:
                try:
                    self._observe_job(job)
                except Exception:  # noqa: BLE001 — a job's telemetry
                    # must never wedge the fleet tick
                    logger.exception("observing job %s failed",
                                     job.name)
        # place
        pool = self._available_pool()
        capacity = sum(pool.values())
        jobs_in = [{"name": j.name, "kind": j.spec.kind,
                    "min_np": j.spec.min_np, "max_np": j.spec.max_np,
                    "demand": j.demand,
                    "priority": j.spec.priority,
                    "active": j.active}
                   for j in self.jobs]
        sizes = size_jobs(capacity, jobs_in)
        order = claim_order(jobs_in)
        alloc = assign_hosts(pool, [h for h in self.spec.pool_hosts
                                    if h in pool],
                             sizes, [jobs_in[i]["name"] for i in order])
        # apply diffs in SPEC order (stable evidence ordering)
        for job in self.jobs:
            if not job.active:
                continue
            self._apply_placement(job, sizes[job.name],
                                  alloc[job.name], tick)
        self._export_gauges()

    def _apply_placement(self, job, np, host_slots, tick):
        """Diff one job's placement against its current state and
        drive the levers: discovery view + ``set_target_np`` (epoch =
        the reconcile tick — last-writer-wins across controller
        generations), suspend on preempt-to-zero, resume when
        capacity returns."""
        opts = self.spec.options
        grew = np > job.np
        if np == job.np and job.state in (RUNNING, SUSPENDED):
            if np > 0 and host_slots != job.alloc:
                # same size, different hosts (a blacklist/revoke hit
                # this job): a capacity substitution, applied now
                job.alloc = dict(host_slots)
                job.discovery.set_slots(host_slots)
            return
        # discretionary growth is rate-limited for TRAINING and EVAL
        # jobs (both greedily reclaim idle chips, so the reclaim must
        # not thrash rounds when capacity flaps); serving growth is
        # already hysteretic at the demand level (AutoscalePolicy
        # breach streaks + cooldown), and capacity loss / SLO shrink
        # always apply immediately
        if grew and job.spec.kind in ("training", "eval") and \
                job.state == RUNNING and \
                tick - job.last_change_tick < opts.cooldown_ticks:
            return
        if np < job.np:
            # a shrink the job's own demand explains is an idle
            # give-back; otherwise another job (or a host loss) took
            # the chips — a preemption
            cause = "idle" if job.demand <= np else "capacity"
        else:
            cause = "demand"
        if job.state == PENDING and np >= job.spec.min_np:
            job.np, job.alloc = np, dict(host_slots)
            job.discovery.set_slots(host_slots)
            self._start_job(job, np, tick, cause="init")
            return
        if np == 0 and job.state == RUNNING:
            # preemption to zero: suspend, never kill
            job.np, job.alloc = 0, {}
            job.discovery.set_slots({})
            if hasattr(job.driver, "suspend"):
                job.driver.suspend()
            job.state = SUSPENDED
            job.last_change_tick = tick
            self._journal_job(job)
            self._count_action(job, "suspend")
            self._evidence({"e": "suspend", "job": job.name})
            return
        if np >= job.spec.min_np and job.state == SUSPENDED:
            job.np, job.alloc = np, dict(host_slots)
            job.discovery.set_slots(host_slots)
            if hasattr(job.driver, "refresh_hosts"):
                job.driver.refresh_hosts()
            if hasattr(job.driver, "set_target_np"):
                job.driver.set_target_np(np, owner=self.LEVER_OWNER,
                                         epoch=tick)
            if job.started:
                if hasattr(job.driver, "unsuspend"):
                    job.driver.unsuspend()
                job.state = RUNNING
            else:
                # resumed under a RESTARTED controller: the fresh
                # driver was never started — start it now; workers
                # restore the last elastic commit from the spill
                self._start_job(job, np, tick, cause="resume",
                                evidence=False)
            job.last_change_tick = tick
            self._journal_job(job)
            self._count_action(job, "resume")
            self._evidence({"e": "resume", "job": job.name,
                            "np": np})
            return
        if job.state != RUNNING or np < job.spec.min_np:
            return
        # ordinary grow/shrink through the elasticity lever; the
        # synchronous host refresh makes the lever compute its
        # effective size against the placement view we just wrote,
        # not the discovery thread's cache (no transient round on a
        # just-revoked host)
        prev = job.np
        job.np, job.alloc = np, dict(host_slots)
        job.discovery.set_slots(host_slots)
        if hasattr(job.driver, "refresh_hosts"):
            job.driver.refresh_hosts()
        if hasattr(job.driver, "set_target_np"):
            job.driver.set_target_np(np, owner=self.LEVER_OWNER,
                                     epoch=tick)
        job.last_change_tick = tick
        self._journal_job(job)
        self._count_action(job, "grow" if np > prev else "shrink")
        self._evidence({"e": "place", "job": job.name, "np": np,
                        "cause": cause})

    def _start_job(self, job, np, tick, cause, evidence=True):
        if hasattr(job.driver, "set_target_np"):
            job.driver.set_target_np(np, owner=self.LEVER_OWNER,
                                     epoch=tick)
        try:
            if hasattr(job.driver, "start") and not job.started:
                job.driver.start(start_timeout=300)
            job.started = True
            job.state = RUNNING
        except Exception:  # noqa: BLE001 — a job that cannot start is
            # failed, not fatal to the fleet
            logger.exception("starting job %s failed", job.name)
            job.state = FAILED
            self._error = True
        job.last_change_tick = tick
        self._journal_job(job)
        if evidence:
            self._evidence({"e": "place", "job": job.name, "np": np,
                            "cause": cause})

    def _count_action(self, job, action):
        self.registry.counter(
            telemetry.FLEET_PREEMPTIONS_FAMILY,
            telemetry.FLEET_PREEMPTIONS_HELP,
            labelnames=telemetry.FLEET_PREEMPTIONS_LABELS).labels(
            job=job.name, action=action).inc()

    def _export_gauges(self):
        chips = self.registry.gauge(
            telemetry.FLEET_CHIPS_FAMILY, telemetry.FLEET_CHIPS_HELP,
            labelnames=telemetry.FLEET_CHIPS_LABELS)
        up = self.registry.gauge(
            telemetry.FLEET_JOB_RUNNING_FAMILY,
            telemetry.FLEET_JOB_RUNNING_HELP,
            labelnames=telemetry.FLEET_JOB_RUNNING_LABELS)
        for job in self.jobs:
            chips.labels(job=job.name).set(float(job.np))
            up.labels(job=job.name).set(
                1.0 if job.state == RUNNING else 0.0)

    # -- introspection -------------------------------------------------------

    def snapshot(self):
        """JSON-able fleet state (tests + the smoke read this)."""
        with self._lock:
            return {
                "tick": self.tick,
                "jobs": {j.name: {"state": j.state, "np": j.np,
                                  "demand": j.demand,
                                  "alloc": dict(j.alloc)}
                         for j in self.jobs},
                "blacklisted": dict(self._blacklisted),
                "revoked": sorted(self._revoked),
            }

"""Fleet spec: N jobs declared over ONE shared host pool
(docs/fleet.md).

A fleet spec is a JSON document (``horovodrun --fleet-spec`` — inline
JSON, ``@/path``, or a bare path, the same source grammar as fault
plans)::

    {
      "pool": {"host-a": 4, "host-b": 4},
      "options": {"reconcile_seconds": 2.0, "settle_ticks": 3,
                  "cooldown_ticks": 10, "blacklist_ticks": 30},
      "jobs": [
        {"name": "serve", "kind": "serving", "min_np": 1, "max_np": 4,
         "priority": 10,
         "command": ["python", "serve_worker.py"],
         "slo": {"p99_ms": 50, "queue_high": 8, "breach_evals": 2,
                 "idle_evals": 6},
         "env": {"HOROVOD_SERVING": "1"}},
        {"name": "train", "kind": "training", "min_np": 2, "max_np": 6,
         "priority": 0,
         "command": ["python", "train_worker.py"]}
      ]
    }

Semantics the controller enforces (docs/fleet.md "Reconciliation"):

* every job is guaranteed ``min_np`` while pool capacity allows —
  serving jobs first (they carry live traffic), then by descending
  ``priority``, then spec order;
* surplus capacity goes to each job's *demand* in the same order —
  a serving job's demand moves with its SLO signals, a training or
  eval job's demand is ``max_np`` (both soak up idle chips and
  return them on demand: preemption-by-elasticity);
* a training or eval job whose ``min_np`` cannot be met is
  **suspended** (preempted to zero — a control-plane pause, never a
  kill); it resumes when capacity returns.
"""

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..chaos.plan import read_plan_source

#: ``eval`` is the distributed-eval job kind (docs/data.md): the
#: controller gang-places it like training (it soaks surplus chips up
#: to max_np, suspends below min_np), its workers score batches
#: against journaled eval-shard cursors, and its goodput is the
#: eval-batch counter (``horovod_eval_batches_total``) — counted per
#: job exactly like training commits.
JOB_KINDS = ("training", "serving", "eval")


@dataclass
class JobSpec:
    """One job of the fleet."""

    name: str
    kind: str                       # training | serving | eval
    command: List[str]
    min_np: int = 1
    max_np: int = 1
    priority: int = 0               # higher = earlier claim on chips
    env: Dict[str, str] = field(default_factory=dict)
    #: serving-only SLO policy knobs (AutoscalePolicy spellings):
    #: p99_ms, queue_high, breach_evals, idle_evals, idle_frac,
    #: idle_queue, cooldown_s
    slo: Optional[dict] = None


@dataclass
class FleetOptions:
    """Controller cadence + debounce windows (tick = one reconcile)."""

    reconcile_seconds: float = 2.0
    #: a restored/resurrected host only re-enters placement after this
    #: many consecutive ticks of presence — the resize-storm debounce
    settle_ticks: int = 2
    #: minimum ticks between successive DISCRETIONARY reconfigurations
    #: of one job (capacity-loss shrinks are never delayed)
    cooldown_ticks: int = 5
    #: fleet-level blacklist duration, in ticks (deterministic — no
    #: jitter: the evidence log must be byte-identical across
    #: same-seed runs)
    blacklist_ticks: int = 30


@dataclass
class FleetSpec:
    pool: Dict[str, int]
    jobs: List[JobSpec]
    options: FleetOptions = field(default_factory=FleetOptions)

    def job(self, name: str) -> JobSpec:
        for j in self.jobs:
            if j.name == name:
                return j
        raise KeyError(name)

    @property
    def pool_hosts(self) -> List[str]:
        """Pool hosts in DECLARED order — the stable order placement
        walks and chaos ``proc`` indices address."""
        return list(self.pool.keys())


def _parse_job(i: int, raw: dict) -> JobSpec:
    if not isinstance(raw, dict):
        raise ValueError(f"fleet job #{i} is not an object: {raw!r}")
    name = raw.get("name")
    if not name or not isinstance(name, str):
        raise ValueError(f"fleet job #{i}: 'name' (string) required")
    kind = raw.get("kind", "training")
    if kind not in JOB_KINDS:
        raise ValueError(
            f"fleet job {name!r}: kind must be one of "
            f"{', '.join(JOB_KINDS)}, got {kind!r}")
    command = raw.get("command")
    if not command or not isinstance(command, list) or \
            not all(isinstance(c, str) for c in command):
        raise ValueError(
            f"fleet job {name!r}: 'command' (list of strings) required")
    min_np = int(raw.get("min_np", 1))
    max_np = int(raw.get("max_np", min_np))
    if min_np < 1 or max_np < min_np:
        raise ValueError(
            f"fleet job {name!r}: need 1 <= min_np <= max_np "
            f"(got {min_np}/{max_np})")
    env = raw.get("env", {})
    if not isinstance(env, dict):
        raise ValueError(f"fleet job {name!r}: 'env' must be an object")
    slo = raw.get("slo")
    if slo is not None:
        if kind != "serving":
            raise ValueError(
                f"fleet job {name!r}: 'slo' is only valid on serving "
                f"jobs")
        if not isinstance(slo, dict):
            raise ValueError(f"fleet job {name!r}: 'slo' must be an "
                             f"object")
    return JobSpec(name=name, kind=kind, command=list(command),
                   min_np=min_np, max_np=max_np,
                   priority=int(raw.get("priority", 0)),
                   env={str(k): str(v) for k, v in env.items()},
                   slo=slo)


def parse_spec(doc) -> FleetSpec:
    """Parse + validate a fleet spec from a dict or JSON string."""
    if isinstance(doc, (str, bytes)):
        doc = json.loads(doc)
    if not isinstance(doc, dict):
        raise ValueError(
            f"fleet spec must be a JSON object, got "
            f"{type(doc).__name__}")
    pool = doc.get("pool")
    if not pool or not isinstance(pool, dict):
        raise ValueError("fleet spec: 'pool' ({host: slots}) required")
    pool = {str(h): int(s) for h, s in pool.items()}
    if any(s < 1 for s in pool.values()):
        raise ValueError("fleet spec: every pool host needs >= 1 slot")
    raw_jobs = doc.get("jobs")
    if not raw_jobs or not isinstance(raw_jobs, list):
        raise ValueError("fleet spec: 'jobs' (non-empty list) required")
    jobs = [_parse_job(i, j) for i, j in enumerate(raw_jobs)]
    names = [j.name for j in jobs]
    if len(set(names)) != len(names):
        raise ValueError(f"fleet spec: duplicate job names in {names}")
    opts_raw = doc.get("options", {})
    if not isinstance(opts_raw, dict):
        raise ValueError("fleet spec: 'options' must be an object")
    opts = FleetOptions(
        reconcile_seconds=float(opts_raw.get("reconcile_seconds", 2.0)),
        settle_ticks=int(opts_raw.get("settle_ticks", 2)),
        cooldown_ticks=int(opts_raw.get("cooldown_ticks", 5)),
        blacklist_ticks=int(opts_raw.get("blacklist_ticks", 30)))
    total_min = sum(j.min_np for j in jobs if j.kind == "serving")
    capacity = sum(pool.values())
    if total_min > capacity:
        raise ValueError(
            f"fleet spec: serving jobs' min_np sum ({total_min}) "
            f"exceeds pool capacity ({capacity}) — nothing could ever "
            f"be placed")
    return FleetSpec(pool=pool, jobs=jobs, options=opts)


def load_spec(source: str) -> FleetSpec:
    """Load a spec from inline JSON, ``@/path``, or a bare file path
    (the same source grammar as fault plans)."""
    return parse_spec(read_plan_source(source))

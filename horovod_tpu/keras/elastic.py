"""Keras elastic callbacks (reference ``horovod/keras/elastic.py``:
CommitStateCallback, UpdateEpochStateCallback, UpdateBatchStateCallback).
"""

import tensorflow as tf

from ..tensorflow.elastic import TensorFlowKerasState, run  # noqa: F401


class CommitStateCallback(tf.keras.callbacks.Callback):
    """Commit state every ``batches_per_commit`` batches (reference
    keras/elastic.py CommitStateCallbackImpl)."""

    def __init__(self, state, batches_per_commit=1):
        super().__init__()
        self.state = state
        self.batches_per_commit = batches_per_commit
        self._counter = 0

    def on_batch_end(self, batch, logs=None):
        self._counter += 1
        if self._counter >= self.batches_per_commit:
            self._counter = 0
            self.state.commit()


class UpdateEpochStateCallback(tf.keras.callbacks.Callback):
    def __init__(self, state):
        super().__init__()
        self.state = state

    def on_epoch_begin(self, epoch, logs=None):
        self.state.epoch = epoch


class UpdateBatchStateCallback(tf.keras.callbacks.Callback):
    def __init__(self, state):
        super().__init__()
        self.state = state

    def on_batch_end(self, batch, logs=None):
        self.state.batch = batch

    def on_epoch_end(self, epoch, logs=None):
        self.state.batch = 0


class KerasState(TensorFlowKerasState):
    """Elastic state for a keras model (reference keras/elastic.py:22 —
    an alias of TensorFlowKerasState bound to the installed keras)."""

"""Keras frontend (reference ``horovod/keras/__init__.py``)."""

from ..common.basics import (  # noqa: F401
    init, shutdown, is_initialized,
    rank, size, local_rank, local_size, cross_rank, cross_size,
)
from ..tensorflow import (  # noqa: F401
    allreduce, allgather, broadcast, broadcast_object, allgather_object,
    broadcast_variables, Average, Sum, Adasum,
    Compression, DistributedOptimizer,
)
from . import callbacks  # noqa: F401
from . import elastic  # noqa: F401


def load_model(filepath, custom_optimizers=None, custom_objects=None,
               compression=None):
    """Load a keras model saved by a distributed run (reference
    keras/__init__.py:216): optimizer wrapping happens transparently at
    compile time in this implementation, so this is a thin wrapper."""
    import tensorflow as tf
    return tf.keras.models.load_model(filepath,
                                      custom_objects=custom_objects)

"""Keras frontend (reference ``horovod/keras/__init__.py``)."""

from ..common.basics import (  # noqa: F401
    init, shutdown, is_initialized,
    rank, size, local_rank, local_size, cross_rank, cross_size,
    metrics, start_metrics_server, dump_trace,
)
from .. import serving  # noqa: F401
from ..tensorflow import (  # noqa: F401
    allreduce, allgather, broadcast, reducescatter, alltoall,
    broadcast_object, allgather_object,
    broadcast_variables, Average, Sum, Adasum,
    Compression, DistributedOptimizer,
)
from . import callbacks  # noqa: F401
from . import elastic  # noqa: F401


def PartialDistributedOptimizer(optimizer, name=None,
                                device_dense="", device_sparse="",
                                compression=Compression.none,
                                sparse_as_dense=False,
                                gradient_predivide_factor=1.0,
                                op=Average, groups=None,
                                process_set=None,
                                local_layers=None,
                                scale_local_gradients=True):
    """DistributedOptimizer whose ``local_layers`` keep their gradients
    local — no allreduce (reference keras/__init__.py:116)."""
    from ..common.process_sets import global_process_set
    from ..tensorflow import DistributedOptimizer as _wrap
    from ..tensorflow import _normalize_local_layers

    local_layers = _normalize_local_layers(local_layers)
    opt = _wrap(optimizer, name=name, compression=compression,
                sparse_as_dense=sparse_as_dense, op=op, groups=groups,
                gradient_predivide_factor=gradient_predivide_factor,
                process_set=process_set or global_process_set,
                scale_local_gradients=scale_local_gradients)
    for layer in local_layers:
        for var in layer.trainable_weights:
            opt.register_local_var(var)
    return opt


def broadcast_global_variables(root_rank):
    """Broadcast all TF global variables from root (reference
    keras/__init__.py:183).  Only graph-mode (tf.compat.v1) variables
    live in the global collection; eagerly-created keras variables do
    not, and silently broadcasting nothing would let ranks train from
    different initializations — so an empty collection is an error."""
    import tensorflow as tf
    variables = tf.compat.v1.global_variables()
    if not variables:
        raise RuntimeError(
            "broadcast_global_variables found no graph-collection "
            "variables (TF2 eager variables are not registered there); "
            "use hvd.broadcast_variables(model.weights, root_rank) or "
            "the BroadcastGlobalVariablesCallback instead")
    return broadcast_variables(variables, root_rank)


def load_model(filepath, custom_optimizers=None, custom_objects=None,
               compression=None):
    """Load a keras model saved by a distributed run (reference
    keras/__init__.py:216): optimizer wrapping happens transparently at
    compile time in this implementation, so this is a thin wrapper."""
    import tensorflow as tf
    return tf.keras.models.load_model(filepath,
                                      custom_objects=custom_objects)

"""Keras callbacks (reference ``horovod/_keras/callbacks.py:23-207``,
re-exported via ``horovod/keras/callbacks.py``)."""

import tensorflow as tf

from ..common import basics
from ..ops import api


class BroadcastGlobalVariablesCallback(tf.keras.callbacks.Callback):
    """Broadcast initial variable states from root to all other ranks
    at the start of training (reference _keras/callbacks.py:23)."""

    def __init__(self, root_rank=0, device=""):
        super().__init__()
        self.root_rank = root_rank
        self.broadcast_done = False
        self._local_vars = set()
        self._local_slot_frags = set()   # (name fragment, shape)

    def register_local_var(self, var):
        """Exclude ``var`` from the initial broadcast (reference
        _keras/callbacks.py:32-41) — the worker-local-variable story
        for PartialDistributedOptimizer users: locally-trained layers
        must not be overwritten by root's initial values."""
        from ..tensorflow import _var_key

        self._local_vars.add(_var_key(var))
        # identity fragments for matching the var's OPTIMIZER slot
        # variables (momentum/adam moments), which would otherwise be
        # clobbered by root's broadcast just like the weight itself
        name = getattr(var, "path", None) or getattr(var, "name", "")
        name = str(name).split(":")[0]
        if name:
            self._local_slot_frags.add((name, tuple(var.shape)))
            self._local_slot_frags.add(
                (name.replace("/", "_"), tuple(var.shape)))

    def on_batch_end(self, batch, logs=None):
        if self.broadcast_done:
            return
        from ..tensorflow import _var_key, broadcast_variables
        broadcast_variables(
            [v for v in self.model.weights
             if _var_key(v) not in self._local_vars], self.root_rank)
        if hasattr(self.model, "optimizer") and \
                getattr(self.model.optimizer, "variables", None):
            broadcast_variables(
                [v for v in self.model.optimizer.variables
                 if not self._is_local_slot(v)], self.root_rank)
        self.broadcast_done = True

    def _is_local_slot(self, opt_var):
        """Best-effort: an optimizer slot belongs to a local var when
        its path embeds the var's name (keras slots are named from
        their reference variable) and the shapes agree.  (The
        reference broadcasts optimizer state unfiltered — clobbering
        exactly the per-rank slots register_local_var protects.)"""
        if not self._local_slot_frags:
            return False
        path = str(getattr(opt_var, "path", None)
                   or getattr(opt_var, "name", "")).split(":")[0]
        shape = tuple(opt_var.shape)
        return any(frag in path and shape == fshape
                   for frag, fshape in self._local_slot_frags)


class MetricAverageCallback(tf.keras.callbacks.Callback):
    """Average epoch metrics across ranks before other callbacks (e.g.
    checkpointers) read them (reference _keras/callbacks.py:62)."""

    def __init__(self, device=""):
        super().__init__()

    def on_epoch_end(self, epoch, logs=None):
        if logs is None or basics.size() == 1:
            return
        from ..tensorflow.functions import allreduce_metrics
        scalar = {k: v for k, v in logs.items()
                  if isinstance(v, (int, float))}
        logs.update(allreduce_metrics(scalar))


class LearningRateScheduleCallback(tf.keras.callbacks.Callback):
    """Multiply initial lr by ``multiplier`` over [start_epoch,
    end_epoch) (reference _keras/callbacks.py:118)."""

    def __init__(self, initial_lr, multiplier, start_epoch=0,
                 end_epoch=None, staircase=True, momentum_correction=True,
                 steps_per_epoch=None):
        super().__init__()
        self.initial_lr = initial_lr
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.momentum_correction = momentum_correction
        self.steps_per_epoch = steps_per_epoch
        self.current_epoch = 0
        if not callable(multiplier):
            self.static_multiplier = multiplier
            self.multiplier = lambda epoch: multiplier
        else:
            self.static_multiplier = None
            self.multiplier = multiplier

    def _in_range(self, epoch):
        return (epoch >= self.start_epoch and
                (self.end_epoch is None or epoch < self.end_epoch))

    def _set_lr(self, lr):
        opt = self.model.optimizer
        if hasattr(opt, "learning_rate"):
            opt.learning_rate = lr
        else:  # pragma: no cover
            tf.keras.backend.set_value(opt.lr, lr)

    def on_epoch_begin(self, epoch, logs=None):
        self.current_epoch = epoch
        if self.staircase and self._in_range(epoch):
            self._set_lr(self.initial_lr * self.multiplier(epoch))

    def on_batch_begin(self, batch, logs=None):
        if self.staircase or not self._in_range(self.current_epoch):
            return
        steps = self.steps_per_epoch or \
            (self.params or {}).get("steps")
        if not steps:
            raise ValueError(
                "steps_per_epoch is required for non-staircase "
                "schedules (keras did not report params['steps'])")
        epoch = self.current_epoch + float(batch) / steps
        self._set_lr(self.initial_lr * self.multiplier(epoch))

    def on_epoch_end(self, epoch, logs=None):
        if logs is not None:
            opt = self.model.optimizer
            lr = opt.learning_rate
            logs["lr"] = float(lr.numpy() if hasattr(lr, "numpy") else lr)


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Gradual lr warmup from lr to lr*size over warmup_epochs
    (reference _keras/callbacks.py:167: 'Facebook ImageNet in 1 Hour'
    gradual warmup)."""

    def __init__(self, initial_lr, warmup_epochs=5, momentum_correction=True,
                 steps_per_epoch=None, verbose=0):
        self.warmup_epochs = warmup_epochs
        self.verbose = verbose

        def multiplier(epoch):
            # epoch is fractional within warmup
            size = basics.size()
            return 1.0 / size * (epoch * (size - 1) /
                                 warmup_epochs + 1)
        super().__init__(initial_lr=initial_lr, multiplier=multiplier,
                         start_epoch=0, end_epoch=warmup_epochs,
                         staircase=False,
                         momentum_correction=momentum_correction,
                         steps_per_epoch=steps_per_epoch)

    def on_epoch_end(self, epoch, logs=None):
        super().on_epoch_end(epoch, logs)
        if epoch == self.end_epoch - 1 and self.verbose > 0 and \
                basics.rank() == 0:
            print(f"Epoch {epoch + 1}: finished gradual learning rate "
                  f"warmup to {self.initial_lr}.")


class BestModelCheckpoint(tf.keras.callbacks.ModelCheckpoint):
    """ModelCheckpoint preset that saves only the best full model by
    the monitored metric (reference keras/callbacks.py:161).  Pair with
    MetricAverageCallback so every rank agrees on the metric, and guard
    saving to rank 0 in the filepath choice."""

    def __init__(self, filepath=None, monitor="val_loss", verbose=0,
                 mode="auto", save_freq="epoch"):
        if filepath is None:
            raise ValueError(
                "BestModelCheckpoint requires a filepath to save to")
        super().__init__(filepath=filepath, monitor=monitor,
                         verbose=verbose, save_best_only=True,
                         save_weights_only=False, mode=mode,
                         save_freq=save_freq)

"""Reference import path ``horovod.ray.runner``.

``RayExecutor``/``BaseHorovodWorker`` live in the package root (the
actor-spawn flow over the env-handoff contract); this module adds the
reference's support classes — MiniSettings, the rank-layout
Coordinator, and the static params/adapter pair — all functional
without a live ray cluster except actor spawning itself."""

import logging
from collections import defaultdict
from dataclasses import dataclass
from typing import Optional

from . import BaseHorovodWorker, RayExecutor, _require_ray  # noqa: F401
from .adapter import Adapter, BaseParams
from ..runner.common.util import secret, timeout

logger = logging.getLogger("horovod_tpu.ray")


class MiniSettings:
    """Minimal settings for the ray flow (reference runner.py:21)."""

    def __init__(self, nics=None, verbose=1, key=None, ssh_port=None,
                 ssh_identity_file=None, timeout_s=300,
                 placement_group_timeout_s=100, elastic=False):
        self.nics = nics
        self.verbose = verbose
        self.key = key if key is not None else \
            secret.make_secret_key()
        self.ssh_port = ssh_port
        self.ssh_identity_file = ssh_identity_file
        self.timeout_s = timeout_s
        self.placement_group_timeout_s = placement_group_timeout_s
        self.elastic = elastic

    @property
    def start_timeout(self):
        return timeout.Timeout(
            self.timeout_s,
            message="Timed out waiting for {activity}. Please check "
                    "connectivity between servers.")


class Coordinator:
    """Rank-layout bookkeeping for actor-based launches (reference
    runner.py:45): workers register (hostname, node, world rank), and
    finalize_registration derives each rank's local/cross geometry."""

    rendezvous = None
    global_rendezv_port = None
    nics = None

    def __init__(self, settings):
        self.settings = settings
        self.node_id_by_rank = defaultdict(list)
        self._hostnames = set()

    @property
    def world_size(self):
        return sum(len(ranks)
                   for ranks in self.node_id_by_rank.values())

    @property
    def hostnames(self):
        return self._hostnames

    @property
    def node_id_string(self):
        return ",".join(f"{node_id}:{len(ranks)}"
                        for node_id, ranks in
                        self.node_id_by_rank.items())

    def register(self, hostname, node_id, world_rank):
        self._hostnames.add(hostname)
        self.node_id_by_rank[node_id].append(world_rank)

    def finalize_registration(self):
        """Per-rank env map (reference runner.py:83)."""
        rank_to_info = {}
        cross_sizes = defaultdict(int)
        cross_ranks = {}
        for rank_list in self.node_id_by_rank.values():
            for local_rank, world_rank in enumerate(rank_list):
                cross_ranks[world_rank] = cross_sizes[local_rank]
                cross_sizes[local_rank] += 1
        for node_id, ranks in self.node_id_by_rank.items():
            for local_rank, world_rank in enumerate(ranks):
                rank_to_info[world_rank] = dict(
                    HOROVOD_CROSS_RANK=cross_ranks[world_rank],
                    HOROVOD_CROSS_SIZE=cross_sizes[local_rank],
                    HOROVOD_LOCAL_RANK=local_rank,
                    HOROVOD_LOCAL_SIZE=len(ranks))
        return rank_to_info

    def establish_rendezvous(self):
        """Start the KV/coordinator service and return the workers'
        rendezvous env (reference runner.py:102 — gloo names kept)."""
        from ..runner.http.http_server import (
            RendezvousServer, local_ip,
        )

        key = self.settings.key \
            if isinstance(self.settings.key, bytes) else None
        self.rendezvous = RendezvousServer(
            secret=key, world_size=self.world_size)
        self.global_rendezv_port = self.rendezvous.start()
        addr = local_ip()
        env = {
            "HOROVOD_GLOO_RENDEZVOUS_ADDR": addr,
            "HOROVOD_GLOO_RENDEZVOUS_PORT":
                str(self.global_rendezv_port),
            "HOROVOD_RENDEZVOUS_ADDR": addr,
            "HOROVOD_RENDEZVOUS_PORT": str(self.global_rendezv_port),
            "HOROVOD_CONTROLLER": "http",
            "HOROVOD_CPU_OPERATIONS": "cpu",
        }
        if key is not None:
            # workers sign every KV/coordinator request with this
            # (common/basics.py reads the hex form; same publication
            # rule as the elastic driver's worker env)
            env["HOROVOD_SECRET_KEY"] = key.hex()
        return env


@dataclass
class StaticParams(BaseParams):
    """Reference runner.py:133."""

    num_workers: Optional[int] = None
    num_hosts: Optional[int] = None
    num_workers_per_host: int = 1
    use_current_placement_group: bool = True

    @property
    def elastic(self):
        return False

    @property
    def adapter(self):
        return StaticAdapter


class StaticAdapter(Adapter):
    """Reference runner.py:424 — drives a fixed-size actor set.
    Delegates to RayExecutor (package root), which owns the actor
    lifecycle; requires ray at start()."""

    def __init__(self, params, settings=None):
        self.params = params
        self.settings = settings or MiniSettings()
        self._executor = None

    def start(self, executable_cls=None, executable_args=None,
              executable_kwargs=None, extra_env_vars=None):
        self._executor = RayExecutor(
            self.settings,
            num_workers=self.params.num_workers,
            num_hosts=self.params.num_hosts,
            num_workers_per_host=self.params.num_workers_per_host,
            cpus_per_worker=self.params.cpus_per_worker,
            use_gpu=self.params.use_gpu,
            gpus_per_worker=self.params.gpus_per_worker)
        self._executor.start(executable_cls=executable_cls,
                             executable_args=executable_args,
                             executable_kwargs=executable_kwargs,
                             extra_env_vars=extra_env_vars)

    def execute(self, fn, callbacks=None):
        return self._executor.execute(fn)

    def run(self, fn, args=None, kwargs=None, callbacks=None):
        return self._executor.run(fn, args=args, kwargs=kwargs)

    def run_remote(self, fn, args=None, kwargs=None):
        return self._executor.run_remote(fn, args=args, kwargs=kwargs)

    def execute_single(self, fn):
        return self._executor.execute_single(fn)

    def shutdown(self):
        if self._executor is not None:
            self._executor.shutdown()

"""Reference import path ``horovod.ray.worker``."""

from . import BaseHorovodWorker  # noqa: F401

"""Ray integration (reference ``horovod/ray/runner.py:168`` RayExecutor,
``ray/elastic.py:150`` ElasticRayExecutor).

Gated: ray is not part of this image.  The executor contract is kept
API-compatible; actors come up through the same rendezvous + env
handoff as the CLI launcher.
"""


def _require_ray():
    try:
        import ray  # noqa: F401
    except ImportError as exc:
        raise ImportError(
            "horovod_tpu.ray requires ray, which is not installed in "
            "this environment") from exc


class RayExecutor:
    """Launch a horovod_tpu job on Ray actors (reference
    ray/runner.py:168-420: placement strategies, per-actor env
    handoff, run/run_remote/execute API)."""

    def __init__(self, settings=None, num_workers=None,
                 cpus_per_worker=1, use_gpu=False,
                 placement_group_timeout_s=100, **kwargs):
        _require_ray()
        self.num_workers = num_workers
        self.cpus_per_worker = cpus_per_worker
        self._workers = []

    def start(self, executable_cls=None, executable_args=None,
              executable_kwargs=None, extra_env_vars=None):
        import ray
        import secrets as _secrets
        from ..runner.http.http_server import (
            RendezvousServer, autotune_kwargs, local_ip,
        )

        secret_hex = _secrets.token_hex(16)
        import os as _os
        at_env = dict(_os.environ)
        at_env.update(extra_env_vars or {})
        self._server = RendezvousServer(
            secret=bytes.fromhex(secret_hex),
            world_size=self.num_workers,
            **autotune_kwargs(at_env))
        port = self._server.start()
        addr = local_ip()
        import socket as _socket
        s = _socket.socket(); s.bind(("", 0))
        coordinator = f"{addr}:{s.getsockname()[1]}"; s.close()

        @ray.remote(num_cpus=self.cpus_per_worker)
        class Worker:
            def __init__(self, index, env):
                import os
                os.environ.update(env)
                os.environ.update({
                    "HOROVOD_CONTROLLER": "http",
                    "HOROVOD_GLOO_RENDEZVOUS_ADDR": addr,
                    "HOROVOD_GLOO_RENDEZVOUS_PORT": str(port),
                    "HOROVOD_SECRET_KEY": secret_hex,
                    "HOROVOD_TPU_PROC_INDEX": str(index),
                    "HOROVOD_TPU_NUM_PROCS": str(self_num),
                    "HOROVOD_TPU_RANKS_PER_PROC": "1",
                    "HOROVOD_TPU_COORDINATOR": coordinator,
                })

            def execute(self, fn, *a, **kw):
                return fn(*a, **kw)

        self_num = self.num_workers
        self._workers = [
            Worker.remote(i, extra_env_vars or {})
            for i in range(self.num_workers)]

    def run(self, fn, args=None, kwargs=None):
        import ray
        return ray.get([w.execute.remote(fn, *(args or ()),
                                         **(kwargs or {}))
                        for w in self._workers])

    def execute(self, fn):
        import ray
        return ray.get([w.execute.remote(fn) for w in self._workers])

    def shutdown(self):
        import ray
        for w in self._workers:
            ray.kill(w)
        self._workers = []
        if getattr(self, "_server", None):
            self._server.stop()

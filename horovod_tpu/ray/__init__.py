"""Ray integration (reference ``horovod/ray/runner.py:168`` RayExecutor,
``ray/elastic.py:150`` ElasticRayExecutor).

Gated: ray is not part of this image.  The executor contract is kept
API-compatible; actors come up through the same rendezvous + env
handoff as the CLI launcher.
"""


def _require_ray():
    try:
        import ray  # noqa: F401
    except ImportError as exc:
        raise ImportError(
            "horovod_tpu.ray requires ray, which is not installed in "
            "this environment") from exc


class HorovodWorker:
    """Actor body for one rank (reference ray/worker.py
    BaseHorovodWorker): carries rank identity, exposes host/node
    queries for placement bookkeeping, executes functions in-actor."""

    def __init__(self, world_rank=0, world_size=1, env=None):
        import os

        self.world_rank = world_rank
        self.world_size = world_size
        os.environ.update(env or {})

    def hostname(self):
        import socket

        return socket.gethostname()

    def node_id(self):
        try:
            import ray

            return ray.get_runtime_context().get_node_id()
        except Exception:  # noqa: BLE001 — fake/old ray
            return self.hostname()

    def update_env_vars(self, env):
        import os

        os.environ.update({k: str(v) for k, v in env.items()})

    def env_vars(self):
        import os

        return dict(os.environ)

    def execute(self, fn, *a, **kw):
        return fn(*a, **kw)


def _probe_coordinator_address():
    """Runs INSIDE the rank-0 actor: the jax.distributed coordination
    service binds on rank 0's host, so rank 0 probes a free port there
    and reports its own reachable IP (a port probed on the driver could
    be taken — or unroutable — on the worker node; same fix as
    ``spark/runner.py:_spark_task_body``)."""
    from ..runner.http.http_server import free_port, local_ip

    return f"{local_ip()}:{free_port()}"


class RayExecutor:
    """Launch a horovod_tpu job on Ray actors (reference
    ray/runner.py:168-420): worker placement goes through the
    reference's two strategies (strategy.py here) —

    * ``num_hosts`` x ``num_workers_per_host`` -> ColocatedStrategy
      (balanced hosts, STRICT_SPREAD bundles; the TPU-pod shape), or
    * ``num_workers`` -> PGStrategy (PACK, honors an ambient
      placement group — Ray Tune trials).
    """

    @classmethod
    def create_settings(cls, timeout_s=30, ssh_identity_file=None,
                        ssh_str=None, placement_group_timeout_s=100,
                        nics=None):
        """Mini settings object (reference ray/runner.py:211): ssh
        identity is used for multi-host worker spawns; nics are N/A on
        TPU pods (kept for signature parity)."""
        import os as _os

        if ssh_str and ssh_identity_file \
                and not _os.path.exists(ssh_identity_file):
            with open(ssh_identity_file, "w") as f:
                _os.chmod(ssh_identity_file, 0o600)
                f.write(ssh_str)
        return {"timeout_s": timeout_s,
                "ssh_identity_file": ssh_identity_file,
                "placement_group_timeout_s": placement_group_timeout_s,
                "nics": nics}

    def __init__(self, settings=None, num_workers=None, num_hosts=None,
                 num_workers_per_host=1, cpus_per_worker=1,
                 use_gpu=False, gpus_per_worker=None,
                 use_current_placement_group=True,
                 placement_group_timeout_s=100, **kwargs):
        _require_ray()
        if settings:
            placement_group_timeout_s = settings.get(
                "placement_group_timeout_s", placement_group_timeout_s)
        if num_workers is None and num_hosts is None:
            raise ValueError(
                "set either num_workers (PACK) or num_hosts + "
                "num_workers_per_host (colocated)")
        if num_workers is not None and num_hosts is not None:
            # the two specs would disagree about world size (the
            # reference runner rejects the combination the same way)
            raise ValueError(
                "num_workers and num_hosts are mutually exclusive")
        self.num_hosts = num_hosts
        self.num_workers_per_host = num_workers_per_host
        self.cpus_per_worker = cpus_per_worker
        self.use_gpu = use_gpu
        self.gpus_per_worker = gpus_per_worker
        self.use_current_placement_group = use_current_placement_group
        self.pg_timeout = placement_group_timeout_s
        self._num_workers = num_workers
        self.strategy = None
        self._workers = []

    @property
    def num_workers(self):
        if self._num_workers is not None:
            return self._num_workers
        return self.num_hosts * self.num_workers_per_host

    def _make_strategy(self):
        from .strategy import ColocatedStrategy, PGStrategy

        if self.num_hosts is not None:
            return ColocatedStrategy(
                num_hosts=self.num_hosts,
                num_workers_per_host=self.num_workers_per_host,
                use_gpu=self.use_gpu,
                cpus_per_worker=self.cpus_per_worker,
                gpus_per_worker=self.gpus_per_worker,
                placement_group_timeout_s=self.pg_timeout)
        return PGStrategy(
            num_workers=self._num_workers, use_gpu=self.use_gpu,
            cpus_per_worker=self.cpus_per_worker,
            gpus_per_worker=self.gpus_per_worker,
            force_create_placement_group=(
                not self.use_current_placement_group),
            placement_group_timeout_s=self.pg_timeout)

    def start(self, executable_cls=None, executable_args=None,
              executable_kwargs=None, extra_env_vars=None):
        import os as _os
        import secrets as _secrets

        from ..runner.http.http_server import (
            RendezvousServer, autotune_kwargs, local_ip,
        )

        secret_hex = _secrets.token_hex(16)
        at_env = dict(_os.environ)
        at_env.update(extra_env_vars or {})
        self._server = RendezvousServer(
            secret=bytes.fromhex(secret_hex),
            world_size=self.num_workers,
            **autotune_kwargs(at_env))
        port = self._server.start()
        addr = local_ip()

        self.strategy = self._make_strategy()
        base_env = dict(extra_env_vars or {})
        base_env.update({
            "HOROVOD_CONTROLLER": "http",
            "HOROVOD_GLOO_RENDEZVOUS_ADDR": addr,
            "HOROVOD_GLOO_RENDEZVOUS_PORT": str(port),
            "HOROVOD_SECRET_KEY": secret_hex,
            "HOROVOD_TPU_NUM_PROCS": str(self.num_workers),
            "HOROVOD_TPU_RANKS_PER_PROC": "1",
        })
        self._workers, self._node_workers =             self.strategy.create_workers(HorovodWorker, base_env)
        import ray

        # The coordination service binds on RANK 0's host — probe the
        # port and learn the reachable address in that actor, not on
        # the driver (which may be a different machine entirely).
        coordinator = ray.get(
            self._workers[0].execute.remote(_probe_coordinator_address))
        # Host topology from the actors' actual node placement.  Rank
        # order must GROUP by host (the engine's two-level mesh rejects
        # interleaved layouts, parallel/mesh.py): PACK placement can
        # land actors interleaved across nodes, so reorder the worker
        # list host-grouped (stable within a host) before stamping
        # ranks, instead of merely recording the interleaving.
        node_ids = ray.get([w.node_id.remote() for w in self._workers])
        host_index = {}
        for nid in node_ids:
            host_index.setdefault(nid, len(host_index))
        order = sorted(range(len(self._workers)),
                       key=lambda i: (host_index[node_ids[i]], i))
        self._workers = [self._workers[i] for i in order]
        host_of_rank = ",".join(
            str(host_index[node_ids[i]]) for i in order)
        # per-rank identity rides a post-placement env update (the
        # reference does the same for CUDA_VISIBLE_DEVICES fan-out)
        ray.get([
            w.update_env_vars.remote({
                "HOROVOD_TPU_PROC_INDEX": i,
                "HOROVOD_RANK": i,
                "HOROVOD_TPU_COORDINATOR": coordinator,
                "HOROVOD_TPU_HOST_OF_RANK": host_of_rank,
            })
            for i, w in enumerate(self._workers)])

    def run(self, fn, args=None, kwargs=None):
        import ray
        return ray.get([w.execute.remote(fn, *(args or ()),
                                         **(kwargs or {}))
                        for w in self._workers])

    def execute(self, fn):
        import ray
        return ray.get([w.execute.remote(fn) for w in self._workers])

    def run_remote(self, fn, args=None, kwargs=None):
        """Launch without blocking; returns the ray futures (reference
        runner.py run_remote)."""
        return [w.execute.remote(fn, *(args or ()), **(kwargs or {}))
                for w in self._workers]

    def execute_single(self, fn):
        """Run ``fn`` on the rank-0 worker only (reference runner.py
        execute_single)."""
        import ray
        return ray.get(self._workers[0].execute.remote(fn))

    def shutdown(self):
        import ray
        # kill actors explicitly: with an ambient placement group the
        # strategy does not remove the group, and lingering handles
        # (incl. _node_workers) would otherwise pin trial resources
        for w in self._workers:
            try:
                ray.kill(w)
            except Exception:  # noqa: BLE001 — already dead / fake ray
                pass
        self._workers = []
        self._node_workers = []
        if self.strategy is not None:
            self.strategy.shutdown()
        if getattr(self, "_server", None):
            self._server.stop()


#: Reference export name (``horovod/ray/__init__.py`` re-exports the
#: actor body as BaseHorovodWorker).
BaseHorovodWorker = HorovodWorker


class RayHostDiscovery:
    """Discovery over the Ray autoscaler (reference
    ray/elastic.py:25-70 RayHostDiscovery / elastic_v2.py
    RayHostDiscovery): each alive Ray node with enough CPUs offers
    ``slots`` worker slots, keyed by node IP."""

    def __init__(self, use_gpu=False, cpus_per_slot=1,
                 gpus_per_slot=0):
        _require_ray()
        self.cpus_per_slot = cpus_per_slot
        self.use_gpu = use_gpu
        self.gpus_per_slot = gpus_per_slot or (1 if use_gpu else 0)

    def find_available_hosts_and_slots(self):
        import ray
        hosts = {}
        for node in ray.nodes():
            if not node.get("Alive"):
                continue
            res = node.get("Resources", {})
            slots = int(res.get("CPU", 0) // self.cpus_per_slot)
            if self.use_gpu:
                slots = min(slots, int(res.get("GPU", 0)
                                       // max(self.gpus_per_slot, 1)))
            if slots > 0:
                hosts[node["NodeManagerAddress"]] = slots
        return hosts


class ElasticRayExecutor:
    """Elastic executor over Ray (reference ``ray/elastic.py:150``
    ElasticRayExecutor): Ray-autoscaler discovery drives the same
    ElasticDriver the CLI elastic launcher uses; worker processes come
    up through `ray job`-hosted shells so a membership change re-forms
    the mesh exactly like ``horovodrun --min-np/--max-np``.

    ``run(fn)`` executes ``fn`` under ``hvd.elastic`` semantics on each
    worker: the user wraps training in ``hvd.elastic.run`` with a
    ``State`` and commits, as in the reference's usage.
    """

    @staticmethod
    def create_settings(min_np=1, max_np=None, reset_limit=None,
                        elastic_timeout=600, cpus_per_slot=1,
                        use_gpu=False, override_discovery=None):
        return {"min_np": min_np, "max_np": max_np,
                "reset_limit": reset_limit,
                "elastic_timeout": elastic_timeout,
                "cpus_per_slot": cpus_per_slot, "use_gpu": use_gpu,
                "override_discovery": override_discovery}

    def __init__(self, settings, cpus_per_slot=None, use_gpu=None,
                 env_vars=None):
        _require_ray()
        self.settings = dict(settings)
        if cpus_per_slot is not None:
            self.settings["cpus_per_slot"] = cpus_per_slot
        if use_gpu is not None:
            self.settings["use_gpu"] = use_gpu
        self.env_vars = env_vars or {}
        self._discovery = None

    def start(self):
        self._discovery = self.settings.get("override_discovery") or \
            RayHostDiscovery(
                use_gpu=self.settings.get("use_gpu", False),
                cpus_per_slot=self.settings.get("cpus_per_slot", 1))

    def run(self, worker_fn, callbacks=None):
        """Run ``worker_fn`` elastically: one worker per discovered
        slot (ssh spawn for remote Ray nodes — autoscaler deployments
        share an ssh fabric), rounds re-forming on membership change.
        ``elastic_timeout`` bounds waiting for min_np slots, never a
        healthy training run.

        ``callbacks`` receive the round-lifecycle events
        (hosts_updated / round_start / worker_start / worker_exit) as
        dicts — the reference's ElasticRayExecutor callback surface
        (ray/elastic_v2.py:402-470)."""
        from ..runner.elastic_api import run_elastic_fn

        run_elastic_fn(
            worker_fn, discovery=self._discovery,
            min_np=self.settings.get("min_np", 1),
            max_np=self.settings.get("max_np"),
            env=dict(self.env_vars),
            reset_limit=self.settings.get("reset_limit"),
            start_timeout=self.settings.get("elastic_timeout"),
            elastic_timeout=self.settings.get("elastic_timeout") or 600,
            callbacks=callbacks)

    def shutdown(self):
        self._discovery = None

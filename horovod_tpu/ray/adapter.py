"""Ray adapter interface (reference ``horovod/ray/adapter.py``):
the strategy-agnostic start/execute/shutdown surface RayExecutor
drives, plus the shared worker-resource params."""

from abc import ABC, abstractmethod
from dataclasses import dataclass
from typing import Optional


@dataclass
class BaseParams:
    """Reference adapter.py:6."""

    cpus_per_worker: int = 1
    use_gpu: bool = False
    gpus_per_worker: Optional[int] = None

    def __post_init__(self):
        if self.gpus_per_worker and not self.use_gpu:
            raise ValueError(
                "gpus_per_worker is set, but use_gpu is False. "
                "use_gpu must be True if gpus_per_worker is set.")
        if self.use_gpu and isinstance(self.gpus_per_worker, int) \
                and self.gpus_per_worker < 1:
            raise ValueError(
                f"gpus_per_worker must be >= 1: "
                f"Got {self.gpus_per_worker}.")
        self.gpus_per_worker = self.gpus_per_worker or \
            int(self.use_gpu)


class Adapter(ABC):
    """Reference adapter.py:22."""

    @abstractmethod
    def start(self, executable_cls=None, executable_args=None,
              executable_kwargs=None, extra_env_vars=None):
        ...

    @abstractmethod
    def execute(self, fn, callbacks=None):
        ...

    @abstractmethod
    def run(self, fn, args=None, kwargs=None, callbacks=None):
        ...

    @abstractmethod
    def run_remote(self, fn, args=None, kwargs=None):
        ...

    @abstractmethod
    def execute_single(self, fn):
        ...

    @abstractmethod
    def shutdown(self):
        ...

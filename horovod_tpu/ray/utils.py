"""Ray helpers (reference ``horovod/ray/utils.py``)."""


def map_blocking(fn, collection):
    """``ray.get`` over ``fn`` mapped on the collection (reference
    utils.py:90)."""
    import ray
    return ray.get([fn(w) for w in collection])


def nics_to_env_var(nics):
    """Reference utils.py:82."""
    return {
        "HOROVOD_GLOO_IFACE": list(nics)[0] if nics else "",
        "NCCL_SOCKET_IFNAME": ",".join(nics or []),
    }


def detect_nics(settings, all_host_names=None, node_workers=None):
    """NIC detection (reference utils.py:36 probes actors on every
    host).  TPU pods share one fabric, so the probe reduces to the
    driver-side resolution: an explicit ``settings.nics`` wins,
    single-host jobs get the loopback set, multi-host jobs need no
    interface constraint (the control plane is address-based)."""
    from ..runner.driver.driver_service import get_common_interfaces

    hosts = list(all_host_names or [])
    nics = get_common_interfaces(settings, hosts)
    return list(nics)

"""Worker placement strategies over Ray placement groups.

Reference: ``horovod/ray/strategy.py:1-223`` — ``ColocatedStrategy``
(balanced hosts via one aggregate bundle per host + STRICT_SPREAD) and
``PGStrategy`` (one bundle per worker, PACK, honoring an ambient
placement group).  The TPU build keeps the same two shapes: colocated
placement is what keeps a host's workers on that host's TPU chips
(local ranks must sit with their chips for ICI to be reachable), and
PACK minimizes cross-host DCN hops for small jobs.

``ray`` is imported lazily inside methods so the classes are
constructible and unit-testable without ray installed (a fake module
in ``sys.modules`` suffices — the tests assert bundle layouts).
"""

import logging
from collections import defaultdict

logger = logging.getLogger("horovod_tpu.ray")


def create_placement_group(resources_per_bundle, num_bundles,
                           pg_timeout, pg_strategy):
    """Allocate + await a placement group (reference strategy.py:13-30)."""
    import ray

    bundles = [dict(resources_per_bundle) for _ in range(num_bundles)]
    pg = ray.util.placement_group(bundles, strategy=pg_strategy)
    ready, _ = ray.wait([pg.ready()], timeout=pg_timeout)
    if not ready:
        # remove the pending group or its reservation keeps queueing
        # against the very resources a retry would need
        ray.util.remove_placement_group(pg)
        raise TimeoutError(
            "Placement group creation timed out; cluster lacks "
            f"resources for {bundles} (available: "
            f"{ray.available_resources()})")
    return pg, bundles


class BaseStrategy:
    """Common surface (reference strategy.py:33-62)."""

    placement_group = None
    workers = None

    def create_workers(self, worker_cls, worker_env):
        raise NotImplementedError

    @property
    def num_workers(self):
        raise NotImplementedError

    @classmethod
    def get_node_workers(cls, workers):
        """One worker per node (the reference uses these for NIC
        probing; here they anchor per-host work like data staging)."""
        import ray

        hostnames = ray.get([w.hostname.remote() for w in workers])
        by_host = {}
        for hostname, worker in zip(hostnames, workers):
            by_host.setdefault(hostname, worker)
        return list(by_host.values())

    def shutdown(self):
        import ray

        if self.placement_group:
            ray.util.remove_placement_group(self.placement_group)
        self.workers = []
        self.placement_group = None


class ColocatedStrategy(BaseStrategy):
    """Balanced hosts: one aggregate bundle per host, STRICT_SPREAD so
    every bundle lands on a distinct node, then
    ``num_workers_per_host`` workers pinned into each bundle
    (reference strategy.py:65-137).  This is the TPU-pod shape: a
    host's workers must sit with the host's chips."""

    def __init__(self, *, settings=None, num_hosts,
                 num_workers_per_host, use_gpu=False, cpus_per_worker=1,
                 gpus_per_worker=None, placement_group_timeout_s=100):
        self.settings = settings
        self.num_hosts = int(num_hosts)
        self.num_workers_per_host = int(num_workers_per_host)
        self.use_gpu = use_gpu
        self.cpus_per_worker = cpus_per_worker
        self.gpus_per_worker = gpus_per_worker or 1
        self.pg_timeout = getattr(settings, "placement_group_timeout_s",
                                  placement_group_timeout_s)

    @property
    def num_workers(self):
        return self.num_hosts * self.num_workers_per_host

    def _resources_per_host(self):
        res = {"CPU": self.cpus_per_worker * self.num_workers_per_host}
        if self.use_gpu:
            res["GPU"] = self.gpus_per_worker * self.num_workers_per_host
        return res

    def create_workers(self, worker_cls, worker_env=None):
        """Returns (workers, node_workers); worker ``i`` has
        world_rank ``i``, grouped per host bundle."""
        import ray

        self.placement_group, bundles = create_placement_group(
            resources_per_bundle=self._resources_per_host(),
            num_bundles=self.num_hosts,
            pg_timeout=self.pg_timeout,
            pg_strategy="STRICT_SPREAD")
        self.workers = []
        remote_cls = ray.remote(worker_cls)
        for bundle_index in range(len(bundles)):
            for i in range(self.num_workers_per_host):
                options = remote_cls.options(
                    num_cpus=self.cpus_per_worker,
                    num_gpus=self.gpus_per_worker * int(self.use_gpu),
                    placement_group_capture_child_tasks=False,
                    placement_group=self.placement_group,
                    placement_group_bundle_index=bundle_index)
                rank = self.num_workers_per_host * bundle_index + i
                self.workers.append(options.remote(
                    world_rank=rank, world_size=self.num_workers,
                    env=dict(worker_env or {})))
        return self.workers, self.get_node_workers(self.workers)


class PGStrategy(BaseStrategy):
    """One bundle per worker, PACK (reference strategy.py:139-223):
    dense placement without a balanced-hosts guarantee; reuses the
    ambient placement group when the caller already runs inside one
    (Ray Tune trials do)."""

    def __init__(self, *, settings=None, num_workers, use_gpu=False,
                 cpus_per_worker=1, gpus_per_worker=None,
                 placement_group=None,
                 force_create_placement_group=False,
                 placement_group_timeout_s=100):
        self.settings = settings
        self._num_workers = int(num_workers)
        self.use_gpu = use_gpu
        self.cpus_per_worker = cpus_per_worker
        self.gpus_per_worker = gpus_per_worker or 1
        self.pg_timeout = getattr(settings, "placement_group_timeout_s",
                                  placement_group_timeout_s)
        if force_create_placement_group:
            self.placement_group = None
        else:
            self.placement_group = placement_group or \
                self._current_placement_group()
        self._created_placement_group = False

    @staticmethod
    def _current_placement_group():
        try:
            from ray.util.placement_group import \
                get_current_placement_group
            return get_current_placement_group()
        except Exception:  # noqa: BLE001 — fake/old ray
            return None

    @property
    def num_workers(self):
        return self._num_workers

    def resources_per_worker(self):
        res = {"CPU": self.cpus_per_worker}
        if self.use_gpu:
            res["GPU"] = self.gpus_per_worker
        return res

    def create_workers(self, worker_cls, worker_env=None):
        import ray

        if not self.placement_group:
            self.placement_group, _ = create_placement_group(
                resources_per_bundle=self.resources_per_worker(),
                num_bundles=self.num_workers,
                pg_timeout=self.pg_timeout,
                pg_strategy="PACK")
            self._created_placement_group = True
        self.workers = []
        remote_cls = ray.remote(worker_cls)
        for worker_index in range(self.num_workers):
            options = remote_cls.options(
                num_cpus=self.cpus_per_worker,
                num_gpus=self.gpus_per_worker * int(self.use_gpu),
                placement_group_capture_child_tasks=False,
                placement_group=self.placement_group,
                placement_group_bundle_index=(
                    worker_index if self._created_placement_group
                    else -1))
            self.workers.append(options.remote(
                world_rank=worker_index, world_size=self.num_workers,
                env=dict(worker_env or {})))
        return self.workers, self.get_node_workers(self.workers)

    def shutdown(self):
        import ray

        if self._created_placement_group and self.placement_group:
            ray.util.remove_placement_group(self.placement_group)
            self.placement_group = None
        self.workers = []


def group_workers_by_node(workers):
    """{node_id: [workers]} — the reference's per-node env fan-out
    (CUDA_VISIBLE_DEVICES aggregation, strategy.py:199-216) keyed the
    same way; TPU pods use it to hand each host its chip set."""
    import ray

    node_ids = ray.get([w.node_id.remote() for w in workers])
    grouped = defaultdict(list)
    for worker, node_id in zip(workers, node_ids):
        grouped[node_id].append(worker)
    return dict(grouped)

"""Rank-0 log forwarding (reference ``horovod/ray/ray_logger.py``):
workers push dicts onto a queue configured by the driver; callbacks
consume them."""

_queue = None
_warning_raised = False

logger = __import__("logging").getLogger("horovod_tpu.ray")


def configure(queue):
    """Reference ray_logger.py:14."""
    global _queue
    _queue = queue


def log(info_dict):
    """Reference ray_logger.py:25 — silently drops (with one warning)
    when no queue is configured."""
    global _warning_raised
    if _queue is None:
        if not _warning_raised:
            logger.warning(
                "ray_logger.log called before configure(); "
                "log entries are dropped")
            _warning_raised = True
        return
    _queue.put(info_dict)


def warning_raised():
    return _warning_raised

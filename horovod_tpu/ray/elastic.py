"""Reference import path ``horovod.ray.elastic`` — the v1 elastic
surface: executor + host discovery (live implementations in the
package root) and the chaos TestDiscovery from elastic_v2."""

import logging

from . import ElasticRayExecutor, RayHostDiscovery  # noqa: F401
from .elastic_v2 import TestDiscovery  # noqa: F401

logger = logging.getLogger("horovod_tpu.ray")

"""Elastic ray adapter (reference ``horovod/ray/elastic_v2.py``).

``ElasticParams``/``ElasticAdapter`` wrap the package root's
ElasticRayExecutor (KV-rendezvous elastic flow); ``TestDiscovery``
injects scheduled host churn for elastic testing, mirroring the
reference's chaos discovery."""

import logging
import random
import time
from dataclasses import dataclass
from typing import Optional, Tuple

from . import ElasticRayExecutor, RayHostDiscovery
from .adapter import Adapter, BaseParams

logger = logging.getLogger("horovod_tpu.ray")


class TestDiscovery(RayHostDiscovery):
    """Scheduled host churn on top of real discovery (reference
    elastic_v2.py:74): every ``change_frequency_s`` a host is added
    back or removed, bounded by min/max."""

    def __init__(self, min_hosts, max_hosts, change_frequency_s,
                 use_gpu=False, cpus_per_worker=1, gpus_per_worker=1,
                 verbose=True, _graceful=True, seed=None):
        super().__init__(use_gpu=use_gpu,
                         cpus_per_slot=cpus_per_worker,
                         gpus_per_slot=gpus_per_worker)
        self._min_hosts = min_hosts
        self._max_hosts = max_hosts
        self._change_frequency_s = change_frequency_s
        self._graceful = _graceful
        self._last_reset_t = None
        self._removed_hosts = set()
        self._rng = random.Random(seed)
        self.verbose = verbose

    def add_host(self, hosts):
        available = self._removed_hosts & set(hosts)
        if available:
            self._removed_hosts.remove(
                self._rng.choice(sorted(available)))
        elif self.verbose:
            print("No hosts to add.")

    def remove_host(self, hosts):
        good = [h for h in hosts if h not in self._removed_hosts]
        if good:
            self._removed_hosts.add(self._rng.choice(good))

    def change_hosts(self, hosts):
        self._removed_hosts &= set(hosts)
        current = len(hosts) - len(self._removed_hosts)
        if current <= self._min_hosts:
            self.add_host(hosts)
        elif current >= self._max_hosts:
            self.remove_host(hosts)
        elif self._rng.random() < 0.5:
            self.add_host(hosts)
        else:
            self.remove_host(hosts)

    def find_available_hosts_and_slots(self):
        t = time.time()
        if self._last_reset_t is None:
            self._last_reset_t = t
        hosts = super().find_available_hosts_and_slots()
        if t - self._last_reset_t >= self._change_frequency_s:
            self.change_hosts(hosts)
            self._last_reset_t = t
        return {h: s for h, s in hosts.items()
                if h not in self._removed_hosts}


@dataclass
class ElasticParams(BaseParams):
    """Reference elastic_v2.py:151."""

    min_workers: int = 1
    max_workers: Optional[int] = None
    reset_limit: Optional[int] = None
    cooldown_range: Optional[Tuple[int, int]] = None
    elastic_timeout: int = 600
    override_discovery: bool = True

    @property
    def elastic(self):
        return True

    @property
    def adapter(self):
        return ElasticAdapter


class ElasticAdapter(Adapter):
    """Reference elastic_v2.py:197 — drives the elastic executor."""

    def __init__(self, params, settings=None, discovery=None):
        self.params = params
        self.settings = settings
        self.discovery = discovery
        self._executor = None
        self._extra_env = None

    def start(self, executable_cls=None, executable_args=None,
              executable_kwargs=None, extra_env_vars=None):
        self._extra_env = extra_env_vars
        settings = self.settings or \
            ElasticRayExecutor.create_settings(
                min_np=self.params.min_workers,
                max_np=self.params.max_workers,
                reset_limit=self.params.reset_limit,
                elastic_timeout=self.params.elastic_timeout,
                cpus_per_slot=self.params.cpus_per_worker,
                use_gpu=self.params.use_gpu,
                override_discovery=self.discovery
                if self.params.override_discovery else None)
        self._executor = ElasticRayExecutor(
            settings, env_vars=extra_env_vars)
        self._executor.start()

    def run(self, fn, args=None, kwargs=None, callbacks=None):
        def bound():
            return fn(*(args or ()), **(kwargs or {}))

        return self._executor.run(bound, callbacks=callbacks)

    def execute(self, fn, callbacks=None):
        return self._executor.run(fn, callbacks=callbacks)

    def run_remote(self, fn, args=None, kwargs=None):
        raise RuntimeError(
            "run_remote is a static-job API; elastic jobs block in "
            "run() so membership changes can be handled")

    def execute_single(self, fn):
        raise RuntimeError(
            "execute_single is a static-job API; elastic jobs have "
            "no stable rank-0 actor")

    def shutdown(self):
        if self._executor is not None:
            self._executor.shutdown()

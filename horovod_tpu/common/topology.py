"""Rank topology: global / local (ICI) / cross (DCN).

The reference derives a 3-level topology from MPI communicators
(mpi/mpi_context.h:104-113: global_comm / local_comm / cross_comm) and
uses it for hierarchical and torus collectives.  On TPU the same levels
fall out of the platform: ranks on one host share ICI (local), hosts
connect over DCN (cross).

This module also owns the ALGORITHM vocabulary for topology-aware
reductions (the reference's ``HOROVOD_HIERARCHICAL_ALLREDUCE`` /
``HOROVOD_TORUS_ALLREDUCE`` toggles, ``nccl_operations.cc:606-830``):

* ``flat``          — one collective over all ranks (the default).
* ``hierarchical``  — reducescatter over each host's ranks (ICI),
  allreduce of the shards across hosts (DCN), allgather back.  Only
  1/local_size of the logical bytes cross the slow hop.
* ``torus``         — the same two-stage decomposition over a 2-D
  factorization of the rank space (Google's 2-D torus allreduce on
  TPU-v3 pods, arXiv:1909.09756), for multi-axis device meshes.

:func:`plan_decomposition` turns (algorithm, topology, set ranks) into
the inner-axis size the executors reshape their meshes by — or
``None`` when the request degrades to flat (heterogeneous hosts,
prime world sizes, tiny sets), exactly like the reference's
``is_homogeneous`` fallback.
"""

from dataclasses import dataclass, field
from typing import List

#: algorithm vocabulary, in autotune-grid order (core/autotune.py)
ALGORITHMS = ("flat", "hierarchical", "torus")

_ALGO_ALIASES = {
    # None / "" = UNSET (a process-wide default may apply); an
    # explicit 'flat' spelling = "one flat collective, overriding any
    # default" — the same unset-vs-explicit split wire_dtype carries
    None: None, "": None,
    "flat": "flat", "none": "flat", "ring": "flat",
    "hier": "hierarchical", "hierarchical": "hierarchical",
    "torus": "torus", "2d": "torus",
}


def normalize_algorithm(algorithm):
    """Canonicalize an algorithm spec -> None (unset) | 'flat'
    (explicit) | 'hierarchical' | 'torus'."""
    key = algorithm.strip().lower() if isinstance(algorithm, str) \
        else algorithm
    try:
        return _ALGO_ALIASES[key]
    except KeyError:
        raise ValueError(
            f"unknown allreduce algorithm {algorithm!r}: expected one "
            f"of {ALGORITHMS}")


def torus_inner(n):
    """Largest factor of ``n`` that is <= sqrt(n): the near-square 2-D
    factorization the torus decomposition reshapes the rank space by.
    Returns 1 for primes / n < 4 (no useful second axis)."""
    best = 1
    f = 1
    while f * f <= n:
        if n % f == 0:
            best = f
        f += 1
    return best


def _grouped_local_size(topology, ranks):
    """Per-host rank count when the set's ranks are grouped by host
    with the SAME count on every spanned host (the reference's
    ``is_homogeneous`` gate); None otherwise (or single-host /
    unknown topology)."""
    if topology is None:
        return None
    hosts = []
    for r in ranks:
        try:
            hosts.append(topology.host_of_rank[r])
        except IndexError:
            return None
    counts = {}
    for h in hosts:
        counts[h] = counts.get(h, 0) + 1
    if len(counts) < 2 or len(set(counts.values())) != 1:
        return None       # single host or heterogeneous
    # ranks must be grouped by host (the launcher emits hosts in slot
    # order, so this holds for every launched job)
    if any(hosts[i] > hosts[i + 1] for i in range(len(hosts) - 1)):
        return None
    return len(ranks) // len(counts)


def plan_decomposition(algorithm, topology, ranks):
    """Inner-axis size for a 2-stage reduction over ``ranks``, or
    ``None`` when the algorithm degrades to flat.

    ``hierarchical`` needs the set's ranks grouped by host with the
    SAME count on every spanned host (the reference's
    ``is_homogeneous`` gate on ``NCCLHierarchicalAllreduce``) and
    more than one host; ``torus`` needs a composite set size.  The
    inner axis is the fast (ICI) hop: host-local ranks for
    hierarchical, the near-square factor for torus — and on
    multi-host jobs the torus inner axis is CONSTRAINED to divisors
    of the per-host rank count so its heavy reducescatter/allgather
    hops never straddle a DCN boundary (otherwise the "fast" axis
    would be the slow one and the cross-byte accounting a lie)."""
    algorithm = normalize_algorithm(algorithm)
    if algorithm in (None, "flat"):
        return None
    n = len(ranks)
    if n < 4:
        return None
    local = _grouped_local_size(topology, ranks)
    if algorithm == "torus":
        if local is None:
            # single host (or no host map): any near-square split of
            # the one ICI domain works
            if topology is not None and topology.num_hosts > 1:
                # spans hosts but heterogeneous/ungrouped: no safe
                # inner axis
                return None
            inner = torus_inner(n)
            return inner if inner > 1 else None
        # multi-host: inner must divide the per-host count so each
        # inner group stays on one host; pick the divisor nearest the
        # near-square ideal, falling back to the whole host (= the
        # hierarchical split) when the host count itself is the only
        # intra-host factor
        divisors = [d for d in range(2, local + 1) if local % d == 0]
        if not divisors:
            return None
        near_square = [d for d in divisors if d * d <= n]
        return max(near_square) if near_square else min(divisors)
    # hierarchical: the whole host is the inner axis
    return local


def carve_stage_ranks(topology, n_stages, ranks=None):
    """Partition ``ranks`` into ``n_stages`` equal, contiguous pipeline
    stages, preferring HOST-ALIGNED boundaries so the pp hops —
    activations and activation gradients, the pipeline's only
    steady-state cross-stage traffic — land on the cross-host/DCN hop
    while each stage's dp×tp collectives stay on intra-host ICI
    (arXiv:1909.09756's pod layout; the pp analogue of what
    :func:`plan_decomposition` does for 2-stage reductions: slow
    traffic on the outer hop, heavy traffic on the inner).

    Stages must be EQUAL-SIZED (activations flow between
    corresponding (dp, tp) peers of adjacent stages; unequal widths
    would need a re-shard at every boundary), so the partition is the
    contiguous equal split of the host-grouped rank list — which IS
    host-aligned whenever any host-aligned equal partition exists,
    including heterogeneous host:slots layouts (e.g. slots 3+1+1+3 at
    pp=2: the boundary after the 4th rank falls between hosts).  When no
    boundary lands on a host edge (or there is no host map) the same
    split still runs, just with pp traffic riding ICI — reported via
    the returned flag so callers can warn.

    Returns ``(stage_ranks, host_aligned)``: a list of ``n_stages``
    rank lists plus whether every boundary fell on a host boundary.
    """
    n_stages = int(n_stages)
    if ranks is None:
        ranks = list(range(topology.size if topology is not None else 0))
    ranks = list(ranks)
    if n_stages < 1:
        raise ValueError(f"n_stages must be >= 1, got {n_stages}")
    if not ranks or len(ranks) % n_stages != 0:
        raise ValueError(
            f"{len(ranks)} ranks not divisible into {n_stages} "
            f"equal pipeline stages")
    per = len(ranks) // n_stages
    stages = [ranks[i * per:(i + 1) * per] for i in range(n_stages)]
    if topology is None or n_stages == 1:
        return stages, n_stages == 1
    try:
        hosts = [topology.host_of_rank[r] for r in ranks]
    except IndexError:
        return stages, False
    # host-aligned only meaningful when ranks arrive grouped by host
    # (the launcher's slot order)
    if any(hosts[i] > hosts[i + 1] for i in range(len(hosts) - 1)):
        return stages, False
    aligned = all(i == len(ranks) or hosts[i - 1] != hosts[i]
                  for i in range(per, len(ranks), per))
    return stages, aligned


@dataclass
class Topology:
    """Static rank layout for one job."""
    size: int
    # host index per global rank; threads-mode jobs are single-host.
    host_of_rank: List[int] = field(default_factory=list)

    def __post_init__(self):
        if not self.host_of_rank:
            self.host_of_rank = [0] * self.size

    @property
    def num_hosts(self):
        return max(self.host_of_rank) + 1 if self.host_of_rank else 1

    def local_ranks(self, host):
        return [r for r, h in enumerate(self.host_of_rank) if h == host]

    def local_rank(self, rank):
        host = self.host_of_rank[rank]
        return self.local_ranks(host).index(rank)

    def local_size(self, rank):
        return len(self.local_ranks(self.host_of_rank[rank]))

    def cross_rank(self, rank):
        """Rank among same-local-rank peers across hosts (reference
        cross_comm semantics: one rank per node at each local index).
        With heterogeneous slot counts, only hosts that HAVE this local
        index participate in the cross communicator."""
        lr = self.local_rank(rank)
        own = self.host_of_rank[rank]
        return sum(1 for h in range(own)
                   if len(self.local_ranks(h)) > lr)

    def cross_size(self, rank):
        lr = self.local_rank(rank)
        return sum(1 for h in range(self.num_hosts)
                   if len(self.local_ranks(h)) > lr)

    def is_homogeneous(self):
        sizes = {len(self.local_ranks(h)) for h in range(self.num_hosts)}
        return len(sizes) <= 1

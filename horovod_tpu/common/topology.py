"""Rank topology: global / local (ICI) / cross (DCN).

The reference derives a 3-level topology from MPI communicators
(mpi/mpi_context.h:104-113: global_comm / local_comm / cross_comm) and
uses it for hierarchical and torus collectives.  On TPU the same levels
fall out of the platform: ranks on one host share ICI (local), hosts
connect over DCN (cross).
"""

from dataclasses import dataclass, field
from typing import List


@dataclass
class Topology:
    """Static rank layout for one job."""
    size: int
    # host index per global rank; threads-mode jobs are single-host.
    host_of_rank: List[int] = field(default_factory=list)

    def __post_init__(self):
        if not self.host_of_rank:
            self.host_of_rank = [0] * self.size

    @property
    def num_hosts(self):
        return max(self.host_of_rank) + 1 if self.host_of_rank else 1

    def local_ranks(self, host):
        return [r for r, h in enumerate(self.host_of_rank) if h == host]

    def local_rank(self, rank):
        host = self.host_of_rank[rank]
        return self.local_ranks(host).index(rank)

    def local_size(self, rank):
        return len(self.local_ranks(self.host_of_rank[rank]))

    def cross_rank(self, rank):
        """Rank among same-local-rank peers across hosts (reference
        cross_comm semantics: one rank per node at each local index).
        With heterogeneous slot counts, only hosts that HAVE this local
        index participate in the cross communicator."""
        lr = self.local_rank(rank)
        own = self.host_of_rank[rank]
        return sum(1 for h in range(own)
                   if len(self.local_ranks(h)) > lr)

    def cross_size(self, rank):
        lr = self.local_rank(rank)
        return sum(1 for h in range(self.num_hosts)
                   if len(self.local_ranks(h)) > lr)

    def is_homogeneous(self):
        sizes = {len(self.local_ranks(h)) for h in range(self.num_hosts)}
        return len(sizes) <= 1

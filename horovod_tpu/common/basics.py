"""Global runtime state and rank contexts.

TPU-native analogue of the reference's ``HorovodBasics``
(horovod/common/basics.py:29-340) + the C ABI topology queries
(operations.cc:932-1404).  Where the reference loads a shared library
and keeps per-*process* rank state, the TPU runtime keeps per-*rank
contexts* inside the host process: a TPU host drives all of its chips
from one process, so ranks are thread/SPMD positions bound to mesh
devices rather than one OS process per accelerator.
"""

import os
import threading
from contextlib import contextmanager as _contextmanager

from . import env as env_mod
from .exceptions import HorovodInitError
from .topology import Topology

_state_lock = threading.RLock()
_engine = None
_topology = None
_timeline = None
_tls = threading.local()
_distributed_up = False
_elastic_round = 0
_metrics_server = None
_last_world_size = None


def _apply_platform_env(jax):
    """Re-assert JAX_PLATFORMS / JAX_NUM_CPU_DEVICES as config updates
    when backends are still uninitialized (see init())."""
    import os

    try:
        from jax._src import xla_bridge as _xb
        if _xb.backends_are_initialized():
            return
        plat = os.environ.get("JAX_PLATFORMS")
        if plat and jax.config.jax_platforms != plat:
            jax.config.update("jax_platforms", plat)
        ncpu = os.environ.get("JAX_NUM_CPU_DEVICES")
        if ncpu:
            try:
                jax.config.update("jax_num_cpu_devices", int(ncpu))
            except AttributeError:
                # older jax spells CPU-device partitioning only as an
                # XLA flag; an inherited flag (e.g. a parent test
                # process forcing 8 devices) must be OVERRIDDEN, not
                # appended to — the launcher's count is the contract
                flags = os.environ.get("XLA_FLAGS", "")
                flags = " ".join(
                    f for f in flags.split()
                    if not f.startswith(
                        "--xla_force_host_platform_device_count"))
                os.environ["XLA_FLAGS"] = (
                    flags + f" --xla_force_host_platform_device_count="
                    f"{int(ncpu)}").strip()
    except Exception:  # noqa: BLE001 — best effort: private API moved,
        # config absent on this jax version, or malformed env value;
        # init proceeds with whatever jax resolves from env alone
        return


def _elastic_rendezvous(rdv_addr, rdv_port, secret):
    """Fetch this worker's rank/size/coordinator for the next elastic
    round from the launcher's KV store (reference: rank/size re-fetched
    from the rendezvous server on every reset,
    gloo_context.cc:168-206)."""
    import json
    import time as _time
    from ..runner.http.http_client import StoreClient

    global _elastic_round
    client = StoreClient(rdv_addr, rdv_port, secret)
    identity = (f"{env_mod.get_str(env_mod.HOROVOD_HOSTNAME, 'localhost')}"
                f":{env_mod.get_int(env_mod.HOROVOD_LOCAL_RANK, 0)}")
    deadline = _time.monotonic() + env_mod.get_float(
        "HOROVOD_ELASTIC_TIMEOUT", 600.0)
    while _time.monotonic() < deadline:
        raw = client.get("/elastic/round", wait=10.0)
        if raw is None:
            continue
        info = json.loads(raw)
        if info.get("suspended") and info["round"] > _elastic_round:
            # the fleet controller preempted this job to zero
            # (docs/fleet.md "Suspension"): the last commit is in the
            # spill and the control plane stays up — a worker that
            # outlives its job's suspension self-aborts CLEANLY so the
            # driver's drain grace never has to SIGTERM it, and the
            # resumed round restores committed state in fresh workers
            import logging as _logging
            _logging.getLogger("horovod_tpu").warning(
                "job suspended at round %d; exiting cleanly (state "
                "committed to the spill)", info["round"])
            raise SystemExit(0)
        if info["round"] <= _elastic_round:
            _time.sleep(0.2)
            continue
        if identity not in info["assignments"]:
            # not part of this round (e.g. blacklisted); keep waiting —
            # the driver terminates us if we stay unassigned
            _time.sleep(0.5)
            continue
        _elastic_round = info["round"]
        # round-formation marker: the driver's elastic_timeout watches
        # these to distinguish a forming round from a stuck one
        client.put(f"/elastic/joined/{info['round']}/"
                   f"{info['assignments'][identity]}", b"1")
        return (info["assignments"][identity], info["size"],
                info["coordinator"], info["round"])
    raise HorovodInitError("timed out waiting for elastic rendezvous")


class RankContext:
    """Per-rank identity + auto-naming counters.  The reference names
    unnamed ops by a per-process incrementing id
    (e.g. allreduce.noname.1); here the counter is per rank context."""

    def __init__(self, rank):
        self.rank = rank
        self._counters = {}

    def next_name(self, op_name):
        n = self._counters.get(op_name, 0) + 1
        self._counters[op_name] = n
        return f"{op_name}.noname.{n}"


def _make_timeline(config, pid=0, num_ranks=1, proc_id=0):
    """Per-process timeline.  With ``HOROVOD_TIMELINE`` it writes a
    Chrome trace file; without one it still runs ring-only when the
    flight recorder is enabled (``HOROVOD_TRACE_RING_EVENTS``, default
    on) so stall warnings always have a last-N-events trace to dump.
    ``pid`` is the process's first global rank — merged traces key one
    lane group per rank on it (docs/timeline.md)."""
    from ..utils.timeline import Timeline
    if not (config.timeline_filename or config.trace_ring_events > 0):
        return None
    if num_ranks > 1:
        pname = (f"ranks {pid}-{pid + num_ranks - 1} "
                 f"(proc {proc_id})")
    else:
        pname = f"rank {pid}"
    return Timeline(config.timeline_filename,
                    config.timeline_mark_cycles,
                    pid=pid, process_name=pname,
                    ring_events=config.trace_ring_events)


def _record_resize_event(new_size):
    """Elastic membership change → telemetry.  Called AFTER the engine
    installed the round's fresh registry; ``_last_world_size``
    survives shutdown/init cycles so the direction is the true delta
    across rounds."""
    global _last_world_size
    from .. import telemetry

    prev, _last_world_size = _last_world_size, new_size
    if prev is None or prev == new_size:
        direction = "initial" if prev is None else "rebalance"
    else:
        direction = "up" if new_size > prev else "down"
    telemetry.registry().counter(
        telemetry.ELASTIC_RESIZE_FAMILY,
        telemetry.ELASTIC_RESIZE_HELP,
        labelnames=("direction",)).labels(direction=direction).inc()


def _start_metrics_endpoint(config, proc_index):
    """Per-worker Prometheus endpoint (HOROVOD_METRICS_PORT /
    ``horovodrun --metrics-port``).  Workers sharing a host offset the
    base port by their process index so every endpoint binds."""
    global _metrics_server
    if config.metrics_port <= 0 or _metrics_server is not None:
        return
    from ..telemetry import MetricsServer
    port = config.metrics_port + (proc_index or 0)
    server = MetricsServer(port=port)
    try:
        server.start()
    except OSError as exc:
        import logging
        logging.getLogger("horovod_tpu").warning(
            "could not bind metrics endpoint on port %d: %s "
            "(metrics still available via hvd.metrics() and the "
            "coordinator's /metrics)", port, exc)
        return
    _metrics_server = server


def init(comm=None, process_sets=None, num_ranks=None, devices=None):
    """Initialize the runtime (reference horovod_init,
    operations.cc:934 → InitializeHorovodOnce :856).

    * ``num_ranks`` — number of ranks this process hosts.  Defaults to
      ``HOROVOD_TPU_RANKS_PER_PROC`` (set by the launcher) or 1.
    * ``comm`` — list of global ranks (subset init), kept for API
      parity; MPI communicators are not a TPU concept.
    * ``process_sets`` — list of ProcessSet objects to register at
      init time (reference basics.py:51-148).

    Under the multi-process launcher (``HOROVOD_CONTROLLER=http``,
    reference gloo_run.py:66-103 env handoff), this also brings up
    ``jax.distributed`` so compiled collectives span processes, and a
    :class:`StoreController` for negotiation (reference
    GlooContext::Initialize, gloo/gloo_context.cc:150-216).
    """
    global _engine, _topology, _timeline
    with _state_lock:
        if _engine is not None:
            # Reference allows repeated init as a no-op once running.
            _bind_thread_if_unbound()
            return
        from ..core.engine import Engine

        # honor the runner's HOROVOD_LOG_LEVEL / HOROVOD_LOG_HIDE_TIME
        # handoff before anything logs (reference logging.cc reads the
        # same env in every worker)
        env_mod.setup_logging()

        controller = None
        rank_offset = 0
        global_size = None
        ranks_of_proc = None
        proc_index = 0
        multiproc = env_mod.get_str(env_mod.HOROVOD_CONTROLLER) == "http"
        if num_ranks is None:
            num_ranks = env_mod.get_int(env_mod.HOROVOD_TPU_RANKS_PER_PROC, 0)
        if not num_ranks:
            num_ranks = 1
        if multiproc:
            from ..core.store_controller import StoreController
            import jax

            # Honor the launcher's platform contract programmatically:
            # site configs (e.g. a preloaded PJRT plugin) can override
            # the JAX_PLATFORMS env var by force-setting the config at
            # interpreter start, which would leave every worker on the
            # wrong backend and break the global device view.  Only
            # possible before first backend use.
            _apply_platform_env(jax)

            rdv_addr = env_mod.get_str(env_mod.HOROVOD_RENDEZVOUS_ADDR,
                                       "127.0.0.1")
            rdv_port = env_mod.get_int(env_mod.HOROVOD_RENDEZVOUS_PORT, 0)
            secret = env_mod.get_str("HOROVOD_SECRET_KEY")
            secret = bytes.fromhex(secret) if secret else None
            round_id = 0
            if env_mod.get_bool("HOROVOD_ELASTIC"):
                proc_id, num_procs, coordinator, round_id = \
                    _elastic_rendezvous(rdv_addr, rdv_port, secret)
            else:
                proc_id = env_mod.get_int(env_mod.HOROVOD_TPU_PROC_INDEX, 0)
                num_procs = env_mod.get_int(env_mod.HOROVOD_TPU_NUM_PROCS, 1)
                coordinator = env_mod.get_str(
                    env_mod.HOROVOD_TPU_COORDINATOR)
            if num_procs > 1 and coordinator:
                # the TFRT CPU client can't launch cross-process
                # computations without a collectives transport; jax's
                # gloo implementation (when this jax has it) makes the
                # virtual CPU mesh behave like a real multi-host TPU
                # slice.  Must be set before the backends initialize.
                try:
                    if jax.config.jax_platforms in ("cpu", None) or \
                            env_mod.get_str(
                                env_mod.HOROVOD_TPU_PLATFORM) == "cpu":
                        jax.config.update(
                            "jax_cpu_collectives_implementation", "gloo")
                except Exception:  # pragma: no cover - option missing
                    pass
                jax.distributed.initialize(
                    coordinator_address=coordinator,
                    num_processes=num_procs, process_id=proc_id,
                    initialization_timeout=env_mod.get_int(
                        "HOROVOD_TPU_INIT_TIMEOUT", 60))
                global _distributed_up
                _distributed_up = True
            else:
                # size-1 round after an IN-PROCESS elastic resize: a
                # sticky gloo collectives flag from the previous
                # multi-proc round would make the fresh CPU backend
                # demand a distributed client that no longer exists
                # (make_gloo_tcp_collectives(None) TypeError) — reset
                # it before first backend use
                try:
                    current = getattr(
                        jax.config,
                        "jax_cpu_collectives_implementation",
                        None) or jax.config._read(
                        "jax_cpu_collectives_implementation")
                    if current == "gloo":
                        jax.config.update(
                            "jax_cpu_collectives_implementation",
                            None)
                except Exception:  # pragma: no cover - option missing
                    pass
            # heterogeneous host:slots jobs (reference -H h1:4,h2:2,
            # gloo_run.py:66-103) carry per-process rank counts; the
            # uniform path is the table [num_ranks] * num_procs
            rop = env_mod.get_str("HOROVOD_TPU_RANKS_OF_PROC")
            ranks_of_proc = None
            if rop:
                ranks_of_proc = [int(x) for x in rop.split(",")]
                if len(ranks_of_proc) != num_procs:
                    raise HorovodInitError(
                        f"HOROVOD_TPU_RANKS_OF_PROC has "
                        f"{len(ranks_of_proc)} entries for "
                        f"{num_procs} processes (stale environment?)")
                num_ranks = ranks_of_proc[proc_id]
                global_size = sum(ranks_of_proc)
                rank_offset = sum(ranks_of_proc[:proc_id])
            else:
                global_size = num_procs * num_ranks
                rank_offset = proc_id * num_ranks
            proc_index = proc_id
            if devices is None:
                import jax as _jax
                devices = _jax.devices()
            if len(devices) < global_size:
                raise HorovodInitError(
                    f"multi-process mode needs one device per rank: "
                    f"{len(devices)} devices < {global_size} ranks")
            hof = env_mod.get_str("HOROVOD_TPU_HOST_OF_RANK")
            counts = ranks_of_proc or [num_ranks] * num_procs
            if hof:
                # launcher's true host layout (one entry per process):
                # multiple processes on one host share local_rank space
                host_of_proc = [int(x) for x in hof.split(",")]
                if len(host_of_proc) != num_procs:
                    raise HorovodInitError(
                        f"HOROVOD_TPU_HOST_OF_RANK has "
                        f"{len(host_of_proc)} entries for {num_procs} "
                        f"processes (stale environment?)")
            else:
                host_of_proc = list(range(num_procs))
            host_of_rank = [host_of_proc[p]
                            for p in range(num_procs)
                            for _ in range(counts[p])]
            _topology = Topology(size=global_size,
                                 host_of_rank=host_of_rank)
            # per-host aggregator tier (docs/fault_tolerance.md): the
            # lowest-indexed proc of each host starts the aggregator
            # and publishes its address in the launcher's KV store;
            # every local proc routes its control traffic through it
            # (TieredStoreClient keeps the direct coordinator route
            # as the fallback)
            agg_addr = agg_port = None
            from ..runner.http import aggregator as agg_mod
            if agg_mod.tier_enabled() and num_procs > 1:
                agg_addr, agg_port, _agg_id = \
                    agg_mod.ensure_host_aggregator(
                        rdv_addr, rdv_port, secret, proc_id,
                        host_of_proc, round_id=round_id)
            controller = StoreController(
                rdv_addr, rdv_port, secret, proc_id, num_procs,
                num_ranks, round_id=round_id,
                agg_addr=agg_addr, agg_port=agg_port)
        else:
            _topology = Topology(size=num_ranks)
        if devices is None:
            import jax
            platform = env_mod.get_str(env_mod.HOROVOD_TPU_PLATFORM)
            devices = jax.devices(platform) if platform else jax.devices()
        config = env_mod.Config()
        # chaos fault injection (docs/fault_tolerance.md): parse the
        # plan BEFORE the engine exists so request-count triggers see
        # every fabric request, and hook the injector into the
        # controller's client (wire faults) + the engine (slow-rank).
        # A malformed plan raises here — a chaos test whose faults
        # silently failed to install would pass vacuously.
        chaos_injector = None
        if config.fault_plan:
            from .. import chaos as chaos_mod
            plan = chaos_mod.plan_from_env()
            if plan is not None and plan.events:
                chaos_injector = chaos_mod.install(
                    plan,
                    proc=controller.proc_id if controller else 0,
                    rank_offset=rank_offset,
                    num_local=num_ranks,
                    client=controller.client if controller else None)
        # each process records its own local ranks; the rank-0 process
        # keeps the user's HOROVOD_TIMELINE path (reference
        # docs/timeline.rst names rank 0's file) and the others write
        # suffixed siblings — same-path clobbering on a shared
        # filesystem would otherwise corrupt the trace
        if config.timeline_filename and rank_offset != 0:
            root, ext = os.path.splitext(config.timeline_filename)
            config.timeline_filename = f"{root}.proc{proc_id}{ext}"
        _timeline = _make_timeline(config, pid=rank_offset,
                                   num_ranks=num_ranks,
                                   proc_id=proc_index)
        _engine = Engine(num_ranks, devices, config=config,
                         topology=_topology, timeline=_timeline,
                         controller=controller, rank_offset=rank_offset,
                         global_size=global_size,
                         ranks_of_proc=ranks_of_proc,
                         chaos=chaos_injector)
        # telemetry surface: per-worker exposition endpoint + elastic
        # resize accounting (the engine just installed this round's
        # fresh registry)
        _start_metrics_endpoint(config, proc_index)
        if env_mod.get_bool(env_mod.HOROVOD_ELASTIC):
            _record_resize_event(_engine.global_size)
        if process_sets:
            from . import process_sets as ps_mod
            for ps in process_sets:
                ps_mod._register(ps)
        _bind_thread_if_unbound()


def _bind_thread_if_unbound():
    if getattr(_tls, "ctx", None) is None and _engine is not None:
        if _engine.num_local == 1:
            _tls.ctx = RankContext(_engine.rank_offset)


def bind_rank(rank):
    """Bind the calling thread to a rank context.  ``rank`` is the
    LOCAL rank index within this process (0..num_local); the context
    carries the global rank.  Used by the thread launcher (one thread
    per rank) and by tests."""
    if _engine is None:
        raise HorovodInitError("horovod_tpu.init() has not been called")
    if rank < 0 or rank >= _engine.num_local:
        raise ValueError(
            f"local rank {rank} out of range [0, {_engine.num_local})")
    _tls.ctx = RankContext(_engine.rank_offset + rank)
    return _tls.ctx


def unbind_rank():
    _tls.ctx = None


@_contextmanager
def bound_context(ctx):
    """Temporarily bind ``ctx`` (a RankContext) to the calling thread.
    Frameworks that run callbacks on their own pool threads (e.g. TF's
    py_function executor) use this to carry the submitting rank's
    identity across the thread hop."""
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = ctx
    try:
        yield ctx
    finally:
        _tls.ctx = prev


def context() -> RankContext:
    ctx = getattr(_tls, "ctx", None)
    if ctx is None:
        if _engine is None:
            raise HorovodInitError(
                "horovod_tpu has not been initialized; call init() first")
        _bind_thread_if_unbound()
        ctx = getattr(_tls, "ctx", None)
        if ctx is None:
            raise HorovodInitError(
                "this thread is not bound to a rank; use horovod_tpu.run() "
                "or bind_rank()")
    return ctx


def engine():
    if _engine is None:
        raise HorovodInitError(
            "horovod_tpu has not been initialized; call init() first")
    return _engine


def is_initialized():
    return _engine is not None


def needs_exec_restart():
    """True when recovery requires a fresh process: the runtime aborted
    (peer death / stale round) while jax.distributed was live — the
    coordination client cannot be cleanly re-initialized in-process
    and will fatally terminate us on its next heartbeat."""
    return _engine is not None and _engine._aborted is not None \
        and _distributed_up


#: set by shutdown() when the clean-teardown coordination barrier
#: timed out (a peer never arrived): the abandoned client makes
#: in-process re-init unsafe
_teardown_wedged = False


def take_teardown_wedged():
    """True (once) when the last shutdown() abandoned its coordination
    barrier — the elastic reset must exec-restart instead of
    re-initializing in-process (docs/fault_tolerance.md)."""
    global _teardown_wedged
    wedged, _teardown_wedged = _teardown_wedged, False
    return wedged


def shutdown():
    """Reference horovod_shutdown (operations.cc:966).  In
    multi-process mode also tears down jax.distributed and clears the
    cached XLA backends so a later init() can re-form the mesh with a
    different world (elastic re-rendezvous, SURVEY §7.7)."""
    global _engine, _topology, _timeline, _distributed_up
    with _state_lock:
        if _engine is None:
            return
        _engine.shutdown()
        if _timeline is not None:
            _timeline.close()
        if _engine.multiproc:
            # stop this process's per-host aggregator (if it owns
            # one) AFTER the engine's goodbye rode it; co-hosted
            # workers still running fall back to direct mode
            from ..runner.http.aggregator import \
                stop_process_aggregator
            stop_process_aggregator()
        from . import process_sets as ps_mod
        ps_mod._reset()
        from ..ops import compiled as _compiled
        _compiled.reset_compiled_state()
        was_multiproc = _engine.multiproc
        was_aborted = _engine._aborted is not None
        _engine = None
        _topology = None
        _timeline = None
        _tls.ctx = None
        if _distributed_up:
            if not was_aborted:
                # clean teardown: every peer participates in the
                # coordination-service shutdown barrier — but BOUNDED.
                # A peer wedged in a data-plane collective (an armed
                # bypass vote racing a graceful resize: its agreement
                # allreduce blocks on us while we block on its
                # barrier) can never arrive; waiting forever would
                # deadlock the whole job.  On timeout, abandon the
                # barrier thread and flag the teardown wedged — the
                # coordination client is in an unknown state, so the
                # elastic reset exec-restarts this worker into the
                # next round (take_teardown_wedged).
                import threading as _threading
                import jax

                done = _threading.Event()

                def _barrier():
                    try:
                        jax.distributed.shutdown()
                    except Exception:  # noqa: BLE001 — peers gone
                        pass
                    done.set()

                _threading.Thread(target=_barrier, daemon=True,
                                  name="hvd-dist-shutdown").start()
                budget = env_mod.get_float(
                    env_mod.HOROVOD_TEARDOWN_BARRIER_SECONDS, 10.0)
                if not done.wait(budget):
                    global _teardown_wedged
                    _teardown_wedged = True
                    import logging as _logging
                    _logging.getLogger("horovod_tpu").warning(
                        "coordination shutdown barrier did not "
                        "complete within %.1fs (a peer is wedged in "
                        "a data-plane collective?); abandoning it — "
                        "this worker will exec-restart into the next "
                        "round", budget)
            # aborted: a peer is dead — the shutdown barrier would
            # LOG(FATAL) this process.  Leave the client; the elastic
            # loop exec-restarts the process instead (see
            # elastic.run / needs_exec_restart).
            _distributed_up = False
        if was_multiproc:
            # clear cached XLA backends even when this round ran
            # single-process (size-1 elastic rounds): the next round may
            # need jax.distributed.initialize, which requires no live
            # backend
            try:
                import jax.extend.backend as _xb
                _xb.clear_backends()
            except Exception:  # noqa: BLE001
                pass


# -- topology queries (reference operations.cc:996-1075) -----------------------

def rank():
    return context().rank


def size():
    return engine().num_ranks


def local_rank():
    return engine().topology.local_rank(rank())


def local_size():
    return engine().topology.local_size(rank())


def cross_rank():
    return engine().topology.cross_rank(rank())


def cross_size():
    return engine().topology.cross_size(rank())


def is_homogeneous():
    return engine().topology.is_homogeneous()


# -- build-feature queries (reference basics.py:250-340).  The TPU
#    runtime has exactly one data plane: compiled XLA collectives. ------------

def mpi_threads_supported():
    return False


def mpi_built():
    return False


def gloo_built():
    return False


def nccl_built():
    return False


def ddl_built():
    return False


def ccl_built():
    return False


def cuda_built():
    return False


def rocm_built():
    return False


def xla_built():
    return True


def tpu_built():
    return True


def mpi_enabled():
    """Whether the MPI controller drives negotiation (reference
    mpi_ops ``mpi_enabled``).  Never on TPU — the store controller
    fills that role."""
    return False


def gloo_enabled():
    """Whether the gloo-style control plane is active (reference
    mpi_ops ``gloo_enabled``).  Always True: the HMAC-HTTP store
    controller (core/store_controller.py) fills the gloo controller's
    role on every launch path, including elastic.  Note
    ``gloo_built()`` stays False — no libgloo is linked."""
    return True


def metrics():
    """Snapshot of this process's metric registry — a JSON-able dict
    keyed by family name (docs/observability.md).  The programmatic
    twin of the ``/metrics.json`` endpoint; works before init() too
    (empty registry)."""
    from .. import telemetry
    return telemetry.metrics()


def start_metrics_server(port=None):
    """Start (or return) this worker's Prometheus endpoint.  With no
    argument uses ``HOROVOD_METRICS_PORT`` (+ process index); an
    explicit ``port`` binds exactly there.  Returns the server object
    (``.port`` is the bound port — pass ``port=0`` for an ephemeral
    one)."""
    global _metrics_server
    with _state_lock:
        if port is None:
            if _metrics_server is not None:
                return _metrics_server
            from . import env as env_mod_
            port = env_mod_.get_int(env_mod_.HOROVOD_METRICS_PORT, 0)
            if port:
                port += env_mod_.get_int(
                    env_mod_.HOROVOD_TPU_PROC_INDEX, 0)
        from ..telemetry import MetricsServer
        server = MetricsServer(port=port or 0)
        server.start()
        if _metrics_server is None:
            _metrics_server = server
        return server


def start_timeline(filename, mark_cycles=False):
    """Runtime timeline activation (reference operations.cc:1077).
    A ring-only flight-recorder timeline (no file) is upgraded in
    place; an already-writing file timeline must be stopped first."""
    global _timeline
    with _state_lock:
        eng = engine()
        if _timeline is not None and _timeline.filename:
            raise ValueError("timeline already active; stop it first")
        from ..utils.timeline import Timeline
        old, pid, pname = _timeline, eng.rank_offset, None
        if old is not None:
            pid, pname = old.pid, old.process_name
        _timeline = Timeline(filename, mark_cycles, pid=pid,
                             process_name=pname,
                             ring_events=eng.config.trace_ring_events)
        eng.timeline = _timeline
        # a job initialized with tracing fully off (ring disabled, no
        # HOROVOD_TIMELINE) had no clock sync to start; the first
        # runtime-activated timeline needs it for mergeable traces
        eng._start_clock_sync()
        if old is not None:
            old.close()


def stop_timeline():
    """Stop writing the timeline file.  The flight recorder stays
    live (a fresh ring-only timeline replaces the file writer) so
    stall auto-dumps and ``hvd.dump_trace()`` keep working."""
    global _timeline
    with _state_lock:
        eng = engine()
        old = _timeline
        eng.config.timeline_filename = None
        _timeline = _make_timeline(
            eng.config, pid=eng.rank_offset, num_ranks=eng.num_local,
            proc_id=eng.controller.proc_id if eng.multiproc else 0)
        eng.timeline = _timeline
        if old is not None:
            old.close()


def dump_trace(path=None):
    """Dump the flight recorder's last-N-events ring NOW: pushes it to
    the launcher over the KV fabric (multi-process — the buffers
    ``GET /timeline`` merges) and writes a stand-alone Chrome trace to
    ``path`` (or ``HOROVOD_TRACE_DUMP_DIR``) when given.  Returns the
    file path written, or None (docs/timeline.md "Flight recorder")."""
    return engine().dump_trace(path=path, reason="manual")


# -- reference-shaped surface (horovod/common/basics.py:21-29) ---------------

class MPI:
    """Typing stand-in matching the reference's lazy mpi4py shim
    (reference basics.py:21-23) — there is no MPI on TPU pods, so
    ``MPI.Comm`` only exists for signature compatibility."""

    class Comm:
        ...


class HorovodBasics:
    """Object-shaped view of this module (reference basics.py:29
    wraps the C library in a class; frontends hold an instance).
    Every method delegates to the module-level implementation, so
    ``HorovodBasics().rank()`` and ``basics.rank()`` are the same."""

    def __init__(self, pkg_path=None, *args):
        # the reference dlopen()s the compiled extension here; this
        # runtime is pure Python so the arguments are accepted and
        # ignored
        self.MPI_LIB_CTYPES = None

    def __getattr__(self, name):
        import sys
        mod = sys.modules[__name__]
        try:
            return getattr(mod, name)
        except AttributeError:
            raise AttributeError(
                f"'HorovodBasics' object has no attribute '{name}'")

"""Exception types for horovod_tpu.

Mirrors the capability surface of the reference's
``horovod/common/exceptions.py`` (HorovodInternalError,
HostsUpdatedInterrupt) while adding engine-specific errors for the
TPU-native runtime.
"""


class HorovodInternalError(RuntimeError):
    """Internal error raised when a collective operation fails.

    In elastic mode this triggers state restoration and re-rendezvous
    (see reference horovod/common/exceptions.py:20).
    """


class HostsUpdatedInterrupt(RuntimeError):
    """Raised asynchronously when the set of available hosts changes.

    Carries ``skip_sync``: when True, the worker state is assumed
    current and need not be restored from the last commit
    (reference horovod/common/exceptions.py:30).
    """

    def __init__(self, skip_sync=False):
        super().__init__("hosts updated")
        self.skip_sync = skip_sync


def get_version_mismatch_message(name, version, installed_version):
    """Reference horovod/common/exceptions.py:39."""
    return (
        f"Framework {name} installed with version {version} but found "
        f"version {installed_version}.\n\t     This can result in "
        "unexpected behavior including runtime errors.\n\t     Reinstall "
        "horovod_tpu so the frontend and runtime versions match.")


class HorovodVersionMismatchError(ImportError):
    """Frontend and runtime were built from different versions
    (reference horovod/common/exceptions.py:48)."""

    def __init__(self, name, version, installed_version):
        super().__init__(get_version_mismatch_message(
            name, version, installed_version))
        self.name = name
        self.version = version
        self.installed_version = installed_version


class HorovodInitError(RuntimeError):
    """Raised when the runtime is used before ``init()`` (or after
    ``shutdown()``)."""


class TensorShapeMismatchError(HorovodInternalError):
    """Cross-rank shape/dtype/op validation failure.

    The reference coordinator constructs an ERROR response when ranks
    disagree (controller.cc:496-843); we raise this on every
    participating rank.
    """


class DuplicateNameError(HorovodInternalError):
    """Same tensor name submitted twice by one rank before completion
    (reference common.h:238 DUPLICATE_NAME_ERROR)."""


class StalledTensorError(HorovodInternalError):
    """A tensor was ready on some ranks but missing on others past the
    stall-shutdown deadline (reference stall_inspector.h)."""

"""Canonical shard_map import shim + small axis helpers.

jax moved shard_map between releases (``jax.shard_map`` vs
``jax.experimental.shard_map``) and renamed its replication checker
(``check_rep`` -> ``check_vma``).  Every shard_map user in this
codebase imports the resolved symbol from here (directly, or via
``parallel._shard_map`` which re-exports it) so an API change is
fixed exactly once.  Callers always pass the modern ``check_vma``
name; the legacy wrapper renames it.
"""

from jax import lax

try:
    from jax import shard_map as _sm  # jax >= 0.6 style
    shard_map = _sm.shard_map if hasattr(_sm, "shard_map") else _sm
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _sm_legacy
    import inspect as _inspect

    if "check_vma" in _inspect.signature(_sm_legacy).parameters:
        shard_map = _sm_legacy
    else:
        import functools as _functools

        @_functools.wraps(_sm_legacy)
        def shard_map(f, *args, **kwargs):
            # pre-0.6 jax spells the replication checker `check_rep`
            if "check_vma" in kwargs:
                kwargs["check_rep"] = kwargs.pop("check_vma")
            return _sm_legacy(f, *args, **kwargs)


def axis_size(axis_name):
    """``lax.axis_size`` where jax has it; the psum-of-one idiom
    (constant-folded to the mapped axis size, no collective emitted)
    everywhere else."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)

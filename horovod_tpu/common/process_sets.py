"""Process sets — collectives over arbitrary rank subsets.

API parity with the reference's ``horovod/common/process_sets.py``
(ProcessSet :18, add_process_set :123, remove_process_set :145).  Each
registered set owns a sub-mesh executor in the engine, the TPU-native
analogue of a per-set communicator (reference process_set.h:26-84).
"""

import threading

from . import basics
from .exceptions import HorovodInitError

_lock = threading.Lock()
_registered = {}   # id -> ProcessSet


class ProcessSet:
    """A set of global ranks collectives may be restricted to."""

    def __init__(self, ranks=None):
        # an EMPTY rank list is a valid (inert) set — the reference's
        # tests register odd/even splits that are empty at small sizes
        # (test_torch.py process-set grids at np=1); None means "the
        # global set", chosen at registration
        self.ranks = sorted(set(int(r) for r in ranks)) \
            if ranks is not None else None
        self.process_set_id = None

    def _require_registered(self):
        if self.process_set_id is None:
            raise ValueError(
                "process set is not yet registered with add_process_set() "
                "or init(process_sets=...)")

    def size(self):
        self._require_registered()
        return len(basics.engine().process_set_ranks(self.process_set_id))

    def rank(self):
        """Rank of the current rank context within this set (reference
        process_sets.py ProcessSet.rank)."""
        self._require_registered()
        ranks = basics.engine().process_set_ranks(self.process_set_id)
        me = basics.rank()
        if me not in ranks:
            return -1
        return ranks.index(me)

    def included(self):
        self._require_registered()
        return basics.rank() in basics.engine().process_set_ranks(
            self.process_set_id)

    def __repr__(self):
        return (f"ProcessSet(process_set_id={self.process_set_id}, "
                f"ranks={self.ranks})")


global_process_set = ProcessSet()
global_process_set.process_set_id = 0


def _register(ps: ProcessSet):
    if ps.process_set_id is not None:
        return ps
    if ps.ranks is None:
        raise ValueError("cannot register a process set without ranks")
    ps.process_set_id = basics.engine().add_process_set(ps.ranks)
    with _lock:
        _registered[ps.process_set_id] = ps
    return ps


def add_process_set(process_set) -> ProcessSet:
    """Register a new process set dynamically (reference
    process_sets.py:123: requires HOROVOD_DYNAMIC_PROCESS_SETS in the
    reference; the TPU engine supports it unconditionally)."""
    if isinstance(process_set, ProcessSet):
        ps = process_set
    else:
        ps = ProcessSet(process_set)
    return _register(ps)


def remove_process_set(process_set) -> bool:
    """Deregister (reference process_sets.py:145).  Collective, like
    the reference: every rank calls it and removal takes effect once
    all local rank threads have (a fast rank can no longer kill a
    collective its peers still have in flight).  Callers without a
    bound rank context (driver/admin threads) remove immediately."""
    if isinstance(process_set, ProcessSet):
        # the ProcessSet object is SHARED across rank threads; the
        # first thread to finish the collective removal nulls
        # process_set_id, so siblings re-resolve through _removed_id
        ps_id = process_set.process_set_id
        if ps_id is None:
            ps_id = getattr(process_set, "_removed_id", None)
    else:
        ps_id = int(process_set)
    if ps_id is None or ps_id == 0:
        return False
    try:
        rank = basics.context().rank
    except HorovodInitError:
        rank = None      # administrative caller (no bound rank thread)
    ok = basics.engine().remove_process_set(ps_id, rank=rank)
    if ok:
        with _lock:
            reg = _registered.pop(ps_id, None)
        if reg is not None:
            reg._removed_id = ps_id
            reg.process_set_id = None
    return ok


def process_set_ids():
    return sorted([0] + list(_registered.keys()))


def _get_by_id(ps_id):
    if ps_id == 0:
        return global_process_set
    with _lock:
        return _registered.get(ps_id)


def _reset():
    global _registered
    with _lock:
        _registered = {}


def global_ranks():
    return list(range(basics.size()))


# reference process_sets.py:21 — mpi4py typing shim (no MPI on TPU)
from .basics import MPI  # noqa: F401,E402

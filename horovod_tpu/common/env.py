"""Environment-variable configuration surface.

The reference uses ~40 ``HOROVOD_*`` env vars as the ABI between the
launcher and the core runtime (reference common/common.h:115-149, parsed
in operations.cc:459-650 and utils/env_parser.cc).  We keep the same
names so launcher flags, config files and user habits carry over.
"""

import logging
import os

# --- knob names (reference common.h:115-149) ---------------------------------
HOROVOD_FUSION_THRESHOLD = "HOROVOD_FUSION_THRESHOLD"
HOROVOD_CYCLE_TIME = "HOROVOD_CYCLE_TIME"
HOROVOD_CACHE_CAPACITY = "HOROVOD_CACHE_CAPACITY"
HOROVOD_TIMELINE = "HOROVOD_TIMELINE"
HOROVOD_TIMELINE_MARK_CYCLES = "HOROVOD_TIMELINE_MARK_CYCLES"
HOROVOD_AUTOTUNE = "HOROVOD_AUTOTUNE"
HOROVOD_AUTOTUNE_LOG = "HOROVOD_AUTOTUNE_LOG"
HOROVOD_AUTOTUNE_WARMUP_SAMPLES = "HOROVOD_AUTOTUNE_WARMUP_SAMPLES"
HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE = "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE"
HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES = "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES"
HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE = "HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE"
HOROVOD_STALL_CHECK_DISABLE = "HOROVOD_STALL_CHECK_DISABLE"
HOROVOD_STALL_CHECK_TIME_SECONDS = "HOROVOD_STALL_CHECK_TIME_SECONDS"
HOROVOD_STALL_SHUTDOWN_TIME_SECONDS = "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS"
HOROVOD_HIERARCHICAL_ALLREDUCE = "HOROVOD_HIERARCHICAL_ALLREDUCE"
HOROVOD_HIERARCHICAL_ALLGATHER = "HOROVOD_HIERARCHICAL_ALLGATHER"
HOROVOD_TORUS_ALLREDUCE = "HOROVOD_TORUS_ALLREDUCE"
HOROVOD_ELASTIC = "HOROVOD_ELASTIC"
HOROVOD_PROCESS_SET_REMOVAL_TIMEOUT = "HOROVOD_PROCESS_SET_REMOVAL_TIMEOUT"
HOROVOD_LOG_LEVEL = "HOROVOD_LOG_LEVEL"
HOROVOD_LOG_HIDE_TIME = "HOROVOD_LOG_HIDE_TIME"

# rank/topology handoff from the launcher (reference gloo_run.py:66-103)
HOROVOD_RANK = "HOROVOD_RANK"
HOROVOD_SIZE = "HOROVOD_SIZE"
HOROVOD_LOCAL_RANK = "HOROVOD_LOCAL_RANK"
HOROVOD_LOCAL_SIZE = "HOROVOD_LOCAL_SIZE"
HOROVOD_CROSS_RANK = "HOROVOD_CROSS_RANK"
HOROVOD_CROSS_SIZE = "HOROVOD_CROSS_SIZE"
HOROVOD_HOSTNAME = "HOROVOD_HOSTNAME"
HOROVOD_RENDEZVOUS_ADDR = "HOROVOD_GLOO_RENDEZVOUS_ADDR"
HOROVOD_RENDEZVOUS_PORT = "HOROVOD_GLOO_RENDEZVOUS_PORT"
HOROVOD_CONTROLLER = "HOROVOD_CONTROLLER"
HOROVOD_CPU_OPERATIONS = "HOROVOD_CPU_OPERATIONS"

# telemetry (docs/observability.md): per-worker Prometheus endpoint
# on METRICS_PORT (+ proc index in multi-process jobs) and the
# worker->coordinator snapshot push cadence feeding the job-wide
# /metrics on the launcher's rendezvous service
HOROVOD_METRICS_PORT = "HOROVOD_METRICS_PORT"
HOROVOD_METRICS_PUSH_SECONDS = "HOROVOD_METRICS_PUSH_SECONDS"

# job-wide tracing (docs/timeline.md "Job-wide traces"): the
# flight-recorder ring size (events; 0 disables), the directory stall
# auto-dumps and hvd.dump_trace() default into (unset = KV push only),
# and the clock-sync re-sample cadence mapping each worker's timeline
# epoch onto the launcher's clock (0 disables)
HOROVOD_TRACE_RING_EVENTS = "HOROVOD_TRACE_RING_EVENTS"
HOROVOD_TRACE_DUMP_DIR = "HOROVOD_TRACE_DUMP_DIR"
HOROVOD_TRACE_CLOCK_SYNC_SECONDS = "HOROVOD_TRACE_CLOCK_SYNC_SECONDS"

# chaos + liveness + fabric hardening (docs/fault_tolerance.md):
# HOROVOD_FAULT_PLAN names a seeded fault plan (inline JSON, @path, or
# a bare file path; horovodrun --fault-plan); HOROVOD_FAULT_SEED
# overrides the plan's seed.  Workers beat the coordinator every
# HEARTBEAT_INTERVAL seconds (0 disables); the coordinator declares a
# proc dead after HEARTBEAT_WINDOW seconds without a beat (0 = 1.5x
# the interval — detection inside 2x the interval).  Fabric retries
# are bounded by attempts AND a wall deadline.
HOROVOD_FAULT_PLAN = "HOROVOD_FAULT_PLAN"
HOROVOD_FAULT_SEED = "HOROVOD_FAULT_SEED"
HOROVOD_HEARTBEAT_INTERVAL_SECONDS = "HOROVOD_HEARTBEAT_INTERVAL_SECONDS"
HOROVOD_HEARTBEAT_WINDOW_SECONDS = "HOROVOD_HEARTBEAT_WINDOW_SECONDS"
HOROVOD_FABRIC_RETRY_ATTEMPTS = "HOROVOD_FABRIC_RETRY_ATTEMPTS"
HOROVOD_FABRIC_RETRY_DEADLINE_SECONDS = \
    "HOROVOD_FABRIC_RETRY_DEADLINE_SECONDS"

# coordinator crash survival + steady-state bypass
# (docs/fault_tolerance.md "Coordinator crash survival"):
# COORD_JOURNAL names the launcher-side control-plane journal a
# restarted rendezvous service replays (epoch-fenced);
# COORD_OUTAGE_DEADLINE bounds how long replay-safe fabric requests
# keep retrying across a coordinator outage; BYPASS_AFTER_CYCLES is
# the K identical negotiation cycles that arm the coordinator-free
# fast path (0 disables the bypass); BYPASS_WAIT_SECONDS bounds each
# armed cycle's wait for the cached tensors before it forces the
# unanimous fallback to full negotiation.
HOROVOD_COORD_JOURNAL = "HOROVOD_COORD_JOURNAL"
HOROVOD_COORD_OUTAGE_DEADLINE_SECONDS = \
    "HOROVOD_COORD_OUTAGE_DEADLINE_SECONDS"
HOROVOD_BYPASS_AFTER_CYCLES = "HOROVOD_BYPASS_AFTER_CYCLES"
HOROVOD_BYPASS_WAIT_SECONDS = "HOROVOD_BYPASS_WAIT_SECONDS"

# per-host aggregator tier (docs/fault_tolerance.md "Per-host
# aggregator tier"): TIER selects the control-plane topology (flat =
# every proc talks to the coordinator, host = one aggregator per host
# batches its workers' traffic upstream); LINGER_MS is the batching
# window the aggregator's flusher waits for co-reporting local
# workers; FALLBACK_DEADLINE bounds how long a worker's requests
# retry against a silent aggregator before falling back to direct
# coordinator mode (deliberately much tighter than the coordinator
# outage deadline — the fallback IS the recovery).
HOROVOD_CONTROL_PLANE_TIER = "HOROVOD_CONTROL_PLANE_TIER"
HOROVOD_AGG_LINGER_MS = "HOROVOD_AGG_LINGER_MS"
HOROVOD_AGG_FALLBACK_DEADLINE_SECONDS = \
    "HOROVOD_AGG_FALLBACK_DEADLINE_SECONDS"

# shared-secret for the launcher's HMAC-authenticated KV channel
# (reference runner/common/util/secret.py; hex in the env)
HOROVOD_SECRET_KEY = "HOROVOD_SECRET_KEY"
# elastic: crash-durable state spill directory (common/elastic.py)
# and the init-barrier wait for the first rendezvous (reference
# --elastic-timeout semantics, also a worker-side knob here)
HOROVOD_STATE_SPILL = "HOROVOD_STATE_SPILL"
HOROVOD_ELASTIC_TIMEOUT = "HOROVOD_ELASTIC_TIMEOUT"
# bound on the clean-teardown coordination barrier
# (jax.distributed.shutdown) during an elastic re-init: a peer wedged
# in a data-plane collective (e.g. an armed bypass vote racing the
# resize) can never reach the barrier — after this many seconds the
# worker abandons it and exec-restarts into the new round instead of
# deadlocking the whole job (docs/fault_tolerance.md)
HOROVOD_TEARDOWN_BARRIER_SECONDS = "HOROVOD_TEARDOWN_BARRIER_SECONDS"
# coordinator journal bounds (runner/http/journal.py): whole-file
# compaction threshold and the per-value KV journaling cap
HOROVOD_COORD_JOURNAL_MAX_BYTES = "HOROVOD_COORD_JOURNAL_MAX_BYTES"
HOROVOD_COORD_JOURNAL_KV_MAX_BYTES = \
    "HOROVOD_COORD_JOURNAL_KV_MAX_BYTES"

# TPU-native additions
# uniform wire shorthand: one format for every hop (a 16-bit value
# applies to both hops of a decomposed reduction; int8/int4 apply to
# the cross hop only — the inner hop stays full width)
HOROVOD_WIRE_DTYPE = "HOROVOD_WIRE_DTYPE"  # f32|fp16|bf16|int8|int4
# per-hop wire pair (docs/concepts.md "Per-hop wire"): INNER is the
# fast intra-host/ICI hop (f32 | fp16 | bf16 — quantized formats are
# never legal there), OUTER the slow cross-host/DCN hop (f32 | fp16 |
# bf16 | int8 | int4).  OUTER wins over the WIRE_DTYPE shorthand.
HOROVOD_WIRE_INNER = "HOROVOD_WIRE_INNER"
HOROVOD_WIRE_OUTER = "HOROVOD_WIRE_OUTER"
# flat | hierarchical | torus (generic spelling; the reference's
# HOROVOD_HIERARCHICAL_ALLREDUCE / HOROVOD_TORUS_ALLREDUCE booleans
# above are honored as aliases)
HOROVOD_ALLREDUCE_ALGORITHM = "HOROVOD_ALLREDUCE_ALGORITHM"
# reducescatter backward convention: default matches the reference
# (Sum grad x= size, Average unscaled); set to 1 for the true adjoint
# of the forward (docs/migration.md "reducescatter gradients")
HOROVOD_EXACT_ADJOINT_REDUCESCATTER = \
    "HOROVOD_EXACT_ADJOINT_REDUCESCATTER"
HOROVOD_TPU_PLATFORM = "HOROVOD_TPU_PLATFORM"  # jax platform for the mesh
HOROVOD_TPU_RANKS_PER_PROC = "HOROVOD_TPU_RANKS_PER_PROC"
HOROVOD_TPU_COORDINATOR = "HOROVOD_TPU_COORDINATOR"
HOROVOD_TPU_NUM_PROCS = "HOROVOD_TPU_NUM_PROCS"
HOROVOD_TPU_PROC_INDEX = "HOROVOD_TPU_PROC_INDEX"
# alltoall SPMD schedule (ops/xla_ops.py: auto | oneshot | diag) and
# the conv+bn fused-backward kernel selector (ops/pallas_conv_bn.py:
# pallas | xla)
HOROVOD_TPU_ALLTOALL_SCHEDULE = "HOROVOD_TPU_ALLTOALL_SCHEDULE"
HOROVOD_CONV_BN_BWD = "HOROVOD_CONV_BN_BWD"
# fusion pack goes multithreaded above this bucket size (csrc
# hvd_pack_mt); a third autotune dimension
HOROVOD_TPU_PACK_MT_THRESHOLD = "HOROVOD_TPU_PACK_MT_THRESHOLD"

# MPMD pipeline runtime (docs/parallelism.md "MPMD pipeline runtime";
# parallel/runtime.py + schedule.py): number of pipeline stages the
# job is carved into (1 = no pipelining), microbatches per step (0 =
# auto: 2·pp for every schedule), the schedule (gpipe |
# 1f1b | interleaved), and model chunks per stage for the interleaved
# schedule.  horovodrun --pipeline-stages / --num-microbatches /
# --pipeline-schedule hand these off; (schedule, n_micro) is also the
# autotuner's seventh dimension, latched per negotiation entry and
# cross-rank validated like the wire pair and algorithm.
HOROVOD_PP_STAGES = "HOROVOD_PP_STAGES"
HOROVOD_PP_MICROBATCHES = "HOROVOD_PP_MICROBATCHES"
HOROVOD_PP_SCHEDULE = "HOROVOD_PP_SCHEDULE"
HOROVOD_PP_CHUNKS = "HOROVOD_PP_CHUNKS"
# autotune warm-start cache (docs/autotune.md "Warm start"): a local
# JSON file of converged best configs keyed by (bucket signature,
# topology, world size); jobs reload yesterday's optimum at start
HOROVOD_AUTOTUNE_CACHE = "HOROVOD_AUTOTUNE_CACHE"

# ZeRO-grade weight-update sharding (docs/parallelism.md
# "Weight-update sharding"; core/sharded.py): SHARDED_OPTIMIZER=1
# makes DistributedOptimizer default to sharded=True on every
# frontend — gradients reducescatter, each rank updates its 1/dp
# shard of params + optimizer state, the updated params allgather
# back on the configured wire.  SHARD_LAYOUT picks the shard-bucket
# granularity (bucket | flat) and is the autotuner's EIGHTH
# dimension.
HOROVOD_SHARDED_OPTIMIZER = "HOROVOD_SHARDED_OPTIMIZER"
HOROVOD_SHARD_LAYOUT = "HOROVOD_SHARD_LAYOUT"

# bucket-granular comm/compute overlap on the compiled path
# (docs/concepts.md "Bucket-granular dispatch"; ops/compiled.py):
# OVERLAP_BUCKET_BYTES splits the compiled grouped reduction into
# per-bucket programs of at most this many payload bytes each,
# dispatched as each bucket's gradients arrive so the collectives
# pipeline against the remaining backward compute (0 = one grouped
# program, the pre-overlap behavior).  OVERLAP_AUTOTUNE sweeps the
# bucket size as the autotuner's NINTH dimension.  Reducers LATCH
# the value once per call/stream, so a mid-step flip can never split
# one step across two bucketings.
HOROVOD_OVERLAP_BUCKET_BYTES = "HOROVOD_OVERLAP_BUCKET_BYTES"
HOROVOD_OVERLAP_AUTOTUNE = "HOROVOD_OVERLAP_AUTOTUNE"

# end-to-end step integrity (docs/fault_tolerance.md "Silent data
# corruption"; core/integrity.py): INTEGRITY=0 disables the wire
# checksums + implicated-rank vote (they default ON — the digests are
# one xor-fold pass per buffer); SENTINEL_STEPS is the divergence
# sentinel's cadence (param-fingerprint MIN/MAX agreement every N
# steps, 0 = off); EVICT_AFTER escalates the N-th detection
# implicating one rank into a HostEvictionError so the driver's
# blacklist verdict evicts the host (0 = always roll back, never
# evict); MAX_GRAD_NORM arms the update guard's norm bound (0 = only
# the nonfinite check).
HOROVOD_INTEGRITY = "HOROVOD_INTEGRITY"
HOROVOD_INTEGRITY_SENTINEL_STEPS = "HOROVOD_INTEGRITY_SENTINEL_STEPS"
HOROVOD_INTEGRITY_EVICT_AFTER = "HOROVOD_INTEGRITY_EVICT_AFTER"
HOROVOD_INTEGRITY_MAX_GRAD_NORM = "HOROVOD_INTEGRITY_MAX_GRAD_NORM"

# expert parallelism (docs/parallelism.md "Expert parallelism";
# parallel/moe.py + ops/compiled.py CompiledAlltoall): MOE_EXPERTS is
# the total expert count (0 = no MoE layers, the default); the
# capacity factor sizes each expert's fixed token buffer
# (capacity = ceil(cf * tokens * topk / experts), deterministic
# drop/pad keeps compiled shapes static → zero steady-state
# recompiles); TOPK is the router fan-out.  MOE_EP caps the
# expert-parallel degree (0 = every rank; experts shard across the ep
# axis, tokens ride the fused quantized alltoall).  (ep × capacity
# factor) is the autotuner's TENTH dimension, swept only when
# MOE_EXPERTS > 0.
HOROVOD_MOE_EXPERTS = "HOROVOD_MOE_EXPERTS"
HOROVOD_MOE_CAPACITY_FACTOR = "HOROVOD_MOE_CAPACITY_FACTOR"
HOROVOD_MOE_TOPK = "HOROVOD_MOE_TOPK"
HOROVOD_MOE_EP = "HOROVOD_MOE_EP"

# multi-tenant fleet controller (docs/fleet.md; horovodrun
# --fleet-spec): the JSON fleet spec source (inline, @path, or bare
# path), the reconciliation cadence, the controller's own journal
# (crash-restartable: HOROVOD_FLEET_RESUME=1 replays it), the
# deterministic preemption/fault evidence log the day-in-the-life
# gate compares byte-for-byte, the controller's Prometheus port, and
# the placement debounce/cooldown windows (in reconcile ticks) that
# keep a resize storm from thrashing rounds.
HOROVOD_FLEET_SPEC = "HOROVOD_FLEET_SPEC"
HOROVOD_FLEET_RECONCILE_SECONDS = "HOROVOD_FLEET_RECONCILE_SECONDS"
HOROVOD_FLEET_JOURNAL = "HOROVOD_FLEET_JOURNAL"
HOROVOD_FLEET_RESUME = "HOROVOD_FLEET_RESUME"
HOROVOD_FLEET_EVIDENCE_LOG = "HOROVOD_FLEET_EVIDENCE_LOG"
HOROVOD_FLEET_METRICS_PORT = "HOROVOD_FLEET_METRICS_PORT"
HOROVOD_FLEET_SETTLE_TICKS = "HOROVOD_FLEET_SETTLE_TICKS"
HOROVOD_FLEET_BLACKLIST_TICKS = "HOROVOD_FLEET_BLACKLIST_TICKS"

# pod-scale data plane (docs/data.md): DATA_SHARD_JOURNAL names the
# shard ledger's cursor journal file (unset = in-memory only — no
# exactly-once guarantee across restarts); DATA_SHARD_SEED seeds the
# deterministic sample permutation the shard planner splits (same
# seed → byte-identical shard plans, the data drill's evidence);
# DATA_QUEUE_SIZE bounds each shard server's staged-batch queue (the
# backpressure window DATA_QUEUE_DEPTH exports); DATA_ACK_POLL_SECONDS
# is the ledger's cadence for draining consumer acks from the KV
# fabric into journaled cursors (the bounded cursor-lag window);
# DATA_ASYNC_CKPT=0 forces utils/checkpoint.py save_rank0-style
# inline saves instead of the background CRC-anchored streamer.
HOROVOD_DATA_SHARD_JOURNAL = "HOROVOD_DATA_SHARD_JOURNAL"
HOROVOD_DATA_SHARD_SEED = "HOROVOD_DATA_SHARD_SEED"
HOROVOD_DATA_QUEUE_SIZE = "HOROVOD_DATA_QUEUE_SIZE"
HOROVOD_DATA_ACK_POLL_SECONDS = "HOROVOD_DATA_ACK_POLL_SECONDS"
HOROVOD_DATA_ASYNC_CKPT = "HOROVOD_DATA_ASYNC_CKPT"

#: Launcher↔worker handoff ABI: env vars the launcher exports for its
#: own workers and users never set by hand.  hvdlint checker 5
#: (`knob-undocumented`) exempts these from the docs/migration.md
#: knob-table requirement; everything else read anywhere in the tree
#: must be documented.  Keep this list honest — moving a knob here to
#: silence the checker defeats the registry.
INTERNAL_KNOBS = (
    # rank/topology handoff (reference gloo_run.py:66-103)
    "HOROVOD_RANK", "HOROVOD_SIZE", "HOROVOD_LOCAL_RANK",
    "HOROVOD_LOCAL_SIZE", "HOROVOD_CROSS_RANK", "HOROVOD_CROSS_SIZE",
    "HOROVOD_HOSTNAME", "HOROVOD_CONTROLLER", "HOROVOD_CPU_OPERATIONS",
    # multi-process mesh handoff (proc_run -> workers)
    "HOROVOD_TPU_PROC_INDEX", "HOROVOD_TPU_NUM_PROCS",
    "HOROVOD_TPU_COORDINATOR", "HOROVOD_TPU_RANKS_PER_PROC",
    "HOROVOD_TPU_RANKS_OF_PROC", "HOROVOD_TPU_HOST_OF_RANK",
    "HOROVOD_TPU_INIT_TIMEOUT",
    # spark driver -> task handoff (spark/task/)
    "HOROVOD_SPARK_PYTHONPATH", "HOROVOD_SPARK_WORK_DIR",
)

DEFAULT_FUSION_THRESHOLD_BYTES = 64 * 1024 * 1024
#: Overlap bucket-size grid the autotuner sweeps (ninth dimension)
#: and docs/autotune.md documents: 0 = grouped single program, then
#: 1/4/16/64 MiB bucket ceilings.  Lives here (not core/autotune.py)
#: so ops/compiled.py and the benches import it without pulling the
#: tuner.
OVERLAP_BUCKET_CHOICES = (0, 1 << 20, 4 << 20, 16 << 20, 64 << 20)
DEFAULT_CYCLE_TIME_MS = 1.0
DEFAULT_CACHE_CAPACITY = 1024
DEFAULT_STALL_WARNING_SECS = 60.0


def get_bool(name, default=False):
    val = os.environ.get(name)
    if val is None:
        return default
    return val.strip().lower() in ("1", "true", "yes", "on")


def _warn_malformed(name, val, default):
    # loud, not fatal: an operator's typo (e.g. FOO=64M) must not be
    # silently replaced by the default with nothing in the logs
    logging.getLogger("horovod_tpu").warning(
        "%s=%r is not a valid number; using default %r",
        name, val, default)


def get_int(name, default=0):
    val = os.environ.get(name)
    if val is None or not val.strip():
        return default
    try:
        return int(val)
    except ValueError:
        _warn_malformed(name, val, default)
        return default


def get_float(name, default=0.0):
    val = os.environ.get(name)
    if val is None or not val.strip():
        return default
    try:
        return float(val)
    except ValueError:
        _warn_malformed(name, val, default)
        return default


def get_str(name, default=None):
    return os.environ.get(name, default)


def require_str(name):
    """A handoff variable that MUST be present: missing-or-empty
    raises naming the variable, instead of leaking None into an
    address/port where it fails as an opaque downstream error."""
    val = os.environ.get(name)
    if val is None or not val.strip():
        raise KeyError(
            f"{name} missing from the environment — the launcher "
            f"handoff did not reach this process")
    return val


def require_int(name):
    return int(require_str(name))


# -- worker-side logging (reference common/logging.cc + env_parser.cc
#    SetLogLevelFromEnv/SetBoolFromEnv(HOROVOD_LOG_HIDE_TIME)) --------------

_LOG_LEVELS = {
    "trace": logging.DEBUG,     # python logging has no TRACE tier
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
}


def setup_logging():
    """Configure the ``horovod_tpu`` logger from ``HOROVOD_LOG_LEVEL``
    and ``HOROVOD_LOG_HIDE_TIME``.

    The runner exports both (runner/config_parser.py) exactly like the
    reference launcher, and the reference workers honor them in
    ``logging.cc``; called from ``hvd.init()`` so launched workers do
    too.  Without an explicit level the logger is left alone (library
    default: warnings propagate to whatever the host app configured)."""
    level = get_str(HOROVOD_LOG_LEVEL)
    hide_time = get_bool(HOROVOD_LOG_HIDE_TIME)
    logger = logging.getLogger("horovod_tpu")
    if level is None:
        return logger
    logger.setLevel(_LOG_LEVELS.get(level.strip().lower(),
                                    logging.WARNING))
    fmt = "[%(levelname)s] %(message)s" if hide_time else \
        "[%(asctime)s.%(msecs)03d, %(levelname)s] %(message)s"
    handler = None
    for h in logger.handlers:
        if getattr(h, "_hvd_env_handler", False):
            handler = h
            break
    if handler is None:
        handler = logging.StreamHandler()
        handler._hvd_env_handler = True
        logger.addHandler(handler)
        # this logger now owns its output (reference logging.cc writes
        # its own stream); propagating too would double every record
        # through the host application's root handlers
        logger.propagate = False
    handler.setFormatter(logging.Formatter(fmt, datefmt="%H:%M:%S"))
    return logger


class Config:
    """Runtime knobs resolved from the environment at init() time.

    Mirrors the parse performed in the reference's BackgroundThreadLoop
    (operations.cc:459-650): fusion threshold, cycle time, cache
    capacity, stall-inspector and autotune settings.
    """

    def __init__(self):
        self.fusion_threshold_bytes = get_int(
            HOROVOD_FUSION_THRESHOLD, DEFAULT_FUSION_THRESHOLD_BYTES)
        self.cycle_time_ms = get_float(HOROVOD_CYCLE_TIME, DEFAULT_CYCLE_TIME_MS)
        # fusion pack goes multithreaded above this bucket size
        # (csrc hvd_pack_mt); a third autotune dimension
        self.pack_mt_threshold_bytes = get_int(
            HOROVOD_TPU_PACK_MT_THRESHOLD, 8 << 20)
        self.cache_capacity = get_int(HOROVOD_CACHE_CAPACITY, DEFAULT_CACHE_CAPACITY)
        # default wire formats for float allreduce/reducescatter
        # payloads (per-request wire_dtype=/wire_inner= override;
        # autotune sweeps the per-hop PAIR as one categorical).
        # wire_dtype is the OUTER (cross-host/DCN) hop — or the only
        # hop of a flat collective; wire_inner the intra-host/ICI hop.
        # HOROVOD_WIRE_DTYPE stays as the uniform shorthand (the
        # engine expands a 16-bit value onto both hops); an explicit
        # HOROVOD_WIRE_OUTER wins over it.  None = full width.
        from ..ops.quantize import (normalize_inner_wire,
                                    normalize_wire_dtype)
        self.wire_dtype = normalize_wire_dtype(
            get_str(HOROVOD_WIRE_OUTER) or get_str(HOROVOD_WIRE_DTYPE))
        self.wire_inner = normalize_inner_wire(
            get_str(HOROVOD_WIRE_INNER))
        # default reduction algorithm for float Sum/Average allreduces
        # (per-request algorithm= overrides; autotune sweeps this as
        # its sixth dimension).  The reference's boolean toggles
        # (HOROVOD_TORUS_ALLREDUCE wins over HIERARCHICAL, matching
        # the fork's NCCL dispatch order) alias the generic knob.
        from .topology import normalize_algorithm
        if get_bool(HOROVOD_TORUS_ALLREDUCE):
            self.algorithm = "torus"
        elif get_bool(HOROVOD_HIERARCHICAL_ALLREDUCE):
            self.algorithm = "hierarchical"
        else:
            self.algorithm = normalize_algorithm(
                get_str(HOROVOD_ALLREDUCE_ALGORITHM))
        self.timeline_filename = get_str(HOROVOD_TIMELINE)
        if self.timeline_filename == "DYNAMIC":
            # reference sentinel (test_torch.py:54): timeline support
            # enabled but no file until start_timeline() names one
            self.timeline_filename = None
        self.timeline_mark_cycles = get_bool(HOROVOD_TIMELINE_MARK_CYCLES)
        self.autotune = get_bool(HOROVOD_AUTOTUNE)
        self.autotune_log = get_str(HOROVOD_AUTOTUNE_LOG)
        self.autotune_warmup_samples = get_int(HOROVOD_AUTOTUNE_WARMUP_SAMPLES, 3)
        self.autotune_steps_per_sample = get_int(HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE, 10)
        self.autotune_max_samples = get_int(
            HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES, 20)
        self.stall_check_disable = get_bool(HOROVOD_STALL_CHECK_DISABLE)
        self.stall_warning_secs = get_float(
            HOROVOD_STALL_CHECK_TIME_SECONDS, DEFAULT_STALL_WARNING_SECS)
        self.stall_shutdown_secs = get_float(HOROVOD_STALL_SHUTDOWN_TIME_SECONDS, 0.0)
        self.elastic = get_bool(HOROVOD_ELASTIC)
        # telemetry exposition (docs/observability.md): metrics_port 0
        # = no per-worker HTTP endpoint.  The snapshot push that feeds
        # the coordinator's job-wide /metrics defaults on (cheap: one
        # small KV put per interval) whenever an endpoint is enabled,
        # and can be forced on/off explicitly.
        self.metrics_port = get_int(HOROVOD_METRICS_PORT, 0)
        self.metrics_push_secs = get_float(
            HOROVOD_METRICS_PUSH_SECONDS,
            2.0 if self.metrics_port else 0.0)
        # flight recorder (docs/timeline.md): always-on bounded ring of
        # recent timeline events, default on — the emit path is a dict
        # + deque append, cheap enough for the dispatch loop; 0
        # disables.  Stall warnings auto-dump it (engine.dump_trace).
        self.trace_ring_events = get_int(HOROVOD_TRACE_RING_EVENTS, 4096)
        self.trace_dump_dir = get_str(HOROVOD_TRACE_DUMP_DIR)
        # NTP-style clock sync against the launcher's clock, re-sampled
        # for drift; multi-process only (single-process traces carry a
        # wall-clock mapping from birth)
        self.clock_sync_secs = get_float(
            HOROVOD_TRACE_CLOCK_SYNC_SECONDS, 30.0)
        # process-set removal is a barrier across local rank threads;
        # this bounds the wait for peers' votes and the drain of
        # in-flight collectives on the set
        self.ps_removal_timeout_secs = get_float(
            HOROVOD_PROCESS_SET_REMOVAL_TIMEOUT, 60.0)
        # worker liveness (docs/fault_tolerance.md): heartbeat cadence
        # to the coordinator in multi-process jobs; 0 disables.  The
        # coordinator's death window rides autotune_kwargs from the
        # same env so both sides agree.
        self.heartbeat_secs = get_float(
            HOROVOD_HEARTBEAT_INTERVAL_SECONDS, 5.0)
        # steady-state negotiation bypass (docs/fault_tolerance.md +
        # core/bypass.py): after K identical negotiation cycles the
        # ranks agree via a bitvector exchange and skip the
        # coordinator; 0 disables.  The wait bound forces the
        # unanimous fallback when a cached tensor never goes ready.
        self.bypass_after_cycles = get_int(
            HOROVOD_BYPASS_AFTER_CYCLES, 5)
        self.bypass_wait_secs = get_float(
            HOROVOD_BYPASS_WAIT_SECONDS, 10.0)
        # chaos fault plan (raw source; parsed by chaos.plan_from_env
        # at init so a malformed plan fails loudly, not silently)
        self.fault_plan = get_str(HOROVOD_FAULT_PLAN)
        # MPMD pipeline runtime (parallel/runtime.py): stage count,
        # schedule and microbatch count.  (pp_schedule, pp_n_micro)
        # is ONE autotune categorical (the seventh dimension) — the
        # runtime latches the pair at each step start, and the engine
        # latches it per negotiation entry on the step's gradient
        # reduces so a mid-step autotune flip can never split one
        # step across two schedules.
        self.pp_stages = get_int(HOROVOD_PP_STAGES, 1)
        raw_sched = get_str(HOROVOD_PP_SCHEDULE)
        if raw_sched:
            # lazy: importing parallel.schedule executes the whole
            # parallel package (flax models, attention helpers) —
            # only jobs that actually set a schedule pay that, and
            # they import it again at make_lm_train_step anyway
            from ..parallel.schedule import normalize_schedule
            self.pp_schedule = normalize_schedule(raw_sched) or "1f1b"
        else:
            self.pp_schedule = "1f1b"
        self.pp_n_micro = get_int(HOROVOD_PP_MICROBATCHES, 0)
        self.pp_chunks = get_int(HOROVOD_PP_CHUNKS, 0)
        # autotune warm-start cache file (core/autotune.py load/save)
        self.autotune_cache = get_str(HOROVOD_AUTOTUNE_CACHE)
        # ZeRO-grade weight-update sharding (core/sharded.py): the
        # process-wide default frontends resolve sharded=None against,
        # and the shard-bucket layout — the autotuner's EIGHTH
        # dimension, re-read by the updaters at each (re)build so a
        # sweep flip re-shards deterministically instead of mid-step
        self.sharded_optimizer = get_bool(HOROVOD_SHARDED_OPTIMIZER)
        raw_layout = get_str(HOROVOD_SHARD_LAYOUT)
        if raw_layout:
            # lazy normalize: core.sharded is tiny, but a malformed
            # value must fail loudly at init, not at first step
            from ..core.sharded import normalize_shard_layout
            self.shard_layout = normalize_shard_layout(raw_layout)
        else:
            self.shard_layout = "bucket"
        # bucket-granular comm/compute overlap (ops/compiled.py):
        # max payload bytes per compiled bucket program (0 = one
        # grouped program), and whether the autotuner sweeps the
        # bucket size as its ninth dimension.  The reducer latches
        # the value once per call/stream — a mid-step autotune flip
        # never splits one step across bucketings.
        self.overlap_bucket_bytes = get_int(
            HOROVOD_OVERLAP_BUCKET_BYTES, 0)
        self.overlap_autotune = get_bool(HOROVOD_OVERLAP_AUTOTUNE)
        # end-to-end step integrity (core/integrity.py): wire
        # checksums + the implicated-rank vote default ON; the
        # sentinel cadence and guards are read by StepSentinel, the
        # eviction threshold by the engine's scoreboard
        self.integrity = get_bool(HOROVOD_INTEGRITY, True)
        self.integrity_sentinel_steps = get_int(
            HOROVOD_INTEGRITY_SENTINEL_STEPS, 50)
        self.integrity_evict_after = get_int(
            HOROVOD_INTEGRITY_EVICT_AFTER, 3)
        self.integrity_max_grad_norm = get_float(
            HOROVOD_INTEGRITY_MAX_GRAD_NORM, 0.0)
        # expert parallelism (parallel/moe.py): total experts (0 = no
        # MoE), fixed-capacity routing factor, router top-k, and the
        # expert-parallel degree cap (0 = every rank).  (ep ×
        # capacity factor) is the autotuner's TENTH dimension, swept
        # only when experts are present; layers re-read the pair at
        # each step start so a sweep flip re-routes deterministically
        # between steps, never inside one.
        self.moe_experts = get_int(HOROVOD_MOE_EXPERTS, 0)
        self.moe_capacity_factor = get_float(
            HOROVOD_MOE_CAPACITY_FACTOR, 1.25)
        self.moe_topk = get_int(HOROVOD_MOE_TOPK, 2)
        self.moe_ep = get_int(HOROVOD_MOE_EP, 0)

"""Elastic training core: State commit/restore/sync + the retry loop.

Reference: ``horovod/common/elastic.py`` (State :26, ObjectState :116,
run_fn :151).  A worker wraps its training function with ``run_fn``;
on ``HorovodInternalError`` the last committed state is restored and
the job re-rendezvouses; on ``HostsUpdatedInterrupt`` the current
state is kept and ranks re-sync.  On TPU a membership change means the
mesh must be rebuilt, so reset() tears the engine down and re-inits.
"""

import functools
import logging
import os
import pickle
import queue
import tempfile

from . import basics
from . import env
from .exceptions import HorovodInternalError, HostsUpdatedInterrupt


def _spill_path():
    """Per-worker state spill file.  The elastic driver sets
    ``HOROVOD_STATE_SPILL`` to a job directory; committed state is
    mirrored there so recovery survives even a *process* restart —
    needed on TPU because a peer's death fatally terminates the jax
    distributed client in survivors (coordination-service heartbeat),
    where the reference's NCCL failures are catchable in-process."""
    d = env.get_str(env.HOROVOD_STATE_SPILL)
    if not d:
        return None
    host = env.get_str(env.HOROVOD_HOSTNAME, "localhost")
    slot = env.get_int(env.HOROVOD_LOCAL_RANK, 0)
    return os.path.join(d, f"state_{host}_{slot}.pkl")


def _count_commit():
    """One elastic commit into the process-current registry — the
    training goodput unit the fleet controller aggregates per job off
    the merged snapshot pushes (docs/fleet.md).  Resolved per call:
    the engine installs a fresh registry each lifecycle."""
    try:
        from .. import telemetry
        telemetry.registry().counter(
            telemetry.ELASTIC_COMMITS_FAMILY,
            telemetry.ELASTIC_COMMITS_HELP).inc()
    except Exception:  # noqa: BLE001 — accounting must never block a commit
        pass


class State:
    """Base class: save/restore/sync + registered reset callbacks
    (reference common/elastic.py:26-98)."""

    def __init__(self, **kwargs):
        self._host_messages = queue.Queue()
        self._last_updated_timestamp = 0
        self._reset_callbacks = []
        self._maybe_unspill()

    def register_reset_callbacks(self, callbacks):
        self._reset_callbacks.extend(callbacks)

    def on_reset(self):
        self._host_messages = queue.Queue()
        self.reset()
        for callback in self._reset_callbacks:
            callback()

    def on_hosts_updated(self, timestamp, update_res):
        self._host_messages.put((timestamp, update_res))

    def commit(self):
        """Save and check for pending host updates (the reference
        commits then raises HostsUpdatedInterrupt at a safe point)."""
        self.save()
        self._spill()
        _count_commit()
        self.check_host_updates()

    # -- crash-durable spill ------------------------------------------------

    def _spill_payload(self):
        return None

    def _load_spill(self, payload):
        pass

    def _spill(self):
        """Write the spill with a CRC trailer, keeping the previous
        generation as ``<path>.prev``: a torn or corrupted write
        (power loss mid-replace, bit rot — exercised by the
        ``corrupt_spill`` chaos kind) is DETECTED at load and recovery
        falls back to the previous commit instead of deserializing
        garbage into the restored state."""
        path = _spill_path()
        payload = self._spill_payload()
        if path is None or payload is None:
            return
        from ..core import integrity as integrity_mod

        tmp = None
        try:
            blob = integrity_mod.append_crc_trailer(
                pickle.dumps(payload,
                             protocol=pickle.HIGHEST_PROTOCOL))
            from .. import chaos as chaos_mod
            inj = chaos_mod.current()
            if inj is not None:
                # corrupt_spill chaos rides the REAL write: the CRC
                # was computed over the true bytes, so the flipped
                # blob is exactly what a torn write leaves behind
                blob = inj.corrupt_spill(blob)
            fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
            with os.fdopen(fd, "wb") as f:
                f.write(blob)
            if os.path.exists(path):
                os.replace(path, path + ".prev")
            os.replace(tmp, path)
        except Exception:  # noqa: BLE001 — spill is best-effort
            if tmp and os.path.exists(tmp):
                os.unlink(tmp)

    def _maybe_unspill(self):
        path = _spill_path()
        if not path:
            return
        from .. import telemetry
        from ..core import integrity as integrity_mod

        for candidate in (path, path + ".prev"):
            if not os.path.exists(candidate):
                continue
            try:
                with open(candidate, "rb") as f:
                    blob = integrity_mod.strip_crc_trailer(f.read())
                payload = pickle.loads(blob)
            except Exception as exc:  # noqa: BLE001 — fall back LOUDLY
                telemetry.count_integrity_check("corrupt", "spill")
                logging.getLogger("horovod_tpu").warning(
                    "elastic spill %s failed integrity verification "
                    "(%s: %s); falling back to %s", candidate,
                    type(exc).__name__, exc,
                    "the previous commit" if candidate == path
                    else "a fresh state")
                if candidate == path and isinstance(
                        exc, (integrity_mod.TrailerCorruptionError,
                              pickle.UnpicklingError, EOFError)):
                    # the file itself is bad ON DISK (torn/corrupt):
                    # drop it NOW, or the next _spill rotates it over
                    # the good .prev we are falling back to.  Scoped
                    # to on-disk badness — a loader/schema error must
                    # never delete a valid commit.
                    try:
                        os.unlink(candidate)
                    except OSError:
                        pass
                continue
            try:
                self._load_spill(payload)
                telemetry.count_integrity_check("ok", "spill")
                return
            except Exception:  # noqa: BLE001 — schema mismatch: the
                # file is VALID on disk (keep it for a binary
                # rollback); just don't install it
                logging.getLogger("horovod_tpu").exception(
                    "elastic spill %s verified but failed to install",
                    candidate)

    def check_host_updates(self):
        """Raise HostsUpdatedInterrupt if the driver pushed membership
        changes since the last check (reference :58-77)."""
        updated = False
        skip_sync = True
        while not self._host_messages.empty():
            timestamp, update_res = self._host_messages.get()
            if timestamp > self._last_updated_timestamp:
                self._last_updated_timestamp = timestamp
                updated = True
                # removals require rollback; additions may skip sync
                skip_sync = skip_sync and not bool(update_res)
        if updated:
            raise HostsUpdatedInterrupt(skip_sync)

    def save(self):
        raise NotImplementedError

    def restore(self):
        raise NotImplementedError

    def sync(self):
        raise NotImplementedError

    def reset(self):
        pass


class ObjectState(State):
    """State for arbitrary picklable attributes: save keeps an
    in-memory copy, sync broadcasts from rank 0 (reference
    common/elastic.py:116-148)."""

    def __init__(self, bcast_object, get_rank, **kwargs):
        self._bcast_object = bcast_object
        self._rank = get_rank
        self._saved_state = kwargs
        self._set_attrs()
        super().__init__()

    def save(self):
        new_state = {}
        for attr in self._saved_state.keys():
            new_state[attr] = getattr(self, attr)
        self._saved_state = new_state

    def restore(self):
        self._set_attrs()

    def sync(self):
        if self._saved_state:
            self._saved_state = self._bcast_object(self._saved_state)
            self._set_attrs()
            self._spill()

    def _set_attrs(self):
        for attr, value in self._saved_state.items():
            setattr(self, attr, value)

    def _spill_payload(self):
        return {"saved_state": self._saved_state}

    def _load_spill(self, payload):
        self._saved_state.update(payload.get("saved_state", {}))
        self._set_attrs()


def run_fn(func, reset):
    """Elastic retry loop (reference common/elastic.py:151-175)."""
    @functools.wraps(func)
    def wrapper(state, *args, **kwargs):
        notification_manager = _get_notification_manager()
        if notification_manager is not None:
            notification_manager.init()
            notification_manager.register_listener(state)
        skip_sync = False
        try:
            while True:
                try:
                    if not skip_sync:
                        state.sync()
                    return func(state, *args, **kwargs)
                except HorovodInternalError as e:
                    if getattr(e, "evict", False):
                        # eviction-grade integrity verdict
                        # (core/integrity.HostEvictionError): repeated
                        # detections implicated THIS host — die so the
                        # driver's blacklist verdict evicts it instead
                        # of endlessly replaying a corrupting host
                        # (docs/fault_tolerance.md "Silent data
                        # corruption")
                        raise
                    # comm failure (peer died / stale round): roll back
                    # to the last commit — covers failures inside
                    # sync() too, which the reference leaves uncaught
                    state.restore()
                    skip_sync = False
                    if getattr(e, "quarantine", False):
                        # step-integrity quarantine: the implicated-
                        # rank vote made the verdict unanimous and
                        # every engine survived delivering it, so the
                        # mesh is healthy — replay in place (restore +
                        # resync) instead of tearing it down; a
                        # teardown here would park every worker in the
                        # rendezvous waiting for a round the driver
                        # (which saw no death and no discovery change)
                        # will never re-form
                        continue
                except HostsUpdatedInterrupt as e:
                    skip_sync = e.skip_sync
                reset()
                state.on_reset()
        finally:
            if notification_manager is not None:
                notification_manager.remove_listener(state)
    return wrapper


def _get_notification_manager():
    """The launcher-side worker notification channel; absent when not
    running under the elastic launcher."""
    try:
        from ..runner.elastic.worker import notification_manager
        return notification_manager
    except Exception:  # pragma: no cover — runner not in use
        return None


# reference common/elastic.py module attribute: the process-wide
# notification manager (lazy here — resolving at import would pull the
# runner stack into every frontend import)
def __getattr__(name):
    if name == "notification_manager":
        manager = _get_notification_manager()
        if manager is None:
            raise AttributeError(
                "notification_manager is unavailable (runner stack "
                "not importable)")
        return manager
    raise AttributeError(f"module {__name__!r} has no attribute "
                         f"{name!r}")

"""Elastic training core: State commit/restore/sync + the retry loop.

Reference: ``horovod/common/elastic.py`` (State :26, ObjectState :116,
run_fn :151).  A worker wraps its training function with ``run_fn``;
on ``HorovodInternalError`` the last committed state is restored and
the job re-rendezvouses; on ``HostsUpdatedInterrupt`` the current
state is kept and ranks re-sync.  On TPU a membership change means the
mesh must be rebuilt, so reset() tears the engine down and re-inits.
"""

import functools
import os
import pickle
import queue
import tempfile

from . import basics
from . import env
from .exceptions import HorovodInternalError, HostsUpdatedInterrupt


def _spill_path():
    """Per-worker state spill file.  The elastic driver sets
    ``HOROVOD_STATE_SPILL`` to a job directory; committed state is
    mirrored there so recovery survives even a *process* restart —
    needed on TPU because a peer's death fatally terminates the jax
    distributed client in survivors (coordination-service heartbeat),
    where the reference's NCCL failures are catchable in-process."""
    d = env.get_str(env.HOROVOD_STATE_SPILL)
    if not d:
        return None
    host = env.get_str(env.HOROVOD_HOSTNAME, "localhost")
    slot = env.get_int(env.HOROVOD_LOCAL_RANK, 0)
    return os.path.join(d, f"state_{host}_{slot}.pkl")


def _count_commit():
    """One elastic commit into the process-current registry — the
    training goodput unit the fleet controller aggregates per job off
    the merged snapshot pushes (docs/fleet.md).  Resolved per call:
    the engine installs a fresh registry each lifecycle."""
    try:
        from .. import telemetry
        telemetry.registry().counter(
            telemetry.ELASTIC_COMMITS_FAMILY,
            telemetry.ELASTIC_COMMITS_HELP).inc()
    except Exception:  # noqa: BLE001 — accounting must never block a commit
        pass


class State:
    """Base class: save/restore/sync + registered reset callbacks
    (reference common/elastic.py:26-98)."""

    def __init__(self, **kwargs):
        self._host_messages = queue.Queue()
        self._last_updated_timestamp = 0
        self._reset_callbacks = []
        self._maybe_unspill()

    def register_reset_callbacks(self, callbacks):
        self._reset_callbacks.extend(callbacks)

    def on_reset(self):
        self._host_messages = queue.Queue()
        self.reset()
        for callback in self._reset_callbacks:
            callback()

    def on_hosts_updated(self, timestamp, update_res):
        self._host_messages.put((timestamp, update_res))

    def commit(self):
        """Save and check for pending host updates (the reference
        commits then raises HostsUpdatedInterrupt at a safe point)."""
        self.save()
        self._spill()
        _count_commit()
        self.check_host_updates()

    # -- crash-durable spill ------------------------------------------------

    def _spill_payload(self):
        return None

    def _load_spill(self, payload):
        pass

    def _spill(self):
        path = _spill_path()
        payload = self._spill_payload()
        if path is None or payload is None:
            return
        tmp = None
        try:
            fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
            with os.fdopen(fd, "wb") as f:
                pickle.dump(payload, f)
            os.replace(tmp, path)
        except Exception:  # noqa: BLE001 — spill is best-effort
            if tmp and os.path.exists(tmp):
                os.unlink(tmp)

    def _maybe_unspill(self):
        path = _spill_path()
        if path and os.path.exists(path):
            try:
                with open(path, "rb") as f:
                    self._load_spill(pickle.load(f))
            except Exception:  # noqa: BLE001 — corrupt spill: start fresh
                pass

    def check_host_updates(self):
        """Raise HostsUpdatedInterrupt if the driver pushed membership
        changes since the last check (reference :58-77)."""
        updated = False
        skip_sync = True
        while not self._host_messages.empty():
            timestamp, update_res = self._host_messages.get()
            if timestamp > self._last_updated_timestamp:
                self._last_updated_timestamp = timestamp
                updated = True
                # removals require rollback; additions may skip sync
                skip_sync = skip_sync and not bool(update_res)
        if updated:
            raise HostsUpdatedInterrupt(skip_sync)

    def save(self):
        raise NotImplementedError

    def restore(self):
        raise NotImplementedError

    def sync(self):
        raise NotImplementedError

    def reset(self):
        pass


class ObjectState(State):
    """State for arbitrary picklable attributes: save keeps an
    in-memory copy, sync broadcasts from rank 0 (reference
    common/elastic.py:116-148)."""

    def __init__(self, bcast_object, get_rank, **kwargs):
        self._bcast_object = bcast_object
        self._rank = get_rank
        self._saved_state = kwargs
        self._set_attrs()
        super().__init__()

    def save(self):
        new_state = {}
        for attr in self._saved_state.keys():
            new_state[attr] = getattr(self, attr)
        self._saved_state = new_state

    def restore(self):
        self._set_attrs()

    def sync(self):
        if self._saved_state:
            self._saved_state = self._bcast_object(self._saved_state)
            self._set_attrs()
            self._spill()

    def _set_attrs(self):
        for attr, value in self._saved_state.items():
            setattr(self, attr, value)

    def _spill_payload(self):
        return {"saved_state": self._saved_state}

    def _load_spill(self, payload):
        self._saved_state.update(payload.get("saved_state", {}))
        self._set_attrs()


def run_fn(func, reset):
    """Elastic retry loop (reference common/elastic.py:151-175)."""
    @functools.wraps(func)
    def wrapper(state, *args, **kwargs):
        notification_manager = _get_notification_manager()
        if notification_manager is not None:
            notification_manager.init()
            notification_manager.register_listener(state)
        skip_sync = False
        try:
            while True:
                try:
                    if not skip_sync:
                        state.sync()
                    return func(state, *args, **kwargs)
                except HorovodInternalError:
                    # comm failure (peer died / stale round): roll back
                    # to the last commit — covers failures inside
                    # sync() too, which the reference leaves uncaught
                    state.restore()
                    skip_sync = False
                except HostsUpdatedInterrupt as e:
                    skip_sync = e.skip_sync
                reset()
                state.on_reset()
        finally:
            if notification_manager is not None:
                notification_manager.remove_listener(state)
    return wrapper


def _get_notification_manager():
    """The launcher-side worker notification channel; absent when not
    running under the elastic launcher."""
    try:
        from ..runner.elastic.worker import notification_manager
        return notification_manager
    except Exception:  # pragma: no cover — runner not in use
        return None


# reference common/elastic.py module attribute: the process-wide
# notification manager (lazy here — resolving at import would pull the
# runner stack into every frontend import)
def __getattr__(name):
    if name == "notification_manager":
        manager = _get_notification_manager()
        if manager is None:
            raise AttributeError(
                "notification_manager is unavailable (runner stack "
                "not importable)")
        return manager
    raise AttributeError(f"module {__name__!r} has no attribute "
                         f"{name!r}")

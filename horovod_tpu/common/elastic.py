"""Elastic training core: State commit/restore/sync + the retry loop.

Reference: ``horovod/common/elastic.py`` (State :26, ObjectState :116,
run_fn :151).  A worker wraps its training function with ``run_fn``;
on ``HorovodInternalError`` the last committed state is restored and
the job re-rendezvouses; on ``HostsUpdatedInterrupt`` the current
state is kept and ranks re-sync.  On TPU a membership change means the
mesh must be rebuilt, so reset() tears the engine down and re-inits.
"""

import functools
import queue

from . import basics
from .exceptions import HorovodInternalError, HostsUpdatedInterrupt


class State:
    """Base class: save/restore/sync + registered reset callbacks
    (reference common/elastic.py:26-98)."""

    def __init__(self, **kwargs):
        self._host_messages = queue.Queue()
        self._last_updated_timestamp = 0
        self._reset_callbacks = []

    def register_reset_callbacks(self, callbacks):
        self._reset_callbacks.extend(callbacks)

    def on_reset(self):
        self._host_messages = queue.Queue()
        self.reset()
        for callback in self._reset_callbacks:
            callback()

    def on_hosts_updated(self, timestamp, update_res):
        self._host_messages.put((timestamp, update_res))

    def commit(self):
        """Save and check for pending host updates (the reference
        commits then raises HostsUpdatedInterrupt at a safe point)."""
        self.save()
        self.check_host_updates()

    def check_host_updates(self):
        """Raise HostsUpdatedInterrupt if the driver pushed membership
        changes since the last check (reference :58-77)."""
        updated = False
        skip_sync = True
        while not self._host_messages.empty():
            timestamp, update_res = self._host_messages.get()
            if timestamp > self._last_updated_timestamp:
                self._last_updated_timestamp = timestamp
                updated = True
                # removals require rollback; additions may skip sync
                skip_sync = skip_sync and not bool(update_res)
        if updated:
            raise HostsUpdatedInterrupt(skip_sync)

    def save(self):
        raise NotImplementedError

    def restore(self):
        raise NotImplementedError

    def sync(self):
        raise NotImplementedError

    def reset(self):
        pass


class ObjectState(State):
    """State for arbitrary picklable attributes: save keeps an
    in-memory copy, sync broadcasts from rank 0 (reference
    common/elastic.py:116-148)."""

    def __init__(self, bcast_object, get_rank, **kwargs):
        self._bcast_object = bcast_object
        self._rank = get_rank
        self._saved_state = kwargs
        self._set_attrs()
        super().__init__()

    def save(self):
        new_state = {}
        for attr in self._saved_state.keys():
            new_state[attr] = getattr(self, attr)
        self._saved_state = new_state

    def restore(self):
        self._set_attrs()

    def sync(self):
        if self._saved_state:
            self._saved_state = self._bcast_object(self._saved_state)
            self._set_attrs()

    def _set_attrs(self):
        for attr, value in self._saved_state.items():
            setattr(self, attr, value)


def run_fn(func, reset):
    """Elastic retry loop (reference common/elastic.py:151-175)."""
    @functools.wraps(func)
    def wrapper(state, *args, **kwargs):
        notification_manager = _get_notification_manager()
        if notification_manager is not None:
            notification_manager.init()
            notification_manager.register_listener(state)
        skip_sync = False
        try:
            while True:
                if not skip_sync:
                    state.sync()
                try:
                    return func(state, *args, **kwargs)
                except HorovodInternalError:
                    state.restore()
                    skip_sync = False
                except HostsUpdatedInterrupt as e:
                    skip_sync = e.skip_sync
                reset()
                state.on_reset()
        finally:
            if notification_manager is not None:
                notification_manager.remove_listener(state)
    return wrapper


def _get_notification_manager():
    """The launcher-side worker notification channel; absent when not
    running under the elastic launcher."""
    try:
        from ..runner.elastic.worker import notification_manager
        return notification_manager
    except Exception:  # pragma: no cover — runner not in use
        return None

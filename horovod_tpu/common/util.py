"""Array conversion + misc helpers shared across the API surface."""

import io
import numpy as np


def to_numpy(tensor):
    """Convert an input value to a host ndarray, remembering the
    original kind so results can be returned in the caller's type.
    Supported kinds: numpy, jax, python scalar/list."""
    kind = "numpy"
    if hasattr(tensor, "__module__") and type(tensor).__module__.startswith("jax"):
        kind = "jax"
        arr = np.asarray(tensor)
    elif isinstance(tensor, np.ndarray):
        arr = tensor
    elif isinstance(tensor, (int, float, bool, complex)):
        kind = "scalar"
        arr = np.asarray(tensor)
    elif isinstance(tensor, (list, tuple)):
        kind = "numpy"
        arr = np.asarray(tensor)
    else:
        # torch / tf tensors are converted by their bindings before
        # reaching the core API; anything else must support __array__.
        arr = np.asarray(tensor)
    return arr, kind


def from_numpy(arr, kind):
    if kind == "jax":
        import jax.numpy as jnp
        return jnp.asarray(arr)
    if kind == "scalar":
        return arr.item() if arr.ndim == 0 else arr
    return arr


def dumps(obj) -> np.ndarray:
    """Pickle an object into a uint8 tensor (reference
    tensorflow/functions.py broadcast_object serialization)."""
    import pickle
    buf = io.BytesIO()
    pickle.dump(obj, buf, protocol=pickle.HIGHEST_PROTOCOL)
    return np.frombuffer(buf.getvalue(), dtype=np.uint8).copy()


def loads(arr) -> object:
    import pickle
    return pickle.loads(arr.tobytes())

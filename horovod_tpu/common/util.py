"""Array conversion + misc helpers shared across the API surface."""

import io
import numpy as np


def to_numpy(tensor):
    """Convert an input value to a host ndarray, remembering the
    original kind so results can be returned in the caller's type.
    Supported kinds: numpy, jax, torch, tf, python scalar/list.

    This is the DLPack-free staging layer of SURVEY §7 step 2: torch
    and TF tensors in this image live on host, so ``.numpy()`` views
    are zero-copy; the single H2D transfer happens per fused bucket in
    the executor."""
    kind = "numpy"
    mod = type(tensor).__module__
    if mod.startswith("jax"):
        kind = "jax"
        arr = np.asarray(tensor)
    elif mod.startswith("torch"):
        kind = "torch"
        t = tensor.detach().cpu()
        if str(t.dtype) == "torch.bfloat16":
            # numpy has no native bf16: reinterpret the bits as
            # ml_dtypes.bfloat16 so the wire stays 16-bit (fp16
            # compression halves collective bytes — keep that).
            import ml_dtypes
            # dtype-reinterpreting view needs a contiguous tensor
            # (transposed/sliced bf16 params would raise otherwise)
            arr = t.contiguous().view(__import__("torch").uint16) \
                .numpy().view(ml_dtypes.bfloat16)
        else:
            arr = t.numpy()
    elif mod.startswith("tensorflow"):
        kind = "tf"
        arr = tensor.numpy() if hasattr(tensor, "numpy") else np.asarray(tensor)
    elif mod.startswith("mxnet"):
        kind = "mxnet"
        arr = tensor.asnumpy()
    elif isinstance(tensor, np.ndarray):
        arr = tensor
    elif isinstance(tensor, (int, float, bool, complex)):
        kind = "scalar"
        arr = np.asarray(tensor)
    elif isinstance(tensor, (list, tuple)):
        kind = "numpy"
        arr = np.asarray(tensor)
    else:
        arr = np.asarray(tensor)
    return arr, kind


def from_numpy(arr, kind):
    if kind == "jax":
        import jax.numpy as jnp
        return jnp.asarray(arr)
    if kind == "torch":
        import torch
        if str(arr.dtype) == "bfloat16":
            return torch.from_numpy(
                np.ascontiguousarray(arr).view(np.uint16)).view(
                torch.bfloat16)
        return torch.from_numpy(np.ascontiguousarray(arr))
    if kind == "tf":
        import tensorflow as tf
        return tf.convert_to_tensor(arr)
    if kind == "mxnet":
        import mxnet as mx
        return mx.nd.array(arr, dtype=arr.dtype)
    if kind == "scalar":
        return arr.item() if arr.ndim == 0 else arr
    return arr


def copy_into(target, arr):
    """In-place copy of a host result into a framework tensor."""
    mod = type(target).__module__
    if mod.startswith("torch"):
        import torch
        with torch.no_grad():
            src = from_numpy(arr, "torch")   # handles bf16 bit views
            target.copy_(src.view_as(target))
        return target
    if mod.startswith("mxnet"):
        target[:] = arr.reshape(target.shape)
        return target
    np.copyto(target, arr.reshape(target.shape))
    return target


def dumps(obj) -> np.ndarray:
    """Pickle an object into a uint8 tensor (reference
    tensorflow/functions.py broadcast_object serialization)."""
    import pickle
    buf = io.BytesIO()
    pickle.dump(obj, buf, protocol=pickle.HIGHEST_PROTOCOL)
    return np.frombuffer(buf.getvalue(), dtype=np.uint8).copy()


def loads(arr) -> object:
    import pickle
    return pickle.loads(arr.tobytes())

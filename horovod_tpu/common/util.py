"""Array conversion + misc helpers shared across the API surface."""

import io
import numpy as np


def to_numpy(tensor):
    """Convert an input value to a host ndarray, remembering the
    original kind so results can be returned in the caller's type.
    Supported kinds: numpy, jax, torch, tf, python scalar/list.

    This is the DLPack-free staging layer of SURVEY §7 step 2: torch
    and TF tensors in this image live on host, so ``.numpy()`` views
    are zero-copy; the single H2D transfer happens per fused bucket in
    the executor."""
    kind = "numpy"
    mod = type(tensor).__module__
    if mod.startswith("jax"):
        kind = "jax"
        arr = np.asarray(tensor)
    elif mod.startswith("torch"):
        kind = "torch"
        t = tensor.detach().cpu()
        if str(t.dtype) == "torch.bfloat16":
            # numpy has no native bf16: reinterpret the bits as
            # ml_dtypes.bfloat16 so the wire stays 16-bit (fp16
            # compression halves collective bytes — keep that).
            import ml_dtypes
            # dtype-reinterpreting view needs a contiguous tensor
            # (transposed/sliced bf16 params would raise otherwise)
            arr = t.contiguous().view(__import__("torch").uint16) \
                .numpy().view(ml_dtypes.bfloat16)
        else:
            arr = t.numpy()
    elif mod.startswith("tensorflow"):
        kind = "tf"
        arr = tensor.numpy() if hasattr(tensor, "numpy") else np.asarray(tensor)
    elif mod.startswith("mxnet"):
        kind = "mxnet"
        arr = tensor.asnumpy()
    elif isinstance(tensor, np.ndarray):
        arr = tensor
    elif isinstance(tensor, (int, float, bool, complex)):
        kind = "scalar"
        arr = np.asarray(tensor)
    elif isinstance(tensor, (list, tuple)):
        kind = "numpy"
        arr = np.asarray(tensor)
    else:
        arr = np.asarray(tensor)
    return arr, kind


def from_numpy(arr, kind):
    if kind == "jax":
        import jax.numpy as jnp
        return jnp.asarray(arr)
    if kind == "torch":
        import torch
        if str(arr.dtype) == "bfloat16":
            return torch.from_numpy(
                np.ascontiguousarray(arr).view(np.uint16)).view(
                torch.bfloat16)
        return torch.from_numpy(np.ascontiguousarray(arr))
    if kind == "tf":
        import tensorflow as tf
        return tf.convert_to_tensor(arr)
    if kind == "mxnet":
        import mxnet as mx
        return mx.nd.array(arr, dtype=arr.dtype)
    if kind == "scalar":
        return arr.item() if arr.ndim == 0 else arr
    return arr


def copy_into(target, arr):
    """In-place copy of a host result into a framework tensor."""
    mod = type(target).__module__
    if mod.startswith("torch"):
        import torch
        with torch.no_grad():
            src = from_numpy(arr, "torch")   # handles bf16 bit views
            target.copy_(src.view_as(target))
        return target
    if mod.startswith("mxnet"):
        target[:] = arr.reshape(target.shape)
        return target
    np.copyto(target, arr.reshape(target.shape))
    return target


def dumps(obj) -> np.ndarray:
    """Pickle an object into a uint8 tensor (reference
    tensorflow/functions.py broadcast_object serialization)."""
    import pickle
    buf = io.BytesIO()
    pickle.dump(obj, buf, protocol=pickle.HIGHEST_PROTOCOL)
    return np.frombuffer(buf.getvalue(), dtype=np.uint8).copy()


def loads(arr) -> object:
    import pickle
    return pickle.loads(arr.tobytes())


# -- reference horovod/common/util.py parity helpers -------------------------
#
# The reference's util module doubles as its build-introspection layer
# (compiled per-framework extensions, metadata.json version stamps).
# This build has no compiled frontend extensions — the queries below
# answer for the frontends' importability and this package's version
# instead, keeping the call sites of migrating scripts working.

EXTENSIONS = ("tensorflow", "torch", "mxnet", "jax")


def get_ext_suffix():
    """Native-extension filename suffix (reference util.py:34)."""
    import sysconfig
    return sysconfig.get_config_var("EXT_SUFFIX") or ".so"


def get_extension_full_path(pkg_path, *args):
    """Path a compiled extension would occupy (reference util.py:47)."""
    import os
    dir_path = os.path.join(os.path.dirname(pkg_path), *args[:-1])
    return os.path.join(dir_path, args[-1] + get_ext_suffix())


def extension_available(ext_base_name, verbose=False):
    """Whether the named frontend is usable (reference util.py:108).
    There is no compiled extension to probe; the frontend is available
    iff its framework imports."""
    import importlib.util
    if ext_base_name not in EXTENSIONS:
        return False
    return importlib.util.find_spec(ext_base_name) is not None


def check_extension(ext_name, ext_env_var, pkg_path, *args):
    """Reference util.py:54 raises when a frontend was built without
    its extension.  Here the equivalent failure is the framework being
    absent from the environment."""
    base = ext_name.split(".")[-1]
    if base in EXTENSIONS and not extension_available(base):
        raise ImportError(
            f"Extension {ext_name} requires {base}, which is not "
            f"installed in this environment.")


def gpu_available(ext_base_name, verbose=False):
    """Reference util.py:131.  The TPU build has no CUDA/ROCm path;
    accelerator presence is a JAX device query, see ``tpu_built``."""
    return False


def env(**kwargs):
    """Context manager: temporarily set environment variables, ignoring
    None values (reference util.py:189)."""
    import contextlib
    import os

    @contextlib.contextmanager
    def _ctx():
        updates = {k: v for k, v in kwargs.items() if v is not None}
        backup = {k: os.environ.get(k) for k in updates}
        os.environ.update(updates)
        try:
            yield
        finally:
            for k, old in backup.items():
                if old is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = old

    return _ctx()


def get_average_backwards_compatibility_fun(reduce_ops):
    """Adapter from the deprecated ``average=`` kwarg to ``op=``
    (reference util.py:214-232): passing both is an error, ``average``
    alone warns and maps True/False to Average/Sum, neither defaults
    to Average."""
    import warnings

    def impl(op, average):
        if op is not None:
            if average is not None:
                raise ValueError(
                    "The op parameter supersedes average. Please "
                    "provide only one of them.")
            return op
        if average is not None:
            warnings.warn(
                "Parameter `average` has been replaced with `op` and "
                "will be removed in v1.0", DeprecationWarning)
            return reduce_ops.Average if average else reduce_ops.Sum
        return reduce_ops.Average

    return impl


def reducescatter_grad_factor(op_is_average, size):
    """Scalar the reducescatter backward multiplies the allgathered
    cotangent by (before the linear prescale*postscale the forward
    applied).

    Default: the REFERENCE convention (tensorflow/mpi_ops.py:483-506 /
    torch mpi_ops_v2 — Sum gradient scaled BY world size, Average
    unscaled), which is size x the true adjoint of the Sum forward but
    is what every migrated multi-worker job was trained against.
    ``HOROVOD_EXACT_ADJOINT_REDUCESCATTER=1`` opts into the exact
    adjoint (Sum unscaled, Average /= size); the two coincide at
    world size 1.  See docs/migration.md "reducescatter gradients"."""
    from . import env as env_mod

    exact = env_mod.get_bool(env_mod.HOROVOD_EXACT_ADJOINT_REDUCESCATTER)
    if op_is_average:
        return 1.0 / size if exact else 1.0
    return 1.0 if exact else float(size)


def num_rank_is_power_2(num_rank):
    """Adasum's rank-count precondition (reference util.py:235)."""
    return num_rank != 0 and (num_rank & (num_rank - 1)) == 0


def split_list(l, n):  # noqa: E741 — reference signature
    """Split ``l`` into ``n`` approximately even chunks (reference
    util.py:244)."""
    d, r = divmod(len(l), n)
    return [l[i * d + min(i, r):(i + 1) * d + min(i + 1, r)]
            for i in range(n)]


def is_iterable(x):
    try:
        iter(x)
    except TypeError:
        return False
    return True


def is_version_greater_equal_than(ver, target):
    """Reference util.py:272 — target must be major.minor.patch."""
    from packaging import version
    if not isinstance(ver, str) or not isinstance(target, str):
        raise ValueError("This function only accepts string arguments.")
    if len(target.split(".")) != 3:
        raise ValueError(
            "We only accept target version values in the form of: "
            f"major.minor.patch. Received: {target}")
    return version.parse(ver) >= version.parse(target)


def check_installed_version(name, version, exception=None):
    """Reference util.py:252 compares a frontend's import-time version
    stamp against the installed package's; here the package is pure
    Python so the stamp is always this module's own version."""
    import warnings
    from ..version import __version__
    from .exceptions import (
        HorovodVersionMismatchError, get_version_mismatch_message,
    )
    if version != __version__:
        if exception is None:
            warnings.warn(get_version_mismatch_message(
                name, version, __version__))
        else:
            raise HorovodVersionMismatchError(
                name, version, __version__) from exception


def support_non_legacy_keras_optimizers(k):
    """Whether keras's non-legacy optimizer classes predate the 2.11
    split (reference util.py:292)."""
    from packaging import version
    return version.parse(
        k.__version__.replace("-tf", "+tf")) < version.parse("2.11")


# reference common/util.py also surfaces the build queries (there they
# probe the compiled extension; here they answer from the runtime)
from .basics import (  # noqa: F401,E402
    ccl_built, cuda_built, ddl_built, gloo_built, mpi_built,
    nccl_built, rocm_built,
)


def _cache(f):
    """Memoize by positional+keyword args (reference util.py:114 —
    imported by the reference's own tests)."""
    cache = {}

    def wrapper(*args, **kwargs):
        key = (args, frozenset(kwargs.items()))
        if key not in cache:
            cache[key] = f(*args, **kwargs)
        return cache[key]

    return wrapper

"""Pallas fused 1x1-conv + BatchNorm kernels (the ResNet BN roofline
fix).

ResNet-50 training on one chip is HBM-bound on BatchNorm: BN's stats
pass re-reads every post-conv activation and its normalize pass adds a
read+write (docs/benchmarks.md "Single-chip MFU analysis": deleting BN
is worth 1.26x).  ~5/6 of BN-touched activation bytes sit after 1x1
convs, and a 1x1 conv over NHWC is exactly a matmul
``(B*H*W, Cin) @ (Cin, Cout)`` — so those convs become pallas matmul
kernels that absorb the BN work into tiles already in VMEM:

* **epilogue**: per-channel ``sum`` / ``sum of squares`` of the output
  accumulate in a VMEM scratch while output tiles are written — the
  BN stats pass costs zero extra HBM traffic;
* **prologue**: the PREVIOUS BN's normalize + ReLU is folded into the
  input read as a per-channel affine ``relu(x * a + b)`` — the
  normalize pass of the upstream BN costs zero extra traffic;
* **backward**: one kernel computes ``dx``, ``dw``, ``da``, ``db`` and
  the BN-backward channel reductions in a single pass over
  ``(x, dy, y)`` with both backward matmuls on the MXU.

The reference ships hand-written CUDA where its compiler stopped
helping (``horovod/common/ops/cuda/cuda_kernels.cu:27-292``); this is
the TPU analogue.  Used by ``models/resnet.py`` ``ResNet(fused=True)``
and ``bench.py``.

Kernels run under ``interpret=True`` on CPU (tests) and compile to
Mosaic on TPU.  Gradient note: the op returns ``(y, s1, s2)`` and the
custom VJP consumes cotangents for all three, so BN's use of the batch
stats in the downstream fold differentiates exactly (the stats chain
flows through ``ds1``/``ds2``).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["conv1x1_bn", "bn_fold", "supported_m"]


def _is_tpu():
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # noqa: BLE001
        return False


# VMEM block budget for picking the M-block size: double-buffered
# in/out blocks beside the weight tile and (backward) the (K, N) f32
# grad accumulator, inside the raised 64 MB scoped-vmem limit
# (_compiler_params).
_VMEM_BUDGET = 40 * 1024 * 1024


def _pick_bm(m, k, n, backward=False):
    """Largest M-block ≤ 1024 that divides ``m``, is sublane-aligned
    for bf16 (multiple of 16), and fits the VMEM budget.  Returns None
    if no such block exists (caller falls back to the XLA path)."""
    # fixed-resident bytes: weight tile (+ grad accumulator backward)
    fixed = k * n * 2 + (k * n * 4 if backward else 0)
    # per-M-block bytes, double-buffered: fwd reads x and writes y;
    # bwd reads x, dy, y and writes dx
    per_row = (2 * (k + n)) * 2 if not backward \
        else (2 * (2 * k + 2 * n)) * 2
    budget = _VMEM_BUDGET - fixed
    best = None
    for bm in range(16, 1041, 16):
        if m % bm == 0 and bm * per_row <= budget:
            best = bm
    return best


def supported_m(m, k, n):
    """Whether the pallas path can tile an (m, k) x (k, n) problem."""
    return _pick_bm(m, k, n) is not None \
        and _pick_bm(m, k, n, backward=True) is not None


# ---------------------------------------------------------------------------
# forward

def _fwd_kernel(x_ref, a_ref, b_ref, w_ref, y_ref, s1_ref, s2_ref,
                acc1, acc2, *, fold):
    i = pl.program_id(0)
    if fold:
        xh = x_ref[:].astype(jnp.float32) * a_ref[:] + b_ref[:]
        xh = jnp.maximum(xh, 0.0).astype(jnp.bfloat16)
    else:
        xh = x_ref[:]
    y = jnp.dot(xh, w_ref[:], preferred_element_type=jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)

    @pl.when(i == 0)
    def _():
        acc1[:] = jnp.zeros_like(acc1)
        acc2[:] = jnp.zeros_like(acc2)

    acc1[:] += jnp.sum(y, axis=0, keepdims=True)
    acc2[:] += jnp.sum(y * y, axis=0, keepdims=True)

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        s1_ref[:] = acc1[:]
        s2_ref[:] = acc2[:]


def _compiler_params(interpret):
    """The stage-4 backward kernels hold a (K, N) f32 grad accumulator
    (up to 8 MB) beside the weight tile — past the compiler's default
    16 MB scoped-vmem limit, well inside the part's physical VMEM
    (measured working on the bench chip at 64 MB)."""
    if interpret:
        return {}
    return {"compiler_params": pltpu.CompilerParams(
        vmem_limit_bytes=64 * 1024 * 1024)}


def _fwd_call(x, a, b, w, fold, interpret):
    m, k = x.shape
    n = w.shape[1]
    bm = _pick_bm(m, k, n)
    y, s1, s2 = pl.pallas_call(
        functools.partial(_fwd_kernel, fold=fold),
        grid=(m // bm,),
        in_specs=[pl.BlockSpec((bm, k), lambda i: (i, 0)),
                  pl.BlockSpec((1, k), lambda i: (0, 0)),
                  pl.BlockSpec((1, k), lambda i: (0, 0)),
                  pl.BlockSpec((k, n), lambda i: (0, 0))],
        out_specs=(pl.BlockSpec((bm, n), lambda i: (i, 0)),
                   pl.BlockSpec((1, n), lambda i: (0, 0)),
                   pl.BlockSpec((1, n), lambda i: (0, 0))),
        out_shape=(jax.ShapeDtypeStruct((m, n), x.dtype),
                   jax.ShapeDtypeStruct((1, n), jnp.float32),
                   jax.ShapeDtypeStruct((1, n), jnp.float32)),
        scratch_shapes=[pltpu.VMEM((1, n), jnp.float32),
                        pltpu.VMEM((1, n), jnp.float32)],
        interpret=interpret,
        **_compiler_params(interpret),
    )(x, a, b, w)
    return y, s1[0], s2[0]


# ---------------------------------------------------------------------------
# backward: one pass over (x, dy, y) producing dx, dw, da, db

def _bwd_kernel(x_ref, a_ref, b_ref, w_ref, dy_ref, y_ref,
                ds1_ref, ds2_ref,
                dx_ref, dw_ref, da_ref, db_ref,
                dw_acc, da_acc, db_acc, *, fold):
    i = pl.program_id(0)
    # total cotangent on the raw output: direct dy plus the stats
    # chain (s1 = sum y, s2 = sum y^2)
    ytot = (dy_ref[:].astype(jnp.float32)
            + ds1_ref[:]
            + 2.0 * y_ref[:].astype(jnp.float32) * ds2_ref[:])
    ytot_bf = ytot.astype(jnp.bfloat16)

    if fold:
        pre = x_ref[:].astype(jnp.float32) * a_ref[:] + b_ref[:]
        mask = (pre > 0.0).astype(jnp.float32)
        xh = jnp.maximum(pre, 0.0).astype(jnp.bfloat16)
    else:
        xh = x_ref[:]

    # dxh = ytot @ w^T  (contract over N)
    dxh = jax.lax.dot_general(
        ytot_bf, w_ref[:], dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(i == 0)
    def _():
        dw_acc[:] = jnp.zeros_like(dw_acc)
        da_acc[:] = jnp.zeros_like(da_acc)
        db_acc[:] = jnp.zeros_like(db_acc)

    # dw += xh^T @ ytot  (contract over the M block)
    dw_acc[:] += jax.lax.dot_general(
        xh, ytot_bf, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    if fold:
        dxh_m = dxh * mask
        dx_ref[:] = (dxh_m * a_ref[:]).astype(dx_ref.dtype)
        da_acc[:] += jnp.sum(dxh_m * x_ref[:].astype(jnp.float32),
                             axis=0, keepdims=True)
        db_acc[:] += jnp.sum(dxh_m, axis=0, keepdims=True)
    else:
        dx_ref[:] = dxh.astype(dx_ref.dtype)

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        dw_ref[:] = dw_acc[:]
        da_ref[:] = da_acc[:]
        db_ref[:] = db_acc[:]


def _bwd_call(x, a, b, w, y, dy, ds1, ds2, fold, interpret):
    m, k = x.shape
    n = w.shape[1]
    bm = _pick_bm(m, k, n, backward=True)
    dx, dw, da, db = pl.pallas_call(
        functools.partial(_bwd_kernel, fold=fold),
        grid=(m // bm,),
        in_specs=[pl.BlockSpec((bm, k), lambda i: (i, 0)),
                  pl.BlockSpec((1, k), lambda i: (0, 0)),
                  pl.BlockSpec((1, k), lambda i: (0, 0)),
                  pl.BlockSpec((k, n), lambda i: (0, 0)),
                  pl.BlockSpec((bm, n), lambda i: (i, 0)),
                  pl.BlockSpec((bm, n), lambda i: (i, 0)),
                  pl.BlockSpec((1, n), lambda i: (0, 0)),
                  pl.BlockSpec((1, n), lambda i: (0, 0))],
        out_specs=(pl.BlockSpec((bm, k), lambda i: (i, 0)),
                   pl.BlockSpec((k, n), lambda i: (0, 0)),
                   pl.BlockSpec((1, k), lambda i: (0, 0)),
                   pl.BlockSpec((1, k), lambda i: (0, 0))),
        out_shape=(jax.ShapeDtypeStruct((m, k), x.dtype),
                   jax.ShapeDtypeStruct((k, n), jnp.float32),
                   jax.ShapeDtypeStruct((1, k), jnp.float32),
                   jax.ShapeDtypeStruct((1, k), jnp.float32)),
        scratch_shapes=[pltpu.VMEM((k, n), jnp.float32),
                        pltpu.VMEM((1, k), jnp.float32),
                        pltpu.VMEM((1, k), jnp.float32)],
        interpret=interpret,
        **_compiler_params(interpret),
    )(x, a, b, w, dy, y, ds1.reshape(1, n), ds2.reshape(1, n))
    return dx, dw, da[0], db[0]


# ---------------------------------------------------------------------------
# public op with custom VJP

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _conv1x1_bn(x, a, b, w, fold, interpret):
    return _fwd_call(x, a, b, w, fold, interpret)


def _vjp_fwd(x, a, b, w, fold, interpret):
    y, s1, s2 = _fwd_call(x, a, b, w, fold, interpret)
    return (y, s1, s2), (x, a, b, w, y)


def _bwd_xla(x, a, b, w, y, dy, ds1, ds2, fold):
    """XLA backward with the same math as _bwd_kernel (A/B lever and
    oracle; env HOROVOD_CONV_BN_BWD=xla selects it)."""
    ytot = (dy.astype(jnp.float32) + ds1[None, :]
            + 2.0 * y.astype(jnp.float32) * ds2[None, :])
    ytot_bf = ytot.astype(jnp.bfloat16)
    if fold:
        pre = x.astype(jnp.float32) * a + b
        mask = (pre > 0.0).astype(jnp.float32)
        xh = jnp.maximum(pre, 0.0).astype(jnp.bfloat16)
    else:
        xh = x
    dxh = jax.lax.dot_general(
        ytot_bf, w, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    dw = jax.lax.dot_general(
        xh, ytot_bf, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    if fold:
        dxh_m = dxh * mask
        dx = (dxh_m * a).astype(x.dtype)
        da = jnp.sum(dxh_m * x.astype(jnp.float32), axis=0,
                     keepdims=True)
        db = jnp.sum(dxh_m, axis=0, keepdims=True)
    else:
        dx = dxh.astype(x.dtype)
        da = db = None
    return dx, dw, da, db


def _bwd_mode():
    from ..common import env

    return env.get_str(env.HOROVOD_CONV_BN_BWD, "pallas")


def _vjp_bwd(fold, interpret, res, cots):
    x, a, b, w, y = res
    dy, ds1, ds2 = cots
    if _bwd_mode() == "xla":
        dx, dw, da, db = _bwd_xla(x, a, b, w, y, dy, ds1, ds2, fold)
    else:
        dx, dw, da, db = _bwd_call(x, a, b, w, y, dy, ds1, ds2,
                                   fold, interpret)
    if not fold or da is None:
        da = jnp.zeros_like(a)
        db = jnp.zeros_like(b)
    else:
        da = da.reshape(a.shape)
        db = db.reshape(b.shape)
    return dx, da, db, dw


_conv1x1_bn.defvjp(_vjp_fwd, _vjp_bwd)


def _reference(x, a, b, w, fold):
    """XLA fallback with identical semantics (also the test oracle)."""
    if fold:
        xh = jnp.maximum(x.astype(jnp.float32) * a + b, 0.0)
        xh = xh.astype(jnp.bfloat16)
    else:
        xh = x
    y = jnp.dot(xh, w, preferred_element_type=jnp.float32)
    s1 = jnp.sum(y, axis=0)
    s2 = jnp.sum(y * y, axis=0)
    return y.astype(x.dtype), s1, s2


def conv1x1_bn(x, w, fold=None, *, interpret=None, use_pallas=None):
    """Fused ``y = relu(x*a + b) @ w`` (or plain ``x @ w``) returning
    ``(y, colsum(y), colsum(y^2))`` in one HBM pass over ``x``.

    Args:
      x: ``(M, K)`` activations (bf16 on TPU).
      w: ``(K, N)`` weights.
      fold: optional ``(a, b)`` per-channel f32 affine of shape
        ``(1, K)`` — the upstream BN's normalize (+ReLU) folded into
        the input read.  ``None`` = consume ``x`` as-is.
    Returns:
      ``(y, s1, s2)`` with ``y`` in ``x.dtype`` and per-channel f32
      sums for the downstream BN.
    """
    m, k = x.shape
    n = w.shape[1]
    do_fold = fold is not None
    a, b = fold if do_fold else (jnp.ones((1, k), jnp.float32),
                                 jnp.zeros((1, k), jnp.float32))
    a = a.reshape(1, k).astype(jnp.float32)
    b = b.reshape(1, k).astype(jnp.float32)
    if use_pallas is None:
        use_pallas = supported_m(m, k, n)
    if not use_pallas:
        return _reference(x, a, b, w, do_fold)
    if interpret is None:
        interpret = not _is_tpu()
    return _conv1x1_bn(x, a, b, w, do_fold, interpret)


def bn_fold(s1, s2, count, scale, bias, epsilon=1e-5):
    """Batch-stat fold: per-channel ``(a, b)`` such that
    ``y*a + b == scale * (y - mean)/sqrt(var+eps) + bias``."""
    mean = s1 / count
    var = s2 / count - mean * mean
    inv = scale * jax.lax.rsqrt(var + epsilon)
    return inv, bias - mean * inv
